"""Compressed store snapshots: the cold-node bootstrap format (r17).

The catch-up plane's fast path: instead of replaying a multi-month gap
change-by-change over delta sync, a cold node fetches ONE compressed
snapshot of a serving peer's database, installs it through the
`store/restore.py` locked-swap path, and tops up with delta sync from
the snapshot's embedded watermark.  The file format is the reference's
backup plane (`corrosion backup`: VACUUM INTO + per-node-state scrub,
`klukai/src/main.rs:157-223`) wrapped in a framed, chunked, zlib
container whose header embeds:

  - the builder's **schema sha** — a canonical digest of the CRR table
    DDL.  Install refuses on mismatch: a snapshot from a node running a
    different schema generation would resurrect dropped columns or lose
    new ones mid-swap (`SnapshotSchemaMismatch`).
  - the **bookie watermark** — per-origin-actor version rangesets the
    builder had fully applied at build time.  The watermark is computed
    BEFORE `VACUUM INTO`, so the database copy is always a superset of
    it: resuming delta sync from the watermark can re-fetch a version
    the copy already holds (idempotent CRDT merge), never miss one.

Frames are the codec's u32-BE length-delimited layout, so the cached
snapshot file is served verbatim frame-by-frame over a sync bi-stream
(`agent/catchup.py`) — no re-framing on the serve path.

File layout:   HeaderFrame · ChunkFrame* · DoneFrame
  header  := u8 format(=1) · vec<u8> schema_sha · raw16 site_id ·
             f64 wall · u64 raw_bytes · u32 chunk_bytes ·
             u32 n_actors · (raw16 actor · u64 n · (u64 lo · u64 hi)*)*
  chunk   := vec<u8> zlib(db_bytes[i*chunk : (i+1)*chunk])
  done    := u64 n_chunks · u64 raw_bytes · u64 compressed_bytes

Chunks are INDEPENDENTLY compressed (no shared dict/stream state), so a
receiver can decompress as frames arrive and a torn transfer is
detectable by the done-frame totals.

Thread contract: everything here does blocking sqlite/file I/O and MUST
be called from a worker thread when an event loop is running — the
async halves live in `agent/catchup.py` and route through
`asyncio.to_thread` (corro-analyze's async-blocking rule pins this).
"""

from __future__ import annotations

import hashlib
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from corrosion_tpu.store import restore as restore_mod
from corrosion_tpu.types.codec import Reader, Writer, deframe, frame
from corrosion_tpu.types.rangeset import RangeSet

SNAPSHOT_FORMAT = 1
DEFAULT_CHUNK_BYTES = 256 * 1024

Range = Tuple[int, int]


class SnapshotError(Exception):
    pass


class SnapshotSchemaMismatch(SnapshotError):
    pass


def schema_sha(schema, exclude: Tuple[str, ...] = ()) -> bytes:
    """Canonical 32-byte digest of a Schema's CRR surface: table DDL +
    index DDL, whitespace-normalized, sorted by name.  Two nodes agree
    on the sha iff their declarative schemas are equivalent — the gate
    that makes a snapshot installable.  `exclude` names runtime-owned
    tables (the SLO canary) that exist only on nodes that opted in and
    must not fail the gate between otherwise-identical peers."""
    h = hashlib.sha256()
    for name in sorted(schema.tables):
        if name in exclude:
            continue
        t = schema.tables[name]
        h.update(b"T\x00" + _norm(t.raw_sql))
        for iname in sorted(t.indexes):
            h.update(b"I\x00" + _norm(t.indexes[iname].raw_sql))
    return h.digest()


def _norm(sql: str) -> bytes:
    return (" ".join(sql.strip().lower().split()).rstrip(";") + "\n").encode()


@dataclass
class SnapshotHeader:
    """The metadata frame a cold node reads before any chunk bytes."""

    schema_sha: bytes
    site_id: bytes  # builder's 16-byte site id (scrubbed on install)
    wall: float  # builder's wall clock at build time
    raw_bytes: int  # uncompressed database size
    chunk_bytes: int
    # per-origin-actor version coverage at build time (16-byte actor id
    # -> sorted disjoint inclusive ranges)
    watermark: Dict[bytes, List[Range]] = field(default_factory=dict)

    def watermark_total(self) -> int:
        return sum(
            e - s + 1 for ranges in self.watermark.values() for s, e in ranges
        )


@dataclass
class SnapshotDone:
    n_chunks: int
    raw_bytes: int
    compressed_bytes: int


def encode_header(h: SnapshotHeader) -> bytes:
    w = Writer()
    w.u8(SNAPSHOT_FORMAT)
    w.vec_u8(h.schema_sha)
    w.raw(h.site_id)
    w.f64(h.wall)
    w.u64(h.raw_bytes)
    w.u32(h.chunk_bytes)
    w.u32(len(h.watermark))
    for aid in sorted(h.watermark):
        ranges = h.watermark[aid]
        w.raw(aid)
        w.u64(len(ranges))
        for s, e in ranges:
            w.u64(s)
            w.u64(e)
    return w.bytes()


def decode_header(data: bytes) -> SnapshotHeader:
    r = Reader(data)
    fmt = r.u8()
    if fmt != SNAPSHOT_FORMAT:
        raise SnapshotError(f"unknown snapshot format {fmt}")
    h = SnapshotHeader(
        schema_sha=r.vec_u8(),
        site_id=r.raw(16),
        wall=r.f64(),
        raw_bytes=r.u64(),
        chunk_bytes=r.u32(),
    )
    for _ in range(r.u32()):
        aid = r.raw(16)
        h.watermark[aid] = [(r.u64(), r.u64()) for _ in range(r.u64())]
    return h


def bookie_watermark(bookie) -> Dict[bytes, List[Range]]:
    """Fully-applied version coverage per origin actor: head minus
    needed gaps minus incomplete partials.  Bookie read locks are brief
    (the sync scheduler's pattern)."""
    wm: Dict[bytes, List[Range]] = {}
    for aid, booked in bookie.items().items():
        with booked.read() as bv:
            last = bv.last()
            if last is None:
                continue
            have = RangeSet([(1, last)])
            for s, e in bv.needed:
                have.remove(s, e)
            for v, p in bv.partials.items():
                if not p.is_complete():
                    have.remove(v, v)
            ranges = list(have)
        if ranges:
            wm[aid.bytes16] = ranges
    return wm


def build_snapshot_file(
    db_path: str,
    out_path: str,
    schema,
    site_id: bytes,
    watermark: Dict[bytes, List[Range]],
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> SnapshotHeader:
    """VACUUM INTO + scrub (restore.backup) then compress into the
    framed container at `out_path` (replaced atomically).  Blocking —
    worker threads only."""
    tmp_db = out_path + ".build-db"
    tmp_out = out_path + ".build"
    for p in (tmp_db, tmp_out):
        if os.path.exists(p):
            os.unlink(p)
    restore_mod.backup(db_path, tmp_db)
    try:
        raw_bytes = os.path.getsize(tmp_db)
        header = SnapshotHeader(
            schema_sha=schema_sha(schema),
            site_id=site_id,
            wall=time.time(),
            raw_bytes=raw_bytes,
            chunk_bytes=chunk_bytes,
            watermark=watermark,
        )
        n_chunks = 0
        compressed = 0
        with open(tmp_db, "rb") as src, open(tmp_out, "wb") as out:
            out.write(frame(encode_snapshot_msg_header(header)))
            while True:
                chunk = src.read(chunk_bytes)
                if not chunk:
                    break
                z = zlib.compress(chunk, 6)
                out.write(frame(encode_snapshot_msg_chunk(z)))
                n_chunks += 1
                compressed += len(z)
            out.write(
                frame(
                    encode_snapshot_msg_done(
                        SnapshotDone(n_chunks, raw_bytes, compressed)
                    )
                )
            )
        os.replace(tmp_out, out_path)
    finally:
        for p in (tmp_db, tmp_db + "-wal", tmp_db + "-shm", tmp_out):
            if os.path.exists(p):
                os.unlink(p)
    return header


def iter_snapshot_frames(path: str, batch: int = 64) -> Iterator[List[bytes]]:
    """The cached snapshot file's frames, in batches — the serve path
    reads a batch per executor hop instead of a syscall per frame."""
    with open(path, "rb") as f:
        buf = b""
        pos = 0
        out: List[bytes] = []
        while True:
            payload, pos = deframe(buf, pos)
            if payload is None:
                if out:
                    yield out
                    out = []
                more = f.read(1 << 20)
                if not more:
                    return
                buf = buf[pos:] + more
                pos = 0
                continue
            out.append(payload)
            if len(out) >= batch:
                yield out
                out = []


# -- wire messages (served verbatim from the cache file) -------------------
#
# SnapshotMessage := u32 version(=0) · u32 tag · body
#   tag 0 Header    body = vec<u8> encoded SnapshotHeader
#   tag 1 Chunk     body = vec<u8> zlib bytes
#   tag 2 Done      body = u64 n_chunks · u64 raw · u64 compressed
#   tag 3 Rejection body = u32 reason

SNAP_HEADER, SNAP_CHUNK, SNAP_DONE, SNAP_REJECTION = range(4)

# rejection reasons
REJECT_CLUSTER = 1
REJECT_SCHEMA = 2
REJECT_BUSY = 3
REJECT_DISABLED = 4


def encode_snapshot_msg_header(h: SnapshotHeader) -> bytes:
    w = Writer()
    w.u32(0)
    w.u32(SNAP_HEADER)
    w.vec_u8(encode_header(h))
    return w.bytes()


def encode_snapshot_msg_chunk(z: bytes) -> bytes:
    w = Writer()
    w.u32(0)
    w.u32(SNAP_CHUNK)
    w.vec_u8(z)
    return w.bytes()


def encode_snapshot_msg_done(d: SnapshotDone) -> bytes:
    w = Writer()
    w.u32(0)
    w.u32(SNAP_DONE)
    w.u64(d.n_chunks)
    w.u64(d.raw_bytes)
    w.u64(d.compressed_bytes)
    return w.bytes()


def encode_snapshot_msg_rejection(reason: int) -> bytes:
    w = Writer()
    w.u32(0)
    w.u32(SNAP_REJECTION)
    w.u32(reason)
    return w.bytes()


def decode_snapshot_msg(data: bytes):
    """-> SnapshotHeader | bytes (zlib chunk) | SnapshotDone | int
    (rejection reason)."""
    r = Reader(data)
    if r.u32() != 0:
        raise ValueError("unknown SnapshotMessage version")
    tag = r.u32()
    if tag == SNAP_HEADER:
        return decode_header(r.vec_u8())
    if tag == SNAP_CHUNK:
        return r.vec_u8()
    if tag == SNAP_DONE:
        return SnapshotDone(r.u64(), r.u64(), r.u64())
    if tag == SNAP_REJECTION:
        return r.u32()
    raise ValueError(f"unknown SnapshotMessage tag {tag}")


# -- install ---------------------------------------------------------------


@dataclass
class InstallResult:
    raw_bytes: int
    watermark_versions: int
    header: SnapshotHeader


def decompress_snapshot_file(snap_path: str, out_db_path: str) -> SnapshotHeader:
    """Framed container -> raw sqlite db file; verifies chunk totals
    against the done frame.  Blocking — worker threads only."""
    header: Optional[SnapshotHeader] = None
    done: Optional[SnapshotDone] = None
    n = 0
    written = 0
    with open(out_db_path, "wb") as out:
        for batch in iter_snapshot_frames(snap_path):
            for payload in batch:
                msg = decode_snapshot_msg(payload)
                if isinstance(msg, SnapshotHeader):
                    header = msg
                elif isinstance(msg, bytes):
                    raw = zlib.decompress(msg)
                    out.write(raw)
                    written += len(raw)
                    n += 1
                elif isinstance(msg, SnapshotDone):
                    done = msg
                elif isinstance(msg, int):
                    raise SnapshotError(f"snapshot file holds rejection {msg}")
    if header is None or done is None:
        raise SnapshotError("truncated snapshot: missing header/done frame")
    if n != done.n_chunks or written != done.raw_bytes:
        raise SnapshotError(
            f"torn snapshot: {n}/{done.n_chunks} chunks, "
            f"{written}/{done.raw_bytes} bytes"
        )
    return header


def install_raw_db(
    tmp_db_path: str,
    db_path: str,
    self_site_id: Optional[bytes],
    builder_site_id: bytes,
) -> None:
    """Locked swap of a decompressed snapshot db over `db_path`,
    re-pinning the installing node's own site id (a bootstrap must keep
    the cold node's identity, not adopt the builder's).  Blocking —
    worker threads only; live stores must quiesce connections first
    (CrdtStore.swapped_database)."""
    import uuid

    restore_mod.restore(tmp_db_path, db_path)
    if self_site_id is not None and self_site_id != builder_site_id:
        restore_mod.set_self_site_id(
            db_path, uuid.UUID(bytes=self_site_id).hex
        )


def install_snapshot_file(
    snap_path: str,
    db_path: str,
    expect_schema_sha: Optional[bytes] = None,
    self_site_id: Optional[bytes] = None,
) -> InstallResult:
    """Decompress + verify + locked swap over `db_path` — the CLI /
    cold-boot (container-file) install path.  Blocking — worker threads
    only."""
    tmp_db = db_path + ".snap-install"
    if os.path.exists(tmp_db):
        os.unlink(tmp_db)
    try:
        header = decompress_snapshot_file(snap_path, tmp_db)
        if (
            expect_schema_sha is not None
            and header.schema_sha != expect_schema_sha
        ):
            raise SnapshotSchemaMismatch(
                f"snapshot schema sha {header.schema_sha.hex()[:12]} != "
                f"local {expect_schema_sha.hex()[:12]}"
            )
        install_raw_db(tmp_db, db_path, self_site_id, header.site_id)
        return InstallResult(
            raw_bytes=header.raw_bytes,
            watermark_versions=header.watermark_total(),
            header=header,
        )
    finally:
        for p in (tmp_db, tmp_db + "-wal", tmp_db + "-shm"):
            if os.path.exists(p):
                os.unlink(p)


# -- serve-side cache ------------------------------------------------------


class SnapshotCache:
    """The serving agent's cached, staleness-bounded snapshot.

    One compressed container file beside the database
    (`<db>.snapshot`); `ensure_fresh` rebuilds it when older than
    `max_age_secs` (or absent) and is idempotent within the window, so
    a burst of cold nodes amortizes ONE VACUUM+compress.  All methods
    blocking — the async serve path wraps them in `asyncio.to_thread`
    under a per-agent build lock."""

    def __init__(self, db_path: str, cache_path: Optional[str] = None):
        self.db_path = db_path
        self.path = cache_path or (db_path + ".snapshot")
        self.header: Optional[SnapshotHeader] = None
        self.built_mono: Optional[float] = None
        self.compressed_bytes: int = 0

    def age(self) -> Optional[float]:
        if self.built_mono is None:
            return None
        return time.monotonic() - self.built_mono

    def ensure_fresh(
        self,
        schema,
        site_id: bytes,
        bookie,
        max_age_secs: float,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    ) -> SnapshotHeader:
        from corrosion_tpu.runtime.metrics import METRICS

        age = self.age()
        if (
            self.header is not None
            and age is not None
            and age <= max_age_secs
            and os.path.exists(self.path)
        ):
            return self.header
        t0 = time.monotonic()
        # watermark BEFORE the VACUUM: the copy is then a superset of
        # the coverage the header claims (see module docstring)
        wm = bookie_watermark(bookie)
        header = build_snapshot_file(
            self.db_path, self.path, schema, site_id, wm, chunk_bytes
        )
        self.header = header
        self.built_mono = time.monotonic()
        self.compressed_bytes = os.path.getsize(self.path)
        METRICS.counter("corro.snapshot.built.total").inc()
        METRICS.histogram("corro.snapshot.build.seconds").observe(
            self.built_mono - t0
        )
        METRICS.gauge("corro.snapshot.bytes").set(self.compressed_bytes)
        return header

    def drop(self) -> None:
        self.header = None
        self.built_mono = None
        try:
            os.unlink(self.path)
        except OSError:
            pass

"""Per-actor version bookkeeping: max / needed gaps / partial versions.

Behavioral counterpart of `klukai-types/src/agent.rs:1068-1609`
(PartialVersion, KnownDbVersion, VersionsSnapshot, BookedVersions, Booked,
Bookie). A node tracks, for every origin actor:

  - `max`:     highest db_version ever observed from that actor
  - `needed`:  RangeSet of version gaps it still needs (anti-entropy pulls
               these during sync)
  - `partials`: versions received incompletely (seq sub-ranges buffered,
               waiting for the seq range to close before applying)

Mutations go through a snapshot/commit protocol: take `snapshot()`, apply
version observations (which both mutates the snapshot and writes the gap
delta through a `GapStore`), then `commit_snapshot()` under the write lock —
mirroring the reference's transactional `insert_db` + `commit_snapshot`
(`agent.rs:1119-1179,1408-1413`).

Note: the reference's `PartialVersion::full_range` starts at seq 1
(`agent.rs:1083`) even though change seqs start at 0 — an off-by-one its
sync path compensates for. We use the correct 0..=last_seq range.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Protocol, Tuple

from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.rangeset import Range, RangeSet


@dataclass
class PartialVersion:
    """Seq coverage of a version received in pieces (agent.rs:1069-1086)."""

    seqs: RangeSet
    last_seq: int
    ts: Timestamp

    def full_range(self) -> Range:
        return (0, self.last_seq)

    def is_complete(self) -> bool:
        return next(self.seqs.gaps(0, self.last_seq), None) is None

    def gaps(self) -> Iterable[Range]:
        return self.seqs.gaps(0, self.last_seq)


class GapStore(Protocol):
    """Persistence hooks for the needed-gap delta (``__corro_bookkeeping_gaps``)."""

    def delete_gap(self, actor_id: ActorId, start: int, end: int) -> None: ...

    def insert_gap(self, actor_id: ActorId, start: int, end: int) -> None: ...


class _NullGapStore:
    def delete_gap(self, actor_id: ActorId, start: int, end: int) -> None:
        pass

    def insert_gap(self, actor_id: ActorId, start: int, end: int) -> None:
        pass


NULL_GAP_STORE = _NullGapStore()


class VersionsSnapshot:
    """Mutable working copy; write gap deltas through a GapStore, then
    commit back into the owning BookedVersions."""

    def __init__(
        self,
        actor_id: ActorId,
        needed: RangeSet,
        partials: Dict[int, PartialVersion],
        max_version: Optional[int],
    ):
        self.actor_id = actor_id
        self.needed = needed
        self.partials = partials
        self.max = max_version

    def insert_db(self, store: GapStore, versions: RangeSet) -> None:
        """Record observed (applied/buffered/cleared) versions.

        Equivalent of `agent.rs:1119-1246`: versions between the previous
        max and a new range's start become needed gaps; observed versions
        are removed from the gaps; the delta is persisted via `store`.
        Processing sorted ranges with an incrementally-updated max yields
        the same result as the reference's original-max algebra because
        RangeSet iteration is sorted ascending.
        """
        before = self.needed.copy()
        for start, end in versions:
            gap_start = (self.max or 0) + 1
            if gap_start < start:
                self.needed.insert(gap_start, start - 1)
            self.needed.remove(start, end)
            if self.max is None or end > self.max:
                self.max = end
        # persist the row-level delta: gap rows are stored as (start, end)
        # pairs, so diff the structural rows (reference deletes overlapping
        # stored ranges and re-inserts the collapsed ones, agent.rs:1131-1177)
        rows_before = set(before)
        rows_after = set(self.needed)
        for s, e in rows_before - rows_after:
            store.delete_gap(self.actor_id, s, e)
        for s, e in rows_after - rows_before:
            store.insert_gap(self.actor_id, s, e)
        # gap deletion must be effective: no observed version may remain
        # needed after the algebra runs (ref assert_always, agent.rs:1144).
        # Condition guarded by enabled(): off mode must not pay the scan
        from corrosion_tpu.runtime import invariants

        if invariants.enabled():
            invariants.assert_always(
                not any(
                    next(self.needed.overlapping(s, e), None) is not None
                    for s, e in versions
                ),
                "gaps.observed_versions_not_needed",
                {"actor": str(self.actor_id)},
            )

    def insert_gaps(self, versions: Iterable[Range]) -> None:
        for s, e in versions:
            self.needed.insert(s, e)


@dataclass
class BookedVersions:
    """All version knowledge about one origin actor (agent.rs:1272-1455)."""

    actor_id: ActorId
    partials: Dict[int, PartialVersion] = field(default_factory=dict)
    needed: RangeSet = field(default_factory=RangeSet)
    max: Optional[int] = None

    def contains_version(self, version: int) -> bool:
        # known if it's ≤ max and not a needed gap (agent.rs:1365-1375)
        return not self.needed.contains(version) and (self.max or 0) >= version

    def get_partial(self, version: int) -> Optional[PartialVersion]:
        return self.partials.get(version)

    def contains(self, version: int, seqs: Optional[Range] = None) -> bool:
        if not self.contains_version(version):
            return False
        if seqs is None:
            return True
        partial = self.partials.get(version)
        if partial is None:
            return True  # fully applied or cleared
        return partial.seqs.contains_range(seqs[0], seqs[1])

    def contains_all(self, versions: Range, seqs: Optional[Range] = None) -> bool:
        return all(self.contains(v, seqs) for v in range(versions[0], versions[1] + 1))

    def last(self) -> Optional[int]:
        return self.max

    def snapshot(self) -> VersionsSnapshot:
        return VersionsSnapshot(
            self.actor_id,
            self.needed.copy(),
            dict(self.partials),
            self.max,
        )

    def commit_snapshot(self, snap: VersionsSnapshot) -> None:
        self.needed = snap.needed
        self.partials = snap.partials
        self.max = snap.max

    def insert_partial(self, version: int, partial: PartialVersion) -> PartialVersion:
        """Merge seq coverage for a buffered version (agent.rs:1424-1447)."""
        existing = self.partials.get(version)
        if existing is None:
            self.partials[version] = partial
            if self.max is None or version > self.max:
                self.max = version
            return partial
        existing.seqs = existing.seqs.union(partial.seqs)
        existing.last_seq = max(existing.last_seq, partial.last_seq)
        return existing


class Booked:
    """A BookedVersions behind a reader/writer lock.

    The reference wraps each actor's bookkeeping in an instrumented tokio
    RwLock (`CountedTokioRwLock`, agent.rs:707-1066) with a watchdog for
    long holds. Host-side we guard with a reentrant mutex; asyncio tasks in
    this runtime never block across awaits while holding it.
    """

    def __init__(self, bv: BookedVersions, registry=None):
        self._bv = bv
        self._lock = threading.RLock()
        self._registry = registry
        self._label = f"booked:{bv.actor_id}"

    def read(self) -> "_BookedGuard":
        return _BookedGuard(
            self._bv, self._lock, self._registry, self._label, "read"
        )

    def write(self, label: str = "") -> "_BookedGuard":
        full = f"{self._label}:{label}" if label else self._label
        return _BookedGuard(self._bv, self._lock, self._registry, full, "write")


class _BookedGuard:
    __slots__ = ("bv", "_lock", "_registry", "_label", "_kind", "_meta")

    def __init__(self, bv: BookedVersions, lock, registry, label, kind):
        self.bv = bv
        self._lock = lock
        self._registry = registry
        self._label = label
        self._kind = kind
        self._meta = None

    def __enter__(self) -> BookedVersions:
        if self._registry is not None:
            self._meta = self._registry.register(self._label, self._kind)
        self._lock.acquire()
        if self._registry is not None:
            self._registry.acquired(self._meta)
        return self.bv

    def __exit__(self, *exc) -> bool:
        self._lock.release()
        if self._registry is not None and self._meta is not None:
            self._registry.release(self._meta)
            self._meta = None
        return False


class Bookie:
    """actor_id → Booked map (agent.rs:1558-1609)."""

    def __init__(self, registry=None):
        self._map: Dict[ActorId, Booked] = {}
        self._lock = threading.Lock()
        # LockRegistry (runtime/locks.py) so admin `locks` sees holds
        self._registry = registry

    def ensure(self, actor_id: ActorId) -> Booked:
        with self._lock:
            b = self._map.get(actor_id)
            if b is None:
                b = Booked(BookedVersions(actor_id), self._registry)
                self._map[actor_id] = b
            return b

    def get(self, actor_id: ActorId) -> Optional[Booked]:
        with self._lock:
            return self._map.get(actor_id)

    def insert(self, actor_id: ActorId, bv: BookedVersions) -> Booked:
        """Install pre-loaded bookkeeping (startup warm-up from durable
        state, run_root.rs:136-197)."""
        with self._lock:
            b = Booked(bv, self._registry)
            self._map[actor_id] = b
            return b

    def replace_all(self, mapping: Dict[ActorId, BookedVersions]) -> None:
        """Atomically replace the whole actor map with exactly `mapping`
        (snapshot install, agent/catchup.py).  Actors absent from
        `mapping` are DROPPED: after a database swap the old map
        describes state that no longer exists, and a stale survivor
        would claim versions the swap discarded, hiding them from the
        delta top-up forever."""
        with self._lock:
            self._map = {
                aid: Booked(bv, self._registry)
                for aid, bv in mapping.items()
            }

    def items(self) -> Dict[ActorId, Booked]:
        with self._lock:
            return dict(self._map)

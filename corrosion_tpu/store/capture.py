"""Direct change capture: statement-shape metadata for the write API (r15).

The r14 profile measured ~60% of a 10-row local commit in the
AFTER-trigger → `__crdt_pending` INSERT → SELECT-back → DELETE
round-trip plus per-change encode.  This module lets
`WriteTx.execute`/`executemany` capture the cells a statement writes IN
MEMORY instead: `parse_shape` classifies the statement text ONCE
(cached per store) into a `Shape` — table, kind, parameter slots,
per-column affinity converters — and the per-execution planner resolves
the actual written cells from the bound parameters, falling back to the
unchanged trigger path whenever anything is outside the
provably-identical set.

Equivalence contract (pinned by tests/test_capture.py randomized
direct-vs-trigger runs): for every captured statement the emitted
(tbl, pk, cid, val) stream is byte- and order-identical to what the
AFTER triggers would have logged to `__crdt_pending`, including

  - sqlite column-affinity conversion of bound parameters (NEW."c" is
    the STORED value, not the bound one) — `_col_convert`;
  - the pending table's own `val ANY` column affinity (NUMERIC on this
    sqlite: a TEXT-column '5' arrives in the trigger log as INTEGER 5,
    a REAL-column 3.0 as INTEGER 3) — `pending_affinity`;
  - `INSERT OR REPLACE` firing ONLY the insert trigger under the
    store's `recursive_triggers = OFF` (no delete marker for the
    displaced row), with NULL values on NOT NULL-with-DEFAULT columns
    replaced by the column default (sqlite's REPLACE semantics);
  - `UPDATE` logging only columns whose NEW value IS NOT the OLD value,
    in table column order (pre-images read with one SELECT per
    statement instead of per-cell trigger rows);
  - `INSERT OR IGNORE` / `ON CONFLICT DO NOTHING` skipping conflicting
    rows silently (existence read from the same pre-image pass).

Anything not provably identical — expressions in SET/VALUES, non-pk
WHERE clauses, numeric-looking text bound into any column (the NUMERIC
conversion grammar is sqlite's, not ours), float→TEXT formatting,
`OR FAIL`/`OR ROLLBACK`, RETURNING, `?N` params — makes `parse_shape`
(statement level) or the planner (value level) decline, and the
statement runs through the triggers exactly as before this round.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.types.change import SENTINEL

# The statement-kind ↔ trigger-suffix contract: one entry per generated
# AFTER trigger in `CrdtStore._create_triggers` (store/crdt.py).  The
# `capture-parity` static rule (analysis/capture_parity.py) pins this
# mapping — and the `_cells_*` column sources below — against the
# trigger DDL so the two capture paths cannot drift silently.
CAPTURED_KINDS = {"insert": "ins", "update": "upd", "delete": "del"}

# the del/upd triggers' row-delete marker (`'{SENTINEL}X'` in the DDL)
DELETE_MARKER = SENTINEL + "X"


class _Unsafe:
    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return "<unsafe>"


# sentinel: "this value/statement cannot be captured provably-identically"
UNSAFE = _Unsafe()


# -- sqlite affinity -------------------------------------------------------


def column_affinity(decl: Optional[str]) -> str:
    """Sqlite's declared-type → affinity rules (datatype3.html §3.1)."""
    d = (decl or "").upper()
    if "INT" in d:
        return "INTEGER"
    if "CHAR" in d or "CLOB" in d or "TEXT" in d:
        return "TEXT"
    if "BLOB" in d or not d:
        return "BLOB"
    if "REAL" in d or "FLOA" in d or "DOUB" in d:
        return "REAL"
    return "NUMERIC"


# any text that even STARTS numeric-looking is handed back to the
# triggers: sqlite's text→number conversion grammar (well-formedness,
# whitespace trim, int/real split) is not re-implemented here
_NUMERIC_TEXT = re.compile(r"^[\s]*[+-]?(\d|\.\d)")


def _col_convert(aff: str, v):
    """NEW."c" for a bound parameter: sqlite's column-affinity storage
    conversion, restricted to cases where the converted value is
    provably what sqlite stores (UNSAFE otherwise)."""
    if v is None:
        return None
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v)  # blobs pass through every affinity unchanged
    if aff == "BLOB":
        return v
    if aff == "TEXT":
        if isinstance(v, str):
            return v
        if isinstance(v, int):
            return str(v)
        return UNSAFE  # float→text rendering drift risk
    # numeric affinities (INTEGER / REAL / NUMERIC)
    if isinstance(v, str):
        return v if not _NUMERIC_TEXT.match(v) else UNSAFE
    if isinstance(v, float):
        if v != v:
            return UNSAFE  # NaN binds as NULL
        if aff == "REAL":
            return v
        return int(v) if v.is_integer() and abs(v) < 2**63 else v
    if isinstance(v, int):
        return float(v) if aff == "REAL" else v
    return UNSAFE


def pending_affinity(v):
    """What `__crdt_pending.val ANY` (NUMERIC affinity on this sqlite)
    stores for a user-table NEW value — the munging every
    trigger-logged cell went through, reproduced for in-memory capture
    (e.g. REAL 2.0 → INTEGER 2; numeric-looking text → UNSAFE)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, float):
        return int(v) if v.is_integer() and abs(v) < 2**63 else v
    if isinstance(v, str) and _NUMERIC_TEXT.match(v):
        return UNSAFE
    return v


def values_distinct(a, b) -> bool:
    """`NEW."c" IS NOT OLD."c"` over user-table stored values: NULL-safe,
    int/real comparable across storage classes, text/blob equal only
    within their own class."""
    if a is None or b is None:
        return (a is None) != (b is None)
    if isinstance(a, bool):
        a = int(a)
    if isinstance(b, bool):
        b = int(b)
    na = isinstance(a, (int, float))
    nb = isinstance(b, (int, float))
    if na or nb:
        return a != b if (na and nb) else True
    if isinstance(a, bytes) != isinstance(b, bytes):
        return True
    return a != b


# -- table metadata --------------------------------------------------------


def _const_default(text: Optional[str], aff: str):
    """A column DEFAULT as a stored-domain constant (UNSAFE when the
    default is an expression we will not evaluate, e.g. CURRENT_TIME)."""
    if text is None:
        return None
    s = text.strip()
    while s.startswith("(") and s.endswith(")"):
        s = s[1:-1].strip()
    u = s.upper()
    if u == "NULL":
        return None
    if u == "TRUE":
        return _col_convert(aff, 1)
    if u == "FALSE":
        return _col_convert(aff, 0)
    if len(s) >= 2 and s[0] == "'" and s[-1] == "'":
        return _col_convert(aff, s[1:-1].replace("''", "'"))
    body = s[1:] if s[:1] in "+-" else s
    try:
        v = int(body)
    except ValueError:
        try:
            v = float(body)
        except ValueError:
            return UNSAFE
    return _col_convert(aff, -v if s[:1] == "-" else v)


@dataclass(frozen=True)
class TableMeta:
    """Per-table capture metadata derived from the Schema Table — the
    direct-capture mirror of what `_create_triggers` bakes into DDL."""

    name: str
    pk_cols: Tuple[str, ...]
    non_pk_cols: Tuple[str, ...]
    affinity: Dict[str, str]
    defaults: Dict[str, object]  # non-pk col → stored-domain constant
    notnull: frozenset  # non-pk NOT NULL columns
    ipk_alias: bool  # single INTEGER pk aliasing rowid
    plain_insert_ok: bool  # no CHECK constraints (OR IGNORE gate)


def table_meta(t) -> TableMeta:
    raw = (t.raw_sql or "").upper()
    pk_cols = tuple(t.pk_cols)
    aff = {c.name: column_affinity(c.sql_type) for c in t.columns.values()}
    defaults: Dict[str, object] = {}
    notnull = set()
    for c in t.columns.values():
        if c.primary_key:
            continue
        defaults[c.name] = _const_default(c.default, aff[c.name])
        if not c.nullable:
            notnull.add(c.name)
    ipk = (
        len(pk_cols) == 1
        and t.columns[pk_cols[0]].sql_type.strip().upper() == "INTEGER"
        and "WITHOUT" not in raw
    )
    return TableMeta(
        name=t.name,
        pk_cols=pk_cols,
        non_pk_cols=tuple(t.non_pk_cols),
        affinity=aff,
        defaults=defaults,
        notnull=frozenset(notnull),
        ipk_alias=ipk,
        plain_insert_ok="CHECK" not in raw,
    )


# -- pending-stream cell builders (the trigger bodies, in memory) ----------


def _cells_insert(meta: TableMeta, vals: Dict[str, object]) -> list:
    """The ins-trigger stream for one inserted row: the row sentinel,
    then every non-pk column's NEW value in table column order (columns
    absent from the statement take their DEFAULT)."""
    cells = [(SENTINEL, None)]
    for c in meta.non_pk_cols:
        cells.append((c, vals[c] if c in vals else meta.defaults[c]))
    return cells


def _cells_update(
    meta: TableMeta, old: Dict[str, object], new: Dict[str, object]
) -> list:
    """The upd-trigger stream for an unchanged-pk UPDATE of one row:
    only columns whose NEW value IS NOT the OLD value, in table column
    order (a no-op assignment logs nothing, exactly like the trigger's
    `WHERE NEW."c" IS NOT OLD."c"`)."""
    return [
        (c, new[c])
        for c in meta.non_pk_cols
        if c in new and values_distinct(new[c], old.get(c))
    ]


def _cells_delete(meta: TableMeta) -> list:
    """The del-trigger stream: one row-delete marker."""
    return [(DELETE_MARKER, None)]


# -- SQL tokenizer ---------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s+|--[^\n]*|/\*.*?\*/"""
    r"""|'(?:[^']|'')*'"""
    r'''|"(?:[^"]|"")*"|`[^`]*`|\[[^\]]*\]'''
    r"""|[A-Za-z_][A-Za-z0-9_]*"""
    r"""|(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"""
    r"""|\?\d*|[:@$][A-Za-z_][A-Za-z0-9_]*"""
    r"""|!=|==|<>|<=|>=|\|\||[(),=;.*<>+\-/%]""",
    re.S,
)


def _tokenize(sql: str) -> Optional[List[str]]:
    out: List[str] = []
    pos = 0
    for m in _TOKEN_RE.finditer(sql):
        if m.start() != pos:
            return None  # unrecognized character → not ours to parse
        pos = m.end()
        tok = m.group(0)
        if tok[0].isspace() or tok.startswith("--") or tok.startswith("/*"):
            continue
        out.append(tok)
    return out if pos == len(sql) else None


def _ident(tok: str) -> Optional[str]:
    """Unquote an identifier token; None if the token is not one."""
    if not tok:
        return None
    c0 = tok[0]
    if c0 == '"':
        return tok[1:-1].replace('""', '"')
    if c0 == "`" or c0 == "[":
        return tok[1:-1]
    if c0.isalpha() or c0 == "_":
        return tok
    return None


class _Cur:
    __slots__ = ("t", "i")

    def __init__(self, toks: List[str]):
        self.t = toks
        self.i = 0

    def peek(self, k: int = 0) -> str:
        j = self.i + k
        return self.t[j] if j < len(self.t) else ""

    def peek_u(self, k: int = 0) -> str:
        return self.peek(k).upper()

    def next(self) -> str:
        tok = self.peek()
        self.i += 1
        return tok

    def eat(self, *kws: str) -> bool:
        """Consume the exact keyword/punct sequence, or nothing."""
        save = self.i
        for kw in kws:
            if self.peek_u() != kw:
                self.i = save
                return False
            self.i += 1
        return True

    def done(self) -> bool:
        while self.peek() == ";":
            self.i += 1
        return self.i >= len(self.t)


# -- slots ------------------------------------------------------------------
#
# A slot is how one value arrives at execution time:
#   ("l", value)  literal baked into the statement text
#   ("p", index)  positional `?` parameter (0-based)
#   ("n", name)   named `:x` / `@x` / `$x` parameter
#   ("x", col)    upsert `excluded."col"` reference


def _num(tok: str):
    try:
        return int(tok)
    except ValueError:
        return float(tok)


def _parse_slot(toks: _Cur, state: dict):
    t = toks.peek()
    if t == "?":
        toks.next()
        i = state["pos"]
        state["pos"] = i + 1
        state["uses_pos"] = True
        return ("p", i)
    if t[:1] == "?":
        return UNSAFE  # ?NNN numbered params — not supported
    if t[:1] in ":@$":
        toks.next()
        state["uses_named"] = True
        return ("n", t[1:])
    if t in ("+", "-"):
        nxt = toks.peek(1)
        if nxt and (nxt[0].isdigit() or nxt[0] == "."):
            toks.next()
            v = _num(toks.next())
            return ("l", -v if t == "-" else v)
        return UNSAFE
    if t and (t[0].isdigit() or (t[0] == "." and len(t) > 1)):
        toks.next()
        return ("l", _num(t))
    if t[:1] == "'":
        toks.next()
        return ("l", t[1:-1].replace("''", "'"))
    u = t.upper()
    if u == "NULL":
        toks.next()
        return ("l", None)
    if u == "TRUE":
        toks.next()
        return ("l", 1)
    if u == "FALSE":
        toks.next()
        return ("l", 0)
    return UNSAFE


def resolve_slot(slot, params):
    """The bound value for a slot (UNSAFE when params don't carry it)."""
    k, v = slot
    if k == "l":
        return v
    try:
        return params[v]
    except (KeyError, IndexError, TypeError):
        return UNSAFE


# -- shapes -----------------------------------------------------------------


@dataclass(frozen=True)
class Shape:
    """One recognized statement's capture metadata (cached per store by
    statement text; schema changes clear the cache)."""

    kind: str  # a CAPTURED_KINDS key
    meta: TableMeta
    uses_pos: bool
    uses_named: bool
    n_pos: int
    # insert
    columns: Tuple[str, ...] = ()
    value_rows: Tuple[Tuple[object, ...], ...] = ()
    conflict: str = ""  # "" | "replace" | "ignore" | "nothing" | "upsert"
    upsert_set: Tuple[Tuple[str, object], ...] = ()
    # update / delete
    set_slots: Tuple[Tuple[str, object], ...] = ()
    pk_slots: Tuple[object, ...] = ()  # aligned to meta.pk_cols
    # r23 statement-profiler key ("kind:table"), precomputed once per
    # cached shape so the per-statement timed_query tap never builds a
    # string on the hot write path
    stmt_key: str = ""


def parse_shape(sql: str, schema) -> Optional[Shape]:
    """Classify a statement for direct capture; None → trigger path."""
    toks_l = _tokenize(sql)
    if toks_l is None:
        return None
    toks = _Cur(toks_l)
    state = {"pos": 0, "uses_pos": False, "uses_named": False}
    u0 = toks.peek_u()
    if u0 in ("INSERT", "REPLACE"):
        shape = _parse_insert(toks, schema, state)
    elif u0 == "UPDATE":
        shape = _parse_update(toks, schema, state)
    elif u0 == "DELETE":
        shape = _parse_delete(toks, schema, state)
    else:
        return None
    if shape is None or not toks.done():
        return None
    if shape.uses_pos and shape.uses_named:
        return None  # mixed param styles — let sqlite sort it out
    return replace(shape, stmt_key=f"{shape.kind}:{shape.meta.name}")


def _schema_table(toks: _Cur, schema):
    name = _ident(toks.peek())
    if name is None:
        return None
    t = schema.tables.get(name)
    if t is None:
        return None
    toks.next()
    return t


def _parse_insert(toks: _Cur, schema, state) -> Optional[Shape]:
    conflict = ""
    if toks.eat("REPLACE"):
        conflict = "replace"
    else:
        toks.next()  # INSERT
        if toks.eat("OR"):
            res = toks.peek_u()
            if res == "REPLACE":
                conflict = "replace"
            elif res == "IGNORE":
                conflict = "ignore"
            elif res == "ABORT":
                conflict = ""
            else:
                return None  # OR FAIL / OR ROLLBACK: partial-effect modes
            toks.next()
    if not toks.eat("INTO"):
        return None
    t = _schema_table(toks, schema)
    if t is None or not toks.eat("("):
        return None
    meta = table_meta(t)
    cols: List[str] = []
    while True:
        c = _ident(toks.peek())
        if c is None or c not in t.columns or c in cols:
            return None
        cols.append(c)
        toks.next()
        if toks.eat(")"):
            break
        if not toks.eat(","):
            return None
    if not toks.eat("VALUES"):
        return None
    rows: List[Tuple[object, ...]] = []
    while True:
        if not toks.eat("("):
            return None
        row: List[object] = []
        while True:
            s = _parse_slot(toks, state)
            if s is UNSAFE:
                return None
            row.append(s)
            if toks.eat(")"):
                break
            if not toks.eat(","):
                return None
        if len(row) != len(cols):
            return None
        rows.append(tuple(row))
        if not toks.eat(","):
            break
    upsert_set: List[Tuple[str, object]] = []
    if toks.eat("ON", "CONFLICT"):
        if conflict:
            return None  # OR REPLACE/IGNORE + ON CONFLICT: let sqlite rule
        if toks.eat("("):
            target: List[str] = []
            while True:
                c = _ident(toks.peek())
                if c is None:
                    return None
                target.append(c)
                toks.next()
                if toks.eat(")"):
                    break
                if not toks.eat(","):
                    return None
            if set(target) != set(meta.pk_cols):
                return None  # only the pk can conflict in a CRR schema
        if not toks.eat("DO"):
            return None
        if toks.eat("NOTHING"):
            conflict = "nothing"
        elif toks.eat("UPDATE", "SET"):
            conflict = "upsert"
            seen: set = set()
            while True:
                c = _ident(toks.peek())
                if c is None or c not in t.columns or c in meta.pk_cols:
                    return None
                if c in seen:
                    return None
                seen.add(c)
                toks.next()
                if not toks.eat("="):
                    return None
                if toks.peek_u() == "EXCLUDED" and toks.peek(1) == ".":
                    toks.next()
                    toks.next()
                    ec = _ident(toks.peek())
                    if ec is None or ec != c:
                        # excluded.<other col>: legal SQL, but keep the
                        # capture matrix simple — trigger path
                        return None
                    toks.next()
                    upsert_set.append((c, ("x", ec)))
                else:
                    s = _parse_slot(toks, state)
                    if s is UNSAFE:
                        return None
                    upsert_set.append((c, s))
                if not toks.eat(","):
                    break
            if toks.peek_u() == "WHERE":
                return None  # conditional DO UPDATE: trigger path
        else:
            return None
    # every pk col must be listed, or be the rowid alias (NULL-assigned)
    missing_pk = [c for c in meta.pk_cols if c not in cols]
    if missing_pk and not (meta.ipk_alias and missing_pk == list(meta.pk_cols)):
        return None
    if conflict == "ignore" and not meta.plain_insert_ok:
        return None  # OR IGNORE swallows CHECK violations we can't see
    # unlisted non-pk columns take their DEFAULT on the insert branch:
    # that constant (and its pending form) must be representable
    for c in meta.non_pk_cols:
        if c not in cols:
            d = meta.defaults[c]
            if d is UNSAFE or pending_affinity(d) is UNSAFE:
                return None
    return Shape(
        kind="insert",
        meta=meta,
        uses_pos=state["uses_pos"],
        uses_named=state["uses_named"],
        n_pos=state["pos"],
        columns=tuple(cols),
        value_rows=tuple(rows),
        conflict=conflict,
        upsert_set=tuple(upsert_set),
    )


def _parse_pk_where(toks: _Cur, meta: TableMeta, state):
    """`WHERE pk1 = ? AND pk2 = ?` covering exactly the pk — the ≤1-row
    guarantee that keeps capture order independent of scan order."""
    if not toks.eat("WHERE"):
        return None
    by_col: Dict[str, object] = {}
    while True:
        c = _ident(toks.peek())
        if c is None or c not in meta.pk_cols or c in by_col:
            return None
        toks.next()
        if not (toks.eat("=") or toks.eat("IS") or toks.eat("==")):
            return None
        s = _parse_slot(toks, state)
        if s is UNSAFE:
            return None
        by_col[c] = s
        if not toks.eat("AND"):
            break
    if set(by_col) != set(meta.pk_cols):
        return None
    return tuple(by_col[c] for c in meta.pk_cols)


def _parse_update(toks: _Cur, schema, state) -> Optional[Shape]:
    toks.next()  # UPDATE
    if toks.peek_u() == "OR":
        return None  # UPDATE OR ...: conflict-resolution modes
    t = _schema_table(toks, schema)
    if t is None or not toks.eat("SET"):
        return None
    meta = table_meta(t)
    sets: List[Tuple[str, object]] = []
    seen: set = set()
    while True:
        c = _ident(toks.peek())
        if c is None or c not in t.columns or c in meta.pk_cols or c in seen:
            return None  # pk reassignment = delete+create: trigger path
        seen.add(c)
        toks.next()
        if not toks.eat("="):
            return None
        s = _parse_slot(toks, state)
        if s is UNSAFE:
            return None
        sets.append((c, s))
        if not toks.eat(","):
            break
    pk_slots = _parse_pk_where(toks, meta, state)
    if pk_slots is None:
        return None
    return Shape(
        kind="update",
        meta=meta,
        uses_pos=state["uses_pos"],
        uses_named=state["uses_named"],
        n_pos=state["pos"],
        set_slots=tuple(sets),
        pk_slots=pk_slots,
    )


def _parse_delete(toks: _Cur, schema, state) -> Optional[Shape]:
    toks.next()  # DELETE
    if not toks.eat("FROM"):
        return None
    t = _schema_table(toks, schema)
    if t is None:
        return None
    meta = table_meta(t)
    pk_slots = _parse_pk_where(toks, meta, state)
    if pk_slots is None:
        return None
    return Shape(
        kind="delete",
        meta=meta,
        uses_pos=state["uses_pos"],
        uses_named=state["uses_named"],
        n_pos=state["pos"],
        pk_slots=pk_slots,
    )


# -- execution-time planning -----------------------------------------------
#
# Plans are plain tuples, fully pre-validated: by the time a statement
# executes, every captured value already exists in its FINAL pending
# form, so the post-execution emit is a bare list extend.
#
#   insert row plan: (pk_tuple|None, cells, skip, assigns, assigns_pend)
#       pk None        → the rowid alias assigns it (lastrowid)
#       cells          → the insert-branch pending stream (pending domain)
#       skip           → OR IGNORE row sqlite will silently drop
#       assigns        → upsert DO UPDATE SET values (stored domain,
#                        for the IS-NOT comparison against the OLD row)
#       assigns_pend   → the same values in pending domain
#   update row plan: (pk_tuple, new_stored, new_pend)
#   delete row plan: pk_tuple


def _params_ok(shape: Shape, params) -> bool:
    if shape.uses_named:
        return isinstance(params, dict)
    if isinstance(params, dict):
        return False
    try:
        return len(params) == shape.n_pos
    except TypeError:
        return False


def plan_insert_rows(
    shape: Shape, param_rows: Sequence, single: bool
) -> Optional[list]:
    meta = shape.meta
    aff = meta.affinity
    conflicty = shape.conflict in ("ignore", "nothing", "upsert")
    out: list = []
    for params in param_rows:
        if not _params_ok(shape, params):
            return None
        for vrow in shape.value_rows:
            vals: Dict[str, object] = {}
            for c, slot in zip(shape.columns, vrow):
                v = resolve_slot(slot, params)
                if v is UNSAFE:
                    return None
                v = _col_convert(aff[c], v)
                if v is UNSAFE:
                    return None
                vals[c] = v
            skip = False
            # NULL into a NOT NULL column: REPLACE substitutes the
            # default, IGNORE drops the row silently — both reproduced;
            # plain/upsert INSERTs will raise at execution (no capture)
            for c in meta.notnull:
                if c in vals and vals[c] is None:
                    if shape.conflict == "replace":
                        d = meta.defaults[c]
                        if d is UNSAFE or pending_affinity(d) is UNSAFE:
                            return None
                        vals[c] = d
                    elif shape.conflict == "ignore":
                        skip = True
            pk: Optional[Tuple] = None
            if all(c in vals for c in meta.pk_cols):
                pk = tuple(vals[c] for c in meta.pk_cols)
                # a NULL value for the rowid-alias pk means sqlite
                # assigns the rowid (filled from lastrowid after the
                # statement); NULL in any other pk is stored as-is by
                # rowid tables — captured like every other value
                if meta.ipk_alias and pk[0] is None:
                    pk = None
            if pk is None and not skip:
                if not meta.ipk_alias:
                    return None
                if not single or len(out) > 0 or len(param_rows) > 1:
                    return None  # lastrowid only identifies ONE new row
            # the insert-branch stream, final pending domain
            cells = []
            for cid, v in _cells_insert(meta, vals):
                pv = pending_affinity(v)
                if pv is UNSAFE:
                    return None
                cells.append((cid, pv))
            assigns: Dict[str, object] = {}
            assigns_pend: Dict[str, object] = {}
            if shape.conflict == "upsert":
                for c, slot in shape.upsert_set:
                    if slot[0] == "x":
                        v = (
                            vals[slot[1]]
                            if slot[1] in vals
                            else meta.defaults.get(slot[1])
                        )
                    else:
                        v = resolve_slot(slot, params)
                        if v is UNSAFE:
                            return None
                        v = _col_convert(aff[c], v)
                    if v is UNSAFE:
                        return None
                    pv = pending_affinity(v)
                    if pv is UNSAFE:
                        return None
                    assigns[c] = v
                    assigns_pend[c] = pv
            out.append((pk, cells, skip, assigns, assigns_pend))
            if conflicty and pk is None:
                return None  # conflict modes need the pk up front
    return out


def _plan_pk(shape: Shape, params) -> Optional[Tuple]:
    if not _params_ok(shape, params):
        return None
    meta = shape.meta
    pk: List[object] = []
    for c, slot in zip(meta.pk_cols, shape.pk_slots):
        v = resolve_slot(slot, params)
        if v is UNSAFE:
            return None
        v = _col_convert(meta.affinity[c], v)
        if v is UNSAFE:
            return None
        pk.append(v)
    return tuple(pk)


def plan_update_row(shape: Shape, params) -> Optional[tuple]:
    pk = _plan_pk(shape, params)
    if pk is None:
        return None
    meta = shape.meta
    new: Dict[str, object] = {}
    new_pend: Dict[str, object] = {}
    for c, slot in shape.set_slots:
        v = resolve_slot(slot, params)
        if v is UNSAFE:
            return None
        v = _col_convert(meta.affinity[c], v)
        if v is UNSAFE:
            return None
        pv = pending_affinity(v)
        if pv is UNSAFE:
            return None
        new[c] = v
        new_pend[c] = pv
    return (pk, new, new_pend)


def plan_delete_row(shape: Shape, params) -> Optional[Tuple]:
    return _plan_pk(shape, params)

"""Background database maintenance: WAL truncation + incremental vacuum.

Counterpart of the reference's db-maintenance task
(`klukai-agent/src/agent/handlers.rs:379-547`): a long-running node must
(a) truncate its WAL once it outgrows `perf.wal_threshold_gb` — a WAL
only shrinks on a TRUNCATE checkpoint, so an always-busy node otherwise
grows it unboundedly — and (b) return freed pages to the OS with
incremental vacuum once the freelist passes a floor (`:405-459`).

The WAL truncate uses the reference's escalating busy-timeout ladder
(`calc_busy_timeout`, `handlers.rs:529`): a TRUNCATE checkpoint needs
all readers to drain, so each failed attempt doubles the patience —
30 s, 60 s, … capped at 16 min — rather than spinning.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from corrosion_tpu.runtime.metrics import METRICS

logger = logging.getLogger(__name__)

BUSY_TIMEOUT_BASE_S = 30.0  # handlers.rs:529 ladder start
BUSY_TIMEOUT_CAP_S = 960.0  # …and its 16-minute cap
VACUUM_CHUNK_PAGES = 1000


def calc_busy_timeout_s(attempt: int) -> float:
    """Escalating patience for a TRUNCATE checkpoint: 30 s doubling per
    failed attempt, capped at 16 min (handlers.rs:529-547)."""
    return min(BUSY_TIMEOUT_BASE_S * (2**attempt), BUSY_TIMEOUT_CAP_S)


def wal_size_bytes(store) -> int:
    """Current WAL file size; 0 for in-memory stores."""
    if store._is_memory:
        return 0
    wal = store.path + "-wal"
    try:
        return os.path.getsize(wal)
    except OSError:
        return 0


def truncate_wal_if_needed(
    store, threshold_bytes: int, attempt: int = 0
) -> Optional[bool]:
    """TRUNCATE-checkpoint the WAL if it exceeds `threshold_bytes`.

    Returns None when below threshold, True when the checkpoint fully
    truncated, False when it could not (readers still held the WAL —
    caller escalates `attempt`)."""
    size = wal_size_bytes(store)
    METRICS.gauge("corro.db.wal_size_bytes").set(size)
    if not store._is_memory:
        try:
            METRICS.gauge("corro.db.size").set(os.path.getsize(store.path))
        except OSError:
            pass
    if size <= threshold_bytes:
        return None
    t_ckpt = time.monotonic()
    timeout_ms = int(calc_busy_timeout_s(attempt) * 1000)
    with store._lock:
        store._conn.execute(f"PRAGMA busy_timeout = {timeout_ms}")
        try:
            row = store._conn.execute(
                "PRAGMA wal_checkpoint(TRUNCATE)"
            ).fetchone()
        finally:
            store._conn.execute("PRAGMA busy_timeout = 5000")
    # row = (busy, wal_pages, checkpointed_pages)
    busy = bool(row[0]) if row is not None else True
    if busy:
        METRICS.counter("corro.db.wal_truncate.busy").inc()
        logger.warning(
            "WAL truncate attempt %d busy (size=%d bytes); next timeout %.0fs",
            attempt,
            size,
            calc_busy_timeout_s(attempt + 1),
        )
        return False
    METRICS.counter("corro.db.wal_truncate.ok").inc()
    METRICS.histogram("corro.db.wal.truncate.seconds").observe(
        time.monotonic() - t_ckpt
    )
    logger.info("WAL truncated (was %d bytes)", size)
    return True


def freelist_pages(store) -> int:
    with store._lock:
        return int(store._conn.execute("PRAGMA freelist_count").fetchone()[0])


def incremental_vacuum_if_needed(
    store, min_freelist_pages: int, chunk_pages: int = VACUUM_CHUNK_PAGES
) -> int:
    """Run incremental_vacuum in bounded chunks while the freelist stays
    over the floor (handlers.rs:405-459). Returns pages reclaimed.

    Requires auto_vacuum=INCREMENTAL (set at store bootstrap); on
    databases created without it this is a no-op (freelist still reported
    but incremental_vacuum reclaims nothing)."""
    reclaimed = 0
    t_vac = time.monotonic()
    while True:
        free = freelist_pages(store)
        METRICS.gauge("corro.db.freelist_pages").set(free)
        if free < min_freelist_pages:
            if reclaimed:
                METRICS.histogram(
                    "corro.db.incremental.vacuum.seconds"
                ).observe(time.monotonic() - t_vac)
            return reclaimed
        with store._lock:
            store._conn.execute(f"PRAGMA incremental_vacuum({chunk_pages})")
        after = freelist_pages(store)
        got = free - after
        reclaimed += max(0, got)
        METRICS.counter("corro.db.vacuum.pages").inc(max(0, got))
        if got <= 0:
            # don't spin — and tell the operator WHY nothing came back:
            # a db created before auto_vacuum=INCREMENTAL can never
            # reclaim incrementally (needs a one-time full VACUUM)
            with store._lock:
                mode = int(
                    store._conn.execute("PRAGMA auto_vacuum").fetchone()[0]
                )
            if mode != 2:
                logger.warning(
                    "freelist has %d pages but auto_vacuum=%d (not "
                    "INCREMENTAL): this database predates incremental "
                    "vacuum support and needs a one-time full VACUUM "
                    "(e.g. via backup/restore) to reclaim disk",
                    free,
                    mode,
                )
            return reclaimed


async def wal_maintenance_loop(agent) -> None:
    """Spawned from agent run: checks the WAL against
    `perf.wal_threshold_gb` every `perf.wal_check_interval_secs`,
    escalating the busy ladder across consecutive failed truncations."""
    perf = agent.config.perf
    threshold = int(perf.wal_threshold_gb * 2**30)
    attempt = 0
    while not agent.tripwire.tripped:
        try:
            # LOW write lane: maintenance must never delay client writes
            # or remote applies (agent.rs:503-519 write_low)
            async with agent.write_gate.low():
                result = await asyncio.to_thread(
                    truncate_wal_if_needed, agent.store, threshold, attempt
                )
            attempt = attempt + 1 if result is False else 0
        except Exception:
            logger.exception("wal maintenance failed")
        try:
            await asyncio.wait_for(
                agent.tripwire.wait(), perf.wal_check_interval_secs
            )
        except asyncio.TimeoutError:
            pass


async def vacuum_loop(agent) -> None:
    """Spawned from agent run: incremental vacuum on a 5-minute cadence
    (handlers.rs:405-459)."""
    perf = agent.config.perf
    while not agent.tripwire.tripped:
        try:
            async with agent.write_gate.low():
                await asyncio.to_thread(
                    incremental_vacuum_if_needed,
                    agent.store,
                    perf.vacuum_min_freelist_pages,
                )
        except Exception:
            logger.exception("incremental vacuum failed")
        try:
            await asyncio.wait_for(
                agent.tripwire.wait(), perf.vacuum_interval_secs
            )
        except asyncio.TimeoutError:
            pass

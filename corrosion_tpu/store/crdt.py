"""CrdtStore: the cr-sqlite replacement — SQLite storage + CRDT clocks.

The reference embeds the cr-sqlite C extension (loaded in
`klukai-types/src/sqlite.rs:125-143`) to make tables conflict-free. This
module reimplements its observable behavior natively on stdlib sqlite3:

  - per-table clock tables  `<t>__crdt_clock(pk, cid, col_version,
    db_version, seq, site_id, ts)` — one row per (row, column) cell holding
    the latest write's clock; the sentinel cid "-1" row tracks row
    create/delete
  - per-table row tables    `<t>__crdt_rows(pk, cl)` — causal length per
    row (odd = alive, even = deleted)
  - change capture for local writes via generated AFTER INSERT/UPDATE/DELETE
    triggers (gated on `__crdt_ctx.enabled` so remote applies don't re-log),
    with pks packed by a registered Python function in the cr-sqlite pk
    format
  - merge semantics for remote changes (column-level LWW): higher causal
    length wins the row; at equal (odd) cl, higher col_version wins the
    cell; at equal col_version the larger value wins, equal values merge
    silently (the reference sets `crsql_config_set('merge-equal-values',1)`,
    agent.rs:361)
  - db_version/seq assignment: every local commit takes the next db_version;
    its changed cells are sequenced 0..=last_seq (change.rs:188-258)

Observable-parity features: `changes_for_version` reconstructs
`crsql_changes` rows (current values only — overwritten versions become
"cleared", the basis of Changeset::Empty/EmptySet in sync), and
`rows_impacted`-style filtering marks which applied changes were impactful
(`agent/util.rs:1206-1310`).
"""

from __future__ import annotations

import contextlib
import logging
import os
import sqlite3
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from corrosion_tpu.store.bookkeeping import (
    BookedVersions,
    GapStore,
    PartialVersion,
)
from corrosion_tpu.store import capture as _capture
from corrosion_tpu.store.schema import Schema, SchemaError, diff_schemas, parse_sql
from corrosion_tpu.types.codec import (
    Writer,
    write_change_cells,
    write_change_fields,
)
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.change import Change, SENTINEL
from corrosion_tpu.types.pack import pack_columns, unpack_columns
from corrosion_tpu.types.rangeset import RangeSet
from corrosion_tpu import native
from corrosion_tpu.types.values import (
    TYPE_BLOB,
    TYPE_INTEGER,
    TYPE_REAL,
    TYPE_TEXT,
    SqliteValue,
    cmp_values,
    value_type,
)


class ChangeApplyError(Exception):
    pass


# -- internal tables -------------------------------------------------------

_BOOTSTRAP = """
CREATE TABLE IF NOT EXISTS __crdt_ctx (id INTEGER PRIMARY KEY CHECK (id = 1),
    capture INTEGER NOT NULL DEFAULT 1);
INSERT OR IGNORE INTO __crdt_ctx (id, capture) VALUES (1, 1);

CREATE TABLE IF NOT EXISTS __crdt_pending (
    rowseq INTEGER PRIMARY KEY AUTOINCREMENT,
    tbl TEXT NOT NULL, pk BLOB NOT NULL, cid TEXT NOT NULL, val ANY
);

CREATE TABLE IF NOT EXISTS __crdt_site (id INTEGER PRIMARY KEY CHECK (id = 1),
    site_id BLOB NOT NULL);

CREATE TABLE IF NOT EXISTS __crdt_db_versions (
    site_id BLOB PRIMARY KEY, db_version INTEGER NOT NULL
);

CREATE TABLE IF NOT EXISTS __corro_bookkeeping_gaps (
    actor_id BLOB NOT NULL, start INTEGER NOT NULL, end INTEGER NOT NULL,
    PRIMARY KEY (actor_id, start)
);

CREATE TABLE IF NOT EXISTS __corro_seq_bookkeeping (
    site_id BLOB NOT NULL, db_version INTEGER NOT NULL,
    start_seq INTEGER NOT NULL, end_seq INTEGER NOT NULL,
    last_seq INTEGER NOT NULL, ts INTEGER NOT NULL,
    PRIMARY KEY (site_id, db_version, start_seq)
);

CREATE TABLE IF NOT EXISTS __corro_buffered_changes (
    site_id BLOB NOT NULL, db_version INTEGER NOT NULL, seq INTEGER NOT NULL,
    tbl TEXT NOT NULL, pk BLOB NOT NULL, cid TEXT NOT NULL, val ANY,
    col_version INTEGER NOT NULL, cl INTEGER NOT NULL,
    last_seq INTEGER NOT NULL, ts INTEGER NOT NULL,
    PRIMARY KEY (site_id, db_version, seq)
);

CREATE TABLE IF NOT EXISTS __corro_schema (
    tbl_name TEXT NOT NULL, type TEXT NOT NULL, name TEXT NOT NULL,
    sql TEXT NOT NULL, source TEXT NOT NULL,
    PRIMARY KEY (tbl_name, type, name)
);

CREATE TABLE IF NOT EXISTS __corro_members (
    actor_id BLOB PRIMARY KEY, address TEXT NOT NULL,
    foca_state TEXT, rtt_min REAL, updated_at INTEGER NOT NULL DEFAULT 0
);

CREATE TABLE IF NOT EXISTS __corro_state (key TEXT PRIMARY KEY, value ANY);
"""


def _corro_json_contains(selector, obj) -> bool:
    """Custom SQL scalar `corro_json_contains(selector, object)`: true if
    every key of the JSON selector appears in the JSON object with a
    recursively-contained value; non-objects compare by equality
    (reference `klukai-types/src/sqlite.rs:237-274`). Used by operators
    to filter rows on JSON columns, e.g. consul service meta."""
    import json

    def contains(s, o) -> bool:
        if isinstance(s, dict) and isinstance(o, dict):
            return all(k in o and contains(v, o[k]) for k, v in s.items())
        return s == o

    try:
        return contains(json.loads(selector), json.loads(obj))
    except (ValueError, TypeError):
        raise sqlite3.OperationalError("corro_json_contains: invalid JSON")


def _safe_rollback(conn: sqlite3.Connection) -> None:
    """Best-effort ROLLBACK for exception paths on the write conn.

    An interrupted statement (interrupt_after watchdog / ?timeout=) has
    already rolled the transaction back; a bare ROLLBACK then raises
    'cannot rollback - no transaction is active' and REPLACES the real
    error mid-unwind. Guard on in_transaction and swallow the benign
    race where the interrupt lands between check and rollback."""
    try:
        if conn.in_transaction:
            conn.execute("ROLLBACK")
    except sqlite3.OperationalError as e:
        if conn.in_transaction:
            # a REAL rollback failure (e.g. I/O error): the tx is still
            # open — surfacing beats a mystery 'cannot start a
            # transaction within a transaction' on the next writer
            raise
        log.debug("rollback raced with auto-rollback: %s", e)


# r23: COMMIT-wall observability.  The outer COMMIT (WAL flush) is the
# write path's disk-bound tail; every commit observes its wall, and a
# commit slower than _COMMIT_STALL_S counts a STALL EVENT — the monotone
# counter the `commit-stall` page rule rates over.  (A flush-wall gauge
# would thrash between fast and slow stores sharing the process-global
# registry in the sim; a rate over a monotone counter cannot.)
_COMMIT_STALL_S = 0.025


def _observe_commit_flush(secs: float) -> None:
    from corrosion_tpu.runtime.metrics import METRICS

    METRICS.histogram("corro.store.commit.flush.seconds").observe(secs)
    if secs >= _COMMIT_STALL_S:
        METRICS.counter("corro.store.commit.stall.total").inc()


def _clock_table(t: str) -> str:
    return f"{t}__crdt_clock"


log = logging.getLogger(__name__)


def _native_batch_enabled() -> bool:
    """The columnar native merge engine is on by default; set
    CORRO_NATIVE_BATCH=0 to force the pure-Python decision loop (the
    equivalence tests exercise both)."""
    return os.environ.get("CORRO_NATIVE_BATCH", "1") != "0"


def _finalize_engine() -> str:
    """Engine for the local-commit clock bookkeeping
    (`WriteTx._finalize_pending` / `CrdtStore.finalize_group`).
    "columnar" (default, r21): the r14 bulk IN(...) probes and grouped
    executemany flush, with the phase-B decisions computed over
    per-kind arrays and EVERY cell's wire bytes built in one batched
    encode pass (`types/codec.py write_change_cells`) instead of a
    per-cell emit/encode loop.  "vector" (r14): same probes and flush,
    per-cell in-memory emit loop — the pre-r21 path, kept bit-for-bit
    as the ingest bench's r21 pre mode.  "percell": the per-cell
    reference loop (one SELECT+upsert round-trip per pending cell),
    the semantic reference for the randomized equivalence pin
    (tests/test_finalize_batch.py).  "native" (r24): the phase-B
    decision loop transcribed to C++ (`native/crdt_batch.cpp::
    crdt_finalize_batch`, bit-identical to all three Python engines
    under the randomized pins); hosts where the .so cannot build fall
    back to "columnar", counted by
    `corro.write.finalize.native.unavailable`."""
    eng = os.environ.get("CORRO_FINALIZE", "columnar")
    if eng not in ("columnar", "vector", "percell", "native"):
        raise ValueError(
            f"unknown CORRO_FINALIZE {eng!r} "
            "(expected 'columnar', 'vector', 'percell' or 'native')"
        )
    return eng


# finalize-parity markers (analysis/finalize_parity.py): the native
# finalize ABI — these must match `FINALIZE_ABI_VERSION` /
# `FIN_CID_SENTINEL` in native/crdt_batch.cpp, pinned at lint time.
_NATIVE_FINALIZE_ABI = 1
_NATIVE_SENTINEL_CID = -1  # interned id `_phase_b_native` sends for SENTINEL


def _capture_engine() -> str:
    """Engine for local-write change capture (r15).  "direct" (default):
    `WriteTx.execute`/`executemany` parse-or-cache the statement shape
    and record the written cells in memory — no `__crdt_pending`
    INSERT, no readback SELECT, no DELETE — with the AFTER triggers
    kept installed as the capture path for raw/unrecognized SQL.
    "trigger": every statement captures through the triggers, the
    pre-r15 path, kept as the semantic reference for the randomized
    equivalence pin (tests/test_capture.py) and the ingest bench's pre
    mode.  `[perf] direct_capture = false` forces "trigger" per agent
    (CrdtStore.direct_capture)."""
    eng = os.environ.get("CORRO_CAPTURE", "direct")
    if eng not in ("direct", "trigger"):
        raise ValueError(
            f"unknown CORRO_CAPTURE {eng!r} (expected 'direct' or 'trigger')"
        )
    return eng


# bound-variable budget for the finalize IN(...) probes: 3.32+ builds
# allow 32766 bound parameters, older ones 999 — shrink once on the old
# cap instead of pre-chunking everything to the worst case (the whole
# point is one probe statement per table at real transaction sizes)
_PROBE_CHUNK = [8000]


def _iter_in_chunks(conn, sql_fmt: str, keys: Sequence):
    """Yield rows of `sql_fmt.format(marks=...)` over `keys`, chunked to
    the build's bound-variable budget."""
    i = 0
    while i < len(keys):
        chunk = keys[i : i + _PROBE_CHUNK[0]]
        try:
            marks = ",".join("?" * len(chunk))
            yield from conn.execute(sql_fmt.format(marks=marks), list(chunk))
        except sqlite3.OperationalError as e:
            if "too many" in str(e) and _PROBE_CHUNK[0] > 900:
                _PROBE_CHUNK[0] = 900
                continue
            raise
        i += len(chunk)


def _merge_engine() -> str:
    """Engine order for the batch decision plane (phase B).

    "python" (default since r6): the pure-Python reference loop — the
    measured end-to-end winner at EVERY banked batch size on both hosts
    (CRDT_MERGE_AB.json: 28.4k vs 18.8k changes/s @512 ... 37.8k vs
    37.7k @65k; CRDT_MERGE_AB_TPU.json agrees), and decision-only winner
    at 3 of 4 rungs.  The old "native" default contradicted the repo's
    own A/B (VERDICT r5 weak #1) — decision + revert criterion recorded
    in COMPONENTS.md "CRDT engine placement".
    "native": C++ columnar loop (ctypes), Python fallback.
    "array": jitted array kernel (ops/crdt_merge.py — SURVEY §7 step 1's
    device-resident form), then native, then Python; the kernel declines
    batches with undecidable value ties.
    The A/B harness (scripts/bench_crdt_merge.py) flips this knob over
    identical inputs."""
    eng = os.environ.get("CORRO_CRDT_ENGINE", "python")
    if eng not in ("native", "array", "python"):
        raise ValueError(
            f"unknown CORRO_CRDT_ENGINE {eng!r} "
            "(expected 'native', 'array' or 'python')"
        )
    if eng == "native" and not _native_batch_enabled():
        return "python"
    return eng


def _dedupe_pending(pending):
    """Collapse one sub-transaction's trigger log (same output as the
    per-cell reference's dedupe, O(n)): last write in the tx wins per
    (table, pk, cid); a delete marker drops the row's other pending
    entries; a key re-added after a delete re-appends, so the
    reverse-dedupe keeps each surviving key's LAST fresh insertion slot
    (the reference's `order.remove` + append behavior).

    ``pending`` rows are (tbl, pk, cid, val) tuples — produced by the
    r15 in-memory direct capture, or drained from `__crdt_pending` in
    rowseq order for trigger-captured statements (the two streams merge
    before this point, `WriteTx._take_pending`).

    Returns (cells, order, deleted_rows)."""
    cells: Dict[Tuple[str, bytes, str], SqliteValue] = {}
    order: List[Tuple[str, bytes, str]] = []
    deleted_rows: Dict[Tuple[str, bytes], bool] = {}
    row_keys: Dict[Tuple[str, bytes], set] = {}
    for r in pending:
        tbl, pk, cid, val = r
        if cid == SENTINEL + "X":  # delete marker from the del trigger
            deleted_rows[(tbl, pk)] = True
            for key in row_keys.pop((tbl, pk), ()):
                cells.pop(key, None)
            continue
        if cid == SENTINEL:
            deleted_rows.pop((tbl, pk), None)
        key = (tbl, pk, cid)
        if key not in cells:
            order.append(key)
        cells[key] = val
        row_keys.setdefault((tbl, pk), set()).add(key)
    if len(order) != len(cells):
        # delete/re-insert chains left stale slots: keep the LAST
        # occurrence of each surviving key, preserving relative order
        seen: set = set()
        fresh: List[Tuple[str, bytes, str]] = []
        for key in reversed(order):
            if key in cells and key not in seen:
                seen.add(key)
                fresh.append(key)
        order = fresh[::-1]
    return cells, order, deleted_rows


def _clock_entry(ch: Change, col_version: int) -> tuple:
    """One `__crsql_clock`-equivalent row plan: (col_version, db_version,
    seq, site_id, ts)."""
    return (col_version, ch.db_version, ch.seq, ch.site_id, ch.ts.ntp64)


# shared read-only default for the columnar phase B's batched
# col_version reads (never mutated — writes go through setdefault)
_EMPTY_CV: Dict[str, int] = {}


def _encode_value(v: SqliteValue, i: int, types, ints, reals, offs, lens,
                  arena: bytearray) -> None:
    """Marshal one sqlite value into slot `i` of the tagged-union columns
    handed to the native merge engine (shared by batch and disk values —
    the two sides of every tie compare must encode identically)."""
    tt = value_type(v)
    types[i] = tt
    if tt == TYPE_INTEGER:
        ints[i] = int(v)
    elif tt == TYPE_REAL:
        reals[i] = v
    elif tt == TYPE_TEXT:
        b = v.encode("utf-8")
        offs[i] = len(arena)
        lens[i] = len(b)
        arena += b
    elif tt == TYPE_BLOB:
        b = bytes(v)
        offs[i] = len(arena)
        lens[i] = len(b)
        arena += b


def _rows_table(t: str) -> str:
    return f"{t}__crdt_rows"


class _InterruptWatchdog:
    """One daemon thread interrupting a connection past armed deadlines.

    `arm(seconds)` registers a deadline and returns a token; `disarm`
    removes it. The thread sleeps until the earliest active deadline and
    fires `conn.interrupt()` only if that token is STILL armed (checked
    under the lock), so cancellation is race-free. The thread starts
    lazily on first arm and idles on a condition variable otherwise."""

    def __init__(self, conn: sqlite3.Connection):
        self._conn = conn
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._deadlines: Dict[int, float] = {}
        self._next_token = 0
        self._thread: Optional[threading.Thread] = None

    def arm(self, seconds: float) -> int:
        import time as _time

        with self._cond:
            token = self._next_token
            self._next_token += 1
            self._deadlines[token] = _time.monotonic() + seconds
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="crdt-interrupt-watchdog",
                    daemon=True,
                )
                self._thread.start()
            self._cond.notify()
            return token

    def disarm(self, token: int) -> None:
        with self._cond:
            self._deadlines.pop(token, None)
            self._cond.notify()

    def _run(self) -> None:
        import time as _time

        with self._cond:
            while True:
                if not self._deadlines:
                    self._cond.wait(timeout=60.0)
                    if not self._deadlines:
                        continue
                now = _time.monotonic()
                token, deadline = min(
                    self._deadlines.items(), key=lambda kv: kv[1]
                )
                if deadline > now:
                    self._cond.wait(timeout=deadline - now)
                    continue
                # fire: token still armed here, under the lock
                self._deadlines.pop(token, None)
                try:
                    self._conn.interrupt()
                    from corrosion_tpu.runtime.metrics import METRICS

                    METRICS.counter("corro.sqlite.interrupt").inc()
                except sqlite3.ProgrammingError:
                    return  # connection closed — watchdog retires


@dataclass
class AppliedChanges:
    """Result of applying a remote changeset portion."""

    impactful: List[Change]
    changed_tables: Dict[str, int]


class CrdtStore:
    """One node's database: user tables + CRDT clocks + bookkeeping.

    Thread model: a single writer at a time (enforced with an RLock, the
    SplitPool equivalent provides queuing above this); readers may use
    `read_conn()` snapshots on other threads (WAL mode).
    """

    _mem_counter = 0

    def __init__(self, path: str, site_id: Optional[ActorId] = None):
        if path == ":memory:":
            # shared-cache URI so read_conn() can open real extra
            # connections to the same in-memory database
            CrdtStore._mem_counter += 1
            path = f"file:crdtmem{id(self)}_{CrdtStore._mem_counter}?mode=memory&cache=shared"
        self.path = path
        # the trigger capture gate (r15): the generated AFTER triggers'
        # WHEN clause calls `corro_capture_on()` (registered per
        # connection in _setup_conn) which reads THIS flag — toggling
        # capture for remote applies / direct-captured statements is a
        # Python list store instead of an UPDATE statement + WAL page
        # per transaction.  Single-writer model: only the write conn
        # fires triggers, and every toggle happens under self._lock.
        self._capture_flag = [1]
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None, uri=True
        )
        self._conn.row_factory = sqlite3.Row
        self._lock = threading.RLock()
        self._setup_conn(self._conn)
        with self._lock:
            self._conn.executescript(_BOOTSTRAP)
            # one boot-time sweep replaces the old per-transaction
            # defensive DELETE: pending rows cannot survive a committed
            # tx (commit drains them) or a rolled-back one (undone), so
            # anything here is pre-crash junk from an older build
            self._conn.execute("DELETE FROM __crdt_pending")
            row = self._conn.execute("SELECT site_id FROM __crdt_site").fetchone()
            if row is None:
                sid = site_id or ActorId.new_random()
                self._conn.execute(
                    "INSERT INTO __crdt_site (id, site_id) VALUES (1, ?)",
                    (sid.bytes16,),
                )
            else:
                sid = ActorId(bytes(row["site_id"]))
                if site_id is not None and site_id != sid:
                    raise ChangeApplyError(
                        f"db already has site id {sid}, asked for {site_id}"
                    )
        self.site_id: ActorId = sid
        self.schema: Schema = Schema()
        self._pk_unpack_cache: Dict[bytes, tuple] = {}
        # r15 direct capture: per-statement-text shape cache (None =
        # "not capturable, use triggers"); cleared on schema changes.
        # `direct_capture` is the agent-level knob ([perf]
        # direct_capture), ANDed with the CORRO_CAPTURE env engine.
        self.direct_capture = True
        self._shape_cache: Dict[str, Optional[object]] = {}
        # r18 chaos: optional injected disk pathology (chaos/faults.py
        # StoreFaults) consulted at the writer-statement, COMMIT and
        # remote-apply touch points — None (the default) costs one
        # attribute check on each
        self.chaos = None
        # r23: wall of the most recent outer COMMIT (chaos latency
        # included — the injected sleep stands in for a slow fsync).
        # Read by the group committer's write-profile bucket stamps;
        # written under the store lock, so reads after group_tx exits
        # see this group's value
        self.last_flush_secs = 0.0
        # own/remote head-version cache: db_version_for is on every
        # commit's path, and the value only changes through
        # _bump_db_version (cache updated there) — cleared on rollback
        # paths where a bump may have been undone
        self._dv_cache: Dict[bytes, int] = {}
        self._read_pool: List[sqlite3.Connection] = []
        self._read_pool_lock = threading.Lock()
        self._read_out = 0  # checked-out read conns (pool gauges)
        # swap generation (r17 snapshot install): a database-file swap
        # bumps this; read conns checked out before the swap are
        # DISCARDED on release instead of re-pooled — a pre-swap conn's
        # fd points at the replaced inode and would serve stale reads
        self._read_gen = 0
        self._read_conn_gen: Dict[int, int] = {}
        self._closed = False
        # resolve (and on first use, compile) the native merge engine NOW:
        # doing it lazily inside _apply_batch would run a g++ subprocess
        # while holding the store lock and an open write transaction
        self._merge_lib = native.merge_batch_lib()
        self._watchdog = _InterruptWatchdog(self._conn)
        self._load_schema()
        if self.schema.tables:
            # refresh capture triggers to the current DDL generation
            # (r15 moved the gate to corro_capture_on()); one-time at
            # open, inside a single transaction
            with self._lock:
                self._conn.execute("BEGIN IMMEDIATE")
                try:
                    for t in self.schema.tables.values():
                        self._drop_triggers(t.name)
                        self._create_triggers(t)
                    self._conn.execute("COMMIT")
                except BaseException:
                    _safe_rollback(self._conn)
                    raise

    # -- connection setup --------------------------------------------------

    @property
    def _is_memory(self) -> bool:
        return "mode=memory" in self.path

    def _setup_conn(
        self, conn: sqlite3.Connection, writer: bool = True
    ) -> None:
        if not self._is_memory:
            # INCREMENTAL before any table exists so the maintenance
            # loops can reclaim freelist pages (setup.rs:80, the
            # reference opens with auto_vacuum=INCREMENTAL); no-op with a
            # warning on pre-existing dbs created without it
            conn.execute("PRAGMA auto_vacuum = INCREMENTAL")
            conn.execute("PRAGMA journal_mode = WAL")
        conn.execute("PRAGMA synchronous = NORMAL")
        conn.execute("PRAGMA foreign_keys = OFF")
        conn.execute("PRAGMA recursive_triggers = OFF")
        # ingest-path I/O tuning (bench_ingest.py): negative cache_size is
        # KiB — a 64 MiB page cache keeps the clock-table btree hot across
        # sync-flood batches, but ONLY on the single write connection; up
        # to 20 pooled readers each holding 64 MiB would balloon resident
        # memory, so readers keep a modest 8 MiB. temp_store dodges disk
        # spills on the IN(...) prefetch sorts; mmap reads (shared pages)
        # skip the syscall per page
        conn.execute(
            f"PRAGMA cache_size = {-65536 if writer else -8192}"
        )
        conn.execute("PRAGMA temp_store = MEMORY")
        try:
            conn.execute("PRAGMA mmap_size = 268435456")
        except sqlite3.DatabaseError:
            pass
        # native C++ extension keeps Python out of the per-row trigger
        # path (the cr-sqlite-equivalent native layer); Python fallback
        # has identical semantics
        from corrosion_tpu import native

        conn.create_function(
            "corro_json_contains", 2, _corro_json_contains,
            deterministic=True,
        )
        # the trigger capture gate — deliberately NON-deterministic so
        # sqlite re-evaluates it per trigger fire.  NOTE: out-of-band
        # writers (a bare sqlite3 shell) would need this function to
        # write CRR tables; like the reference's crsql extension, all
        # writes are expected to go through the agent.
        flag = self._capture_flag
        conn.create_function("corro_capture_on", 0, lambda: flag[0])
        if not native.load_into(conn):
            conn.create_function(
                "crdt_pack", -1, _sql_pack, deterministic=True
            )
            conn.create_function(
                "crdt_cmp", 2, lambda a, b: cmp_values(a, b),
                deterministic=True,
            )

    @contextlib.contextmanager
    def interrupt_after(self, seconds: float):
        """Arm the shared watchdog to interrupt the write connection if
        the wrapped block runs longer than `seconds` — the
        InterruptibleTransaction counterpart
        (`klukai-types/src/sqlite_pool/mod.rs`: timeout →
        sqlite3_interrupt). The in-flight statement then raises
        sqlite3.OperationalError('interrupted') and the open transaction
        rolls back, instead of wedging the single write path forever.

        One long-lived watchdog thread serves every guarded block (the
        ingestion hot path arms one per apply batch — a fresh
        threading.Timer each time would churn an OS thread per batch),
        and disarm-before-fire is checked under the watchdog lock so a
        block that finishes right at the deadline can never interrupt
        the NEXT writer's healthy transaction."""
        token = self._watchdog.arm(seconds)
        try:
            yield
        finally:
            self._watchdog.disarm(token)

    READ_POOL_MAX = 20  # SplitPool read side: 20 RO conns (agent.rs:478)

    def acquire_read(self) -> sqlite3.Connection:
        """Check a read connection out of the pool (or open a fresh one).
        Return it with `release_read`, or use `pooled_read()`.

        The pool has its own mutex: WAL readers never wait on the writer,
        so a checkout must not block on `self._lock` while a write batch
        holds it across BEGIN IMMEDIATE..COMMIT (the SplitPool read side
        is lock-free with respect to the write side, agent.rs:478-519)."""
        from corrosion_tpu.runtime.metrics import METRICS

        with self._read_pool_lock:
            if self._closed:
                raise sqlite3.ProgrammingError(
                    "cannot acquire read connection: store is closed"
                )
            if self._read_pool:
                conn = self._read_pool.pop()
                self._read_out += 1
                self._read_conn_gen[id(conn)] = self._read_gen
                METRICS.gauge("corro.sqlite.pool.read.connections").set(
                    self._read_out
                )
                METRICS.gauge(
                    "corro.sqlite.pool.read.connections.available"
                ).set(len(self._read_pool))
                return conn
        # open outside the lock; count only a SUCCESSFUL open so a failed
        # sqlite3.connect can't permanently inflate the checked-out gauge
        conn = self.read_conn()
        with self._read_pool_lock:
            self._read_out += 1
            self._read_conn_gen[id(conn)] = self._read_gen
            METRICS.gauge("corro.sqlite.pool.read.connections").set(
                self._read_out
            )
        return conn

    def release_read(
        self, conn: sqlite3.Connection, discard: bool = False
    ) -> None:
        """Return a read connection to the pool.

        Pass ``discard=True`` when releasing on an error path: an
        exception can leave a cursor open on the connection (e.g. a
        half-consumed generator), and a parked open statement pins its
        WAL read snapshot — the next acquirer would read stale data and
        block checkpointing. Discarded conns are closed, not pooled."""
        from corrosion_tpu.runtime.metrics import METRICS

        with self._read_pool_lock:
            self._read_out = max(0, self._read_out - 1)
            gen = self._read_conn_gen.pop(id(conn), self._read_gen)
            METRICS.gauge("corro.sqlite.pool.read.connections").set(
                self._read_out
            )
            if (
                not discard
                and not self._closed
                and gen == self._read_gen
                and len(self._read_pool) < self.READ_POOL_MAX
            ):
                self._read_pool.append(conn)
                METRICS.gauge(
                    "corro.sqlite.pool.read.connections.available"
                ).set(len(self._read_pool))
                return
        # discarding, pool full, or the store closed while this conn was
        # checked out — close it instead of parking it open forever
        conn.close()

    @contextlib.contextmanager
    def pooled_read(self):
        """Context-managed pooled read connection — the SplitPool read
        side (1 RW + 20 RO, agent.rs:478-519): hot read paths (queries,
        sync serves, metrics) skip per-call sqlite connection setup.
        A connection released while an exception unwinds is discarded
        (see release_read)."""
        conn = self.acquire_read()
        try:
            yield conn
        except BaseException:
            self.release_read(conn, discard=True)
            raise
        else:
            self.release_read(conn)

    def read_conn(self) -> sqlite3.Connection:
        """A new read connection (WAL snapshot isolation for file stores,
        shared cache for in-memory). Caller closes."""
        conn = sqlite3.connect(self.path, check_same_thread=False, uri=True)
        conn.row_factory = sqlite3.Row
        conn.execute("PRAGMA query_only = ON")
        # modest read-side tuning: 8 MiB cache (20 pooled readers stay
        # ~160 MiB worst case), shared mmap pages, in-memory sort spills
        conn.execute("PRAGMA cache_size = -8192")
        conn.execute("PRAGMA temp_store = MEMORY")
        try:
            conn.execute("PRAGMA mmap_size = 268435456")
        except sqlite3.DatabaseError:
            pass
        # custom SQL fns must exist on READ connections too — that is
        # where /v1/queries and the pubsub matcher run user SQL
        conn.create_function(
            "corro_json_contains", 2, _corro_json_contains,
            deterministic=True,
        )
        return conn

    def close(self) -> None:
        with self._read_pool_lock:
            self._closed = True
            for conn in self._read_pool:
                conn.close()
            self._read_pool.clear()
        with self._lock:
            self._conn.close()

    # -- live database swap (r17 snapshot bootstrap) -----------------------

    @contextlib.contextmanager
    def swapped_database(self):
        """Replace the database FILE underneath a live store
        (`store/snapshot.py` install): closes every connection, yields
        for the caller to swap the file, then reopens onto the new one
        — fresh write connection and watchdog, caches dropped (pk
        shapes, statement shapes, head versions all describe the OLD
        database), schema + capture triggers reloaded, the tail of
        __init__ replayed against the installed snapshot.

        `self._lock` is held for the WHOLE block, so every direct-conn
        user (maintenance loops, member persistence, bookkeeping reads)
        parks on the lock and resumes against the new connection —
        never observes a closed one.  The caller must still have
        quiesced the write path (the agent's write gate) and run this
        on ONE worker thread (the RLock is reentrant per-thread).
        Readers checked out before the swap are discarded on release
        via the read-generation bump, never re-pooled."""
        with self._read_pool_lock:
            for conn in self._read_pool:
                conn.close()
            self._read_pool.clear()
            self._read_gen += 1
        with self._lock:
            self._conn.close()
            try:
                yield
            finally:
                # reopen even when the swap body failed: restore's
                # os.replace is atomic, so the path holds either the
                # old or the new database — never a torn one
                self._reopen_after_swap()

    def _reopen_after_swap(self) -> None:
        self._conn = sqlite3.connect(
            self.path, check_same_thread=False, isolation_level=None,
            uri=True,
        )
        self._conn.row_factory = sqlite3.Row
        self._setup_conn(self._conn)
        # the old watchdog thread retires on its next closed-conn
        # interrupt attempt; swaps are rare enough that a fresh
        # thread per swap is the simple, correct ownership
        self._watchdog = _InterruptWatchdog(self._conn)
        self._pk_unpack_cache.clear()
        self._shape_cache.clear()
        self._dv_cache.clear()
        self._load_schema()
        if self.schema.tables:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                for t in self.schema.tables.values():
                    self._drop_triggers(t.name)
                    self._create_triggers(t)
                self._conn.execute("COMMIT")
            except BaseException:
                _safe_rollback(self._conn)
                raise

    # -- schema ------------------------------------------------------------

    def _load_schema(self) -> None:
        rows = self._conn.execute(
            "SELECT sql FROM __corro_schema WHERE type IN ('table','index')"
            " ORDER BY rowid"
        ).fetchall()
        if rows:
            self.schema = parse_sql("\n".join(r["sql"] + ";" for r in rows))

    def apply_schema_sql(self, sql: str) -> Schema:
        """Parse + diff + apply new schema DDL (the `/v1/migrations` path,
        reference `api/public/mod.rs:560-667` → `schema.rs:285`)."""
        new_schema = parse_sql(sql)
        diff = diff_schemas(self.schema, new_schema)
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                for t in diff.new_tables:
                    self._conn.execute(t.raw_sql)
                    self._create_crr_machinery(t)
                    for idx in t.indexes.values():
                        self._conn.execute(idx.raw_sql)
                for tname, col, decl in diff.new_columns:
                    self._conn.execute(f'ALTER TABLE "{tname}" ADD COLUMN {decl}')
                    # regenerate triggers to include the new column
                    t = new_schema.tables[tname]
                    self._drop_triggers(tname)
                    self._create_triggers(t)
                for t in diff.rebuild_tables:
                    # 12-step rebuild for changed column definitions
                    # (schema.rs:528-596). The CRDT clock/rows state lives
                    # in separate __crdt tables keyed by packed pk, so
                    # recreating the user table preserves replication
                    # state exactly (pk set changes are refused upstream).
                    old_t = self.schema.tables[t.name]
                    common = [c for c in old_t.columns if c in t.columns]
                    collist = ", ".join(f'"{c}"' for c in common)
                    tmp = f"{t.name}__rebuild_old"
                    self._drop_triggers(t.name)
                    self._conn.execute(
                        f'ALTER TABLE "{t.name}" RENAME TO "{tmp}"'
                    )
                    self._conn.execute(t.raw_sql)  # original name, new def
                    self._conn.execute(
                        f'INSERT INTO "{t.name}" ({collist}) '
                        f'SELECT {collist} FROM "{tmp}"'
                    )
                    self._conn.execute(f'DROP TABLE "{tmp}"')
                    for idx in t.indexes.values():
                        self._conn.execute(
                            f'DROP INDEX IF EXISTS "{idx.name}"'
                        )
                        self._conn.execute(idx.raw_sql)
                    self._create_triggers(t)
                for iname in diff.dropped_indexes:
                    self._conn.execute(f'DROP INDEX IF EXISTS "{iname}"')
                for idx in diff.changed_indexes:
                    self._conn.execute(f'DROP INDEX IF EXISTS "{idx.name}"')
                    self._conn.execute(idx.raw_sql)
                for idx in diff.new_indexes:
                    self._conn.execute(idx.raw_sql)
                # persist schema source
                self._conn.execute("DELETE FROM __corro_schema")
                for t in new_schema.tables.values():
                    self._conn.execute(
                        "INSERT INTO __corro_schema VALUES (?,?,?,?,?)",
                        (t.name, "table", t.name, t.raw_sql, "api"),
                    )
                    for idx in t.indexes.values():
                        self._conn.execute(
                            "INSERT INTO __corro_schema VALUES (?,?,?,?,?)",
                            (t.name, "index", idx.name, idx.raw_sql, "api"),
                        )
                self._conn.execute("COMMIT")
            except BaseException:
                _safe_rollback(self._conn)
                raise
        self.schema = new_schema
        self._shape_cache.clear()  # shapes bind column sets/affinities
        return new_schema

    def capture_shape(self, sql: str):
        """Cached direct-capture shape for one statement text (None =
        not capturable — the triggers handle it).  Callers hold the
        store lock (the write path)."""
        cache = self._shape_cache
        try:
            return cache[sql]
        except KeyError:
            pass
        if len(cache) > 4096:
            cache.clear()  # unbounded ad-hoc SQL must not pin memory
        shape = _capture.parse_shape(sql, self.schema)
        cache[sql] = shape
        return shape

    def _create_crr_machinery(self, t) -> None:
        ct, rt = _clock_table(t.name), _rows_table(t.name)
        self._conn.execute(
            f'CREATE TABLE IF NOT EXISTS "{ct}" ('
            " pk BLOB NOT NULL, cid TEXT NOT NULL,"
            " col_version INTEGER NOT NULL, db_version INTEGER NOT NULL,"
            " seq INTEGER NOT NULL, site_id BLOB NOT NULL, ts INTEGER NOT NULL,"
            " PRIMARY KEY (pk, cid))"
        )
        self._conn.execute(
            f'CREATE INDEX IF NOT EXISTS "{ct}__site_version"'
            f' ON "{ct}" (site_id, db_version)'
        )
        self._conn.execute(
            f'CREATE TABLE IF NOT EXISTS "{rt}" ('
            " pk BLOB PRIMARY KEY, cl INTEGER NOT NULL)"
        )
        self._create_triggers(t)

    def _pk_pack_expr(self, t, prefix: str) -> str:
        cols = ", ".join(f'{prefix}."{c}"' for c in t.pk_cols)
        return f"crdt_pack({cols})"

    def _create_triggers(self, t) -> None:
        name = t.name
        new_pk = self._pk_pack_expr(t, "NEW")
        old_pk = self._pk_pack_expr(t, "OLD")
        # r15: the gate is a connection-registered function over
        # CrdtStore._capture_flag (toggling costs no statement and no
        # WAL page; pre-r15 DDL gated on a __crdt_ctx subselect, and
        # triggers are refreshed at open so old DBs migrate)
        gate = "corro_capture_on() = 1"
        ins_cols = "".join(
            f"INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" VALUES ('{name}', {new_pk}, '{c}', NEW.\"{c}\");\n"
            for c in t.non_pk_cols
        )
        self._conn.execute(
            f'CREATE TRIGGER "{name}__crdt_ins" AFTER INSERT ON "{name}"'
            f" WHEN {gate} BEGIN\n"
            f" INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" VALUES ('{name}', {new_pk}, '{SENTINEL}', NULL);\n"
            f"{ins_cols} END"
        )
        # A pk change is modeled as delete(old row) + create(new row), the
        # way cr-sqlite treats it — otherwise replicas diverge silently.
        pk_changed = f"{new_pk} IS NOT {old_pk}"
        upd_cols = "".join(
            f"INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" SELECT '{name}', {new_pk}, '{c}', NEW.\"{c}\""
            f' WHERE NEW."{c}" IS NOT OLD."{c}" OR {pk_changed};\n'
            for c in t.non_pk_cols
        )
        self._conn.execute(
            f'CREATE TRIGGER "{name}__crdt_upd" AFTER UPDATE ON "{name}"'
            f" WHEN {gate} BEGIN\n"
            f" INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" SELECT '{name}', {old_pk}, '{SENTINEL}X', NULL WHERE {pk_changed};\n"
            f" INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" SELECT '{name}', {new_pk}, '{SENTINEL}', NULL WHERE {pk_changed};\n"
            f"{upd_cols} END"
        )
        self._conn.execute(
            f'CREATE TRIGGER "{name}__crdt_del" AFTER DELETE ON "{name}"'
            f" WHEN {gate} BEGIN\n"
            f" INSERT INTO __crdt_pending (tbl, pk, cid, val)"
            f" VALUES ('{name}', {old_pk}, '{SENTINEL}X', NULL);\n END"
        )

    def _drop_triggers(self, name: str) -> None:
        for suffix in ("ins", "upd", "del"):
            self._conn.execute(f'DROP TRIGGER IF EXISTS "{name}__crdt_{suffix}"')

    # -- db_version accounting --------------------------------------------

    def db_version_for(self, site: ActorId) -> int:
        key = site.bytes16
        v = self._dv_cache.get(key)
        if v is not None:
            return v
        row = self._conn.execute(
            "SELECT db_version FROM __crdt_db_versions WHERE site_id = ?",
            (key,),
        ).fetchone()
        v = row["db_version"] if row else 0
        self._dv_cache[key] = v
        return v

    def _bump_db_version(self, site: ActorId, version: int) -> None:
        self._conn.execute(
            "INSERT INTO __crdt_db_versions (site_id, db_version) VALUES (?, ?)"
            " ON CONFLICT (site_id) DO UPDATE SET db_version ="
            " MAX(db_version, excluded.db_version)",
            (site.bytes16, version),
        )
        key = site.bytes16
        cached = self._dv_cache.get(key, 0)
        if version > cached:
            self._dv_cache[key] = version

    # -- local writes ------------------------------------------------------

    def write_tx(
        self, ts: Timestamp, nested: bool = False, savepoint: bool = True
    ) -> "WriteTx":
        """Begin a local write transaction capturing CRDT changes.

        ``nested=True`` begins a SAVEPOINT sub-transaction for use
        inside a ``group_tx`` scope (r14 group commit): the sub-tx gets
        per-writer rollback isolation while the leader's one
        BEGIN/COMMIT (one fsync, one lock hold) covers the batch.
        ``savepoint=False`` (nested only, r15) skips the savepoint for
        a SOLO batch — no batchmates to isolate, failure aborts the
        whole group tx."""
        return WriteTx(self, ts, nested=nested, savepoint=savepoint)

    def finalize_group(self, items) -> List[Tuple[List[Change], int, int]]:
        """Finalize one or more sub-transactions' pending logs in ONE
        vectorized pass (r14): the dedupe → sentinel → col_version
        decisions run purely in memory over a single bulk-read of the
        current cl/clock state, and the final clock/rows state flushes
        with one executemany per (table × statement shape) for the
        WHOLE batch — the `_apply_batch` shape applied to local commits.

        ``items`` is ``[(pending_rows, ts), ...]`` in commit order; the
        caller holds the store lock and the open (group) transaction,
        and every item's data-table effects are already applied (a
        rolled-back sub-tx must not be passed here).  Items with
        changes get consecutive db_versions.  Returns
        ``[(changes, db_version, last_seq), ...]`` aligned to items
        (db_version 0 = the item produced no changes).

        Cross-item semantics are identical to committing the items as
        separate sequential transactions (pinned in
        tests/test_group_commit.py): later items see earlier items'
        cl/col_version effects through the shared in-memory state the
        way sequential commits see them through the database."""
        conn = self._conn
        site = self.site_id

        deduped = [_dedupe_pending(pending) for pending, _ts in items]

        # -- phase A: ONE bulk read over the union of touched keys ---------
        probe_pks: Dict[str, set] = {}  # rows-table probe (all touched pks)
        clock_pks: Dict[str, set] = {}  # clock probe (pks with col writes)
        clock_need: set = set()  # (tbl, pk, cid) whose cv decides col_version
        for cells, order, deleted_rows in deduped:
            for (tbl, pk) in deleted_rows:
                probe_pks.setdefault(tbl, set()).add(pk)
            for (tbl, pk, cid) in order:
                probe_pks.setdefault(tbl, set()).add(pk)
                if cid != SENTINEL:
                    clock_pks.setdefault(tbl, set()).add(pk)
                    clock_need.add((tbl, pk, cid))
        cur_cl: Dict[Tuple[str, bytes], int] = {}  # absent key = no row yet
        # live col_version view per (tbl, pk): starts as the disk state,
        # mutated by clears/puts so later items (and later cells) see
        # exactly what a sequential re-read would have seen
        cv_state: Dict[Tuple[str, bytes], Dict[str, int]] = {}
        for tbl, pks in probe_pks.items():
            rt = _rows_table(tbl)
            for r in _iter_in_chunks(
                conn,
                f'SELECT pk, cl FROM "{rt}" WHERE pk IN ({{marks}})',
                list(pks),
            ):
                cur_cl[(tbl, bytes(r["pk"]))] = r["cl"]
        for tbl, pks in clock_pks.items():
            ct = _clock_table(tbl)
            for r in _iter_in_chunks(
                conn,
                f'SELECT pk, cid, col_version FROM "{ct}"'
                f" WHERE pk IN ({{marks}})",
                list(pks),
            ):
                key = (tbl, bytes(r["pk"]), r["cid"])
                if key in clock_need:
                    cv_state.setdefault(key[:2], {})[r["cid"]] = (
                        r["col_version"]
                    )

        # -- phase B: per-item in-memory decisions, shared live state ------
        rows_up: Dict[str, Dict[bytes, int]] = {}
        clock_clear: Dict[str, Dict[bytes, None]] = {}  # ordered set
        clock_put: Dict[str, Dict[bytes, Dict[str, tuple]]] = {}
        out: List[List[Change]] = []
        start_dv = self.db_version_for(site)
        next_dv = start_dv + 1

        eng = _finalize_engine()
        if eng == "native":
            next_dv = self._phase_b_native(
                deduped, items, cur_cl, cv_state, rows_up, clock_clear,
                clock_put, out, next_dv,
            )
        elif eng == "columnar":
            next_dv = self._phase_b_columnar(
                deduped, items, cur_cl, cv_state, rows_up, clock_clear,
                clock_put, out, next_dv,
            )
        else:
            next_dv = self._phase_b_percell_emit(
                deduped, items, cur_cl, cv_state, rows_up, clock_clear,
                clock_put, out, next_dv,
            )

        # -- phase C: ONE bulk flush for the whole batch -------------------
        for tbl in {
            t for d in (rows_up, clock_clear, clock_put) for t in d
        }:
            rt, ct = _rows_table(tbl), _clock_table(tbl)
            if rows_up.get(tbl):
                conn.executemany(
                    f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                    " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                    list(rows_up[tbl].items()),
                )
            if clock_clear.get(tbl):
                conn.executemany(
                    f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?',
                    [(pk, SENTINEL) for pk in clock_clear[tbl]],
                )
            if clock_put.get(tbl):
                conn.executemany(
                    f'INSERT INTO "{ct}" (pk, cid, col_version, db_version,'
                    " seq, site_id, ts) VALUES (?,?,?,?,?,?,?)"
                    " ON CONFLICT (pk, cid) DO UPDATE SET"
                    " col_version = excluded.col_version,"
                    " db_version = excluded.db_version,"
                    " seq = excluded.seq, site_id = excluded.site_id,"
                    " ts = excluded.ts",
                    [
                        (pk, cid, cv, dbv, sq, st, ts)
                        for pk, entries in clock_put[tbl].items()
                        for cid, (cv, dbv, sq, st, ts) in entries.items()
                    ],
                )

        if next_dv > start_dv + 1:
            self._bump_db_version(site, next_dv - 1)
        results: List[Tuple[List[Change], int, int]] = []
        for changes in out:
            if changes:
                dv = changes[0].db_version
                last_seq = changes[-1].seq
                self.record_last_seq(site, dv, last_seq)
                results.append((changes, dv, last_seq))
            else:
                results.append(([], 0, 0))
        return results

    def _phase_b_columnar(
        self, deduped, items, cur_cl, cv_state, rows_up, clock_clear,
        clock_put, out, next_dv,
    ) -> int:
        """Columnar finalize phase B (r21): decisions per (table × kind)
        batch, encode in ONE pass.

        The r14/r15 loop paid a Writer allocation, a 4-call field encode
        and a frozen-dataclass construction PER CELL inside the decision
        walk (~180 µs of a 10-row commit).  Here each item's decisions
        run over per-kind arrays — delete-kind causal lengths in one
        comprehension over the deleted-row array, sentinel
        creation/resurrection decisions as their own pass, column-kind
        cl/col_version reads as array comprehensions over the deduped
        keys (unique per item, so the batched reads see exactly the
        sequential state) — producing compact spec tuples; then the
        WHOLE GROUP's wire cells are built by one `write_change_cells`
        batch-encode call and the Change objects materialize in a tight
        zip loop.  Emission order, seq numbering, clock rows and cell
        bytes are pinned identical to `_finalize_pending_percell` /
        CORRO_FINALIZE=vector by tests/test_finalize_batch.py.

        Kind-splitting is only equivalent while every SENTINEL precedes
        its own row's column cells in `order` (true for everything the
        capture planes emit: insert-like statements log sentinel-first,
        updates log no sentinel); a violating item falls back to the
        in-order sequential walk so correctness never rides on the
        capture convention."""
        site = self.site_id
        site_bytes = site.bytes16
        all_specs: List[tuple] = []
        item_slices: List[tuple] = []  # (start, end, ts)
        for (cells, order, deleted_rows), (_pending, ts) in zip(
            deduped, items
        ):
            db_version = next_dv
            ts_ntp = ts.ntp64
            specs: List[tuple] = []
            add = specs.append

            def clear_clocks(tbl, pk):
                clock_clear.setdefault(tbl, {})[pk] = None
                cv_state.pop((tbl, pk), None)
                puts = clock_put.get(tbl, {}).get(pk)
                if puts:
                    for c in [c for c in puts if c != SENTINEL]:
                        del puts[c]

            # delete kind: bumped-even causal lengths over the whole
            # deleted-row array in one pass
            if deleted_rows:
                dr = list(deleted_rows)
                del_cls = [cur_cl.get(k, 1) + 1 for k in dr]
                del_cls = [c + (c & 1) for c in del_cls]
                for (tbl, pk), cl in zip(dr, del_cls):
                    cur_cl[(tbl, pk)] = cl
                    rows_up.setdefault(tbl, {})[pk] = cl
                    clear_clocks(tbl, pk)
                    seq = len(specs)
                    add((tbl, pk, SENTINEL, None, cl, db_version, seq, cl))
                    clock_put.setdefault(tbl, {}).setdefault(pk, {})[
                        SENTINEL
                    ] = (cl, db_version, seq, site_bytes, ts_ntp)

            hazard = False
            col_rows: set = set()
            for tbl, pk, cid in order:
                if cid == SENTINEL:
                    if (tbl, pk) in col_rows:
                        hazard = True
                        break
                else:
                    col_rows.add((tbl, pk))

            if not hazard:
                slots: List[Optional[tuple]] = [None] * len(order)
                # sentinel kind: creation/resurrection over its array
                for i, (tbl, pk, cid) in enumerate(order):
                    if cid != SENTINEL:
                        continue
                    k2 = (tbl, pk)
                    exists = k2 in cur_cl
                    prev_cl = cur_cl.get(k2, 0)
                    cl = prev_cl + 1 if prev_cl % 2 == 0 else prev_cl
                    if not exists or prev_cl % 2 == 0:
                        cur_cl[k2] = cl
                        rows_up.setdefault(tbl, {})[pk] = cl
                        if prev_cl % 2 == 0 and prev_cl > 0:
                            clear_clocks(tbl, pk)
                        slots[i] = (tbl, pk, SENTINEL, None, cl, cl)
                # column kind: cl / col_version reads as one array
                # comprehension each over the (unique) deduped keys
                col_idx = [
                    i for i, key in enumerate(order) if key[2] != SENTINEL
                ]
                cl_get = cur_cl.get
                cv_get = cv_state.get
                col_cls = [
                    cl_get((order[i][0], order[i][1]), 1) for i in col_idx
                ]
                col_cvs = [
                    cv_get((order[i][0], order[i][1]), _EMPTY_CV).get(
                        order[i][2], 0
                    )
                    + 1
                    for i in col_idx
                ]
                for i, cl, cv in zip(col_idx, col_cls, col_cvs):
                    key = order[i]
                    tbl, pk, cid = key
                    cv_state.setdefault((tbl, pk), {})[cid] = cv
                    slots[i] = (tbl, pk, cid, cells[key], cv, cl)
                # compact in emission order; clock rows keyed off the
                # final seqs (put order within an item is upsert-keyed,
                # so deferring past the decisions is state-identical)
                for sl in slots:
                    if sl is None:
                        continue
                    tbl, pk, cid, val, cv, cl = sl
                    seq = len(specs)
                    add((tbl, pk, cid, val, cv, db_version, seq, cl))
                    clock_put.setdefault(tbl, {}).setdefault(pk, {})[
                        cid
                    ] = (cv, db_version, seq, site_bytes, ts_ntp)
            else:
                # in-order sequential fallback: same arithmetic with
                # immediate effects (a later sentinel may clear this
                # item's own earlier column puts here)
                for key in order:
                    tbl, pk, cid = key
                    k2 = (tbl, pk)
                    if cid == SENTINEL:
                        exists = k2 in cur_cl
                        prev_cl = cur_cl.get(k2, 0)
                        cl = prev_cl + 1 if prev_cl % 2 == 0 else prev_cl
                        if not exists or prev_cl % 2 == 0:
                            cur_cl[k2] = cl
                            rows_up.setdefault(tbl, {})[pk] = cl
                            if prev_cl % 2 == 0 and prev_cl > 0:
                                clear_clocks(tbl, pk)
                            seq = len(specs)
                            add((
                                tbl, pk, SENTINEL, None, cl, db_version,
                                seq, cl,
                            ))
                            clock_put.setdefault(tbl, {}).setdefault(
                                pk, {}
                            )[SENTINEL] = (
                                cl, db_version, seq, site_bytes, ts_ntp,
                            )
                        continue
                    cl = cur_cl.get(k2, 1)
                    cv = cv_state.get(k2, {}).get(cid, 0) + 1
                    cv_state.setdefault(k2, {})[cid] = cv
                    seq = len(specs)
                    add((tbl, pk, cid, cells[key], cv, db_version, seq, cl))
                    clock_put.setdefault(tbl, {}).setdefault(pk, {})[
                        cid
                    ] = (cv, db_version, seq, site_bytes, ts_ntp)

            if specs:
                next_dv += 1
            item_slices.append((len(all_specs), len(all_specs) + len(specs), ts))
            all_specs.extend(specs)

        # ONE vectorized pack pass for every cell in the group
        blobs = write_change_cells(all_specs, site_bytes)
        if all_specs:
            from corrosion_tpu.runtime.metrics import METRICS

            METRICS.counter("corro.write.finalize.columnar.total").inc(
                len(all_specs)
            )
        new_change = Change.__new__
        for a, b, ts in item_slices:
            changes: List[Change] = []
            for spec, cell in zip(all_specs[a:b], blobs[a:b]):
                tbl, pk, cid, val, cv, dbv, seq, cl = spec
                ch = new_change(Change)
                ch.__dict__.update(
                    table=tbl, pk=pk, cid=cid, val=val, col_version=cv,
                    db_version=dbv, seq=seq, site_id=site_bytes, cl=cl,
                    ts=ts, wire_cell=cell,
                )
                changes.append(ch)
            out.append(changes)
        return next_dv

    def _phase_b_native(
        self, deduped, items, cur_cl, cv_state, rows_up, clock_clear,
        clock_put, out, next_dv,
    ) -> int:
        """Native finalize phase B (r24, CORRO_FINALIZE=native): the
        decision loop runs in C++ (`native/crdt_batch.cpp::
        crdt_finalize_batch`), Python keeps the value plane.

        The glue interns the group's (table, pk) rows and cids to dense
        integer ids, ships the deduped order keys / deleted-row sets /
        phase-A snapshot as flat arrays, and gets back per-item change
        SPECS (seq implicit by position, db_version derived here — the
        same consecutive-assignment rule every engine uses) plus the
        final rows/clock plans with Python-dict insertion-order
        semantics.  Values never cross the boundary: a column spec
        carries its global order index and the value is fetched from
        the item's own deduped cells, then the WHOLE group encodes via
        the same one-pass `write_change_cells` call the columnar engine
        uses — byte-identity pinned in tests/test_finalize_batch.py.

        A host where the .so cannot build (or the call reports a
        malformed batch, which a correct glue never produces) falls
        back to the columnar engine, silently but COUNTED:
        `corro.write.finalize.native.unavailable`."""
        from corrosion_tpu import native as _native_mod
        from corrosion_tpu.runtime.metrics import METRICS

        lib = _native_mod.finalize_batch_lib()
        if lib is None:
            METRICS.counter(
                "corro.write.finalize.native.unavailable"
            ).inc()
            return self._phase_b_columnar(
                deduped, items, cur_cl, cv_state, rows_up, clock_clear,
                clock_put, out, next_dv,
            )
        import ctypes as C

        # -- intern rows/cids + flatten the group geometry -----------------
        row_ids: Dict[Tuple[str, bytes], int] = {}
        rows: List[Tuple[str, bytes]] = []
        cid_ids: Dict[str, int] = {}
        cids: List[str] = []
        del_off = [0]
        del_rows: List[int] = []
        ord_off = [0]
        ord_rows: List[int] = []
        ord_cids: List[int] = []
        ord_keys: List[tuple] = []  # global order index -> (tbl, pk, cid)
        for cells, order, deleted_rows in deduped:
            for k in deleted_rows:
                i = row_ids.get(k)
                if i is None:
                    i = row_ids[k] = len(rows)
                    rows.append(k)
                del_rows.append(i)
            del_off.append(len(del_rows))
            for key in order:
                tbl, pk, cid = key
                k = (tbl, pk)
                i = row_ids.get(k)
                if i is None:
                    i = row_ids[k] = len(rows)
                    rows.append(k)
                ord_rows.append(i)
                if cid == SENTINEL:
                    ord_cids.append(_NATIVE_SENTINEL_CID)
                else:
                    ci = cid_ids.get(cid)
                    if ci is None:
                        ci = cid_ids[cid] = len(cids)
                        cids.append(cid)
                    ord_cids.append(ci)
                ord_keys.append(key)
            ord_off.append(len(ord_rows))

        cap = len(del_rows) + len(ord_rows)
        if cap == 0:
            for _ in deduped:
                out.append([])
            return next_dv

        n_rows = len(rows)
        row_cl = [0] * n_rows
        row_ex = [0] * n_rows
        for k, i in row_ids.items():
            cl = cur_cl.get(k)
            if cl is not None:
                row_cl[i] = cl
                row_ex[i] = 1
        cv_r: List[int] = []
        cv_c: List[int] = []
        cv_v: List[int] = []
        for k2, entry in cv_state.items():
            i = row_ids.get(k2)
            if i is None:
                continue
            for cid, v in entry.items():
                ci = cid_ids.get(cid)
                if ci is None:
                    continue  # probe row whose cid this group never writes
                cv_r.append(i)
                cv_c.append(ci)
                cv_v.append(v)

        I32, I64, U8 = C.c_int32, C.c_int64, C.c_uint8

        def arr(ctype, lst):
            return (ctype * max(1, len(lst)))(*lst)

        spec_count = (I32 * len(deduped))()
        spec_row = (I32 * cap)()
        spec_cid = (I32 * cap)()
        spec_ord = (I32 * cap)()
        spec_cv = (I64 * cap)()
        spec_cl = (I64 * cap)()
        up_row = (I32 * cap)()
        up_cl = (I64 * cap)()
        n_up = I32()
        clear_row = (I32 * cap)()
        n_clear = I32()
        put_row = (I32 * cap)()
        put_cid = (I32 * cap)()
        put_cv = (I64 * cap)()
        put_item = (I32 * cap)()
        put_seq = (I32 * cap)()
        n_put = I32()
        rc = lib.crdt_finalize_batch(
            len(deduped), arr(I32, del_off), arr(I32, del_rows),
            arr(I32, ord_off), arr(I32, ord_rows), arr(I32, ord_cids),
            n_rows, arr(I64, row_cl), arr(U8, row_ex),
            len(cv_r), arr(I32, cv_r), arr(I32, cv_c), arr(I64, cv_v),
            spec_count, spec_row, spec_cid, spec_ord, spec_cv, spec_cl,
            up_row, up_cl, C.byref(n_up), clear_row, C.byref(n_clear),
            put_row, put_cid, put_cv, put_item, put_seq, C.byref(n_put),
        )
        if rc != 0:
            METRICS.counter(
                "corro.write.finalize.native.unavailable"
            ).inc()
            return self._phase_b_columnar(
                deduped, items, cur_cl, cv_state, rows_up, clock_clear,
                clock_put, out, next_dv,
            )

        # -- materialize specs / plans back into the phase-C shapes --------
        site_bytes = self.site_id.bytes16
        all_specs: List[tuple] = []
        item_slices: List[tuple] = []  # (start, end, ts)
        item_meta: List[tuple] = []  # (db_version, ts_ntp) per item
        pos = 0
        for idx, ((cells, _order, _deleted), (_pending, ts)) in enumerate(
            zip(deduped, items)
        ):
            cnt = spec_count[idx]
            db_version = next_dv
            if cnt:
                next_dv += 1
            item_meta.append((db_version, ts.ntp64))
            for seq in range(cnt):
                j = pos + seq
                tbl, pk = rows[spec_row[j]]
                ci = spec_cid[j]
                if ci == _NATIVE_SENTINEL_CID:
                    cid, val = SENTINEL, None
                else:
                    cid = cids[ci]
                    val = cells[ord_keys[spec_ord[j]]]
                all_specs.append((
                    tbl, pk, cid, val, spec_cv[j], db_version, seq,
                    spec_cl[j],
                ))
            item_slices.append((pos, pos + cnt, ts))
            pos += cnt
        for j in range(n_up.value):
            tbl, pk = rows[up_row[j]]
            rows_up.setdefault(tbl, {})[pk] = up_cl[j]
        for j in range(n_clear.value):
            tbl, pk = rows[clear_row[j]]
            clock_clear.setdefault(tbl, {})[pk] = None
        for j in range(n_put.value):
            tbl, pk = rows[put_row[j]]
            ci = put_cid[j]
            cid = SENTINEL if ci == _NATIVE_SENTINEL_CID else cids[ci]
            dbv, ts_ntp = item_meta[put_item[j]]
            clock_put.setdefault(tbl, {}).setdefault(pk, {})[cid] = (
                put_cv[j], dbv, put_seq[j], site_bytes, ts_ntp,
            )

        # ONE vectorized pack pass — the same batch encoder (and the
        # same Change materialization) as the columnar engine
        blobs = write_change_cells(all_specs, site_bytes)
        if all_specs:
            METRICS.counter("corro.write.finalize.native.total").inc(
                len(all_specs)
            )
        new_change = Change.__new__
        for a, b, ts in item_slices:
            changes: List[Change] = []
            for spec, cell in zip(all_specs[a:b], blobs[a:b]):
                tbl, pk, cid, val, cv, dbv, seq, cl = spec
                ch = new_change(Change)
                ch.__dict__.update(
                    table=tbl, pk=pk, cid=cid, val=val, col_version=cv,
                    db_version=dbv, seq=seq, site_id=site_bytes, cl=cl,
                    ts=ts, wire_cell=cell,
                )
                changes.append(ch)
            out.append(changes)
        return next_dv

    def _phase_b_percell_emit(
        self, deduped, items, cur_cl, cv_state, rows_up, clock_clear,
        clock_put, out, next_dv,
    ) -> int:
        """The r14/r15 per-cell emit loop, kept bit-for-bit as the
        CORRO_FINALIZE=vector engine (the columnar phase B's A/B
        baseline and second semantic witness)."""
        site = self.site_id
        site_bytes = site.bytes16
        new_change = Change.__new__
        for (cells, order, deleted_rows), (_pending, ts) in zip(
            deduped, items
        ):
            db_version = next_dv
            changes: List[Change] = []

            def emit(tbl, pk, cid, val, col_version, cl):
                # fused encode (r15): build the change's wire cell in
                # the SAME pass that emits it, so commit goes captured
                # cells → clocked changes → shared wire bytes in one
                # walk (with_wire_body then just splices cached cells).
                # The Change is built via __dict__ to skip the frozen
                # dataclass's per-field object.__setattr__ — this loop
                # runs once per written cell on every local commit.
                seq = len(changes)
                cw = Writer()
                write_change_fields(
                    cw, tbl, pk, cid, val, col_version, db_version,
                    seq, site_bytes, cl,
                )
                ch = new_change(Change)
                ch.__dict__.update(
                    table=tbl, pk=pk, cid=cid, val=val,
                    col_version=col_version, db_version=db_version,
                    seq=seq, site_id=site_bytes, cl=cl,
                    ts=ts, wire_cell=cw.bytes(),
                )
                changes.append(ch)

            def clear_clocks(tbl, pk):
                clock_clear.setdefault(tbl, {})[pk] = None
                cv_state.pop((tbl, pk), None)
                puts = clock_put.get(tbl, {}).get(pk)
                if puts:
                    for c in [c for c in puts if c != SENTINEL]:
                        del puts[c]

            # deletes first: sentinel change with bumped-even cl
            for (tbl, pk) in deleted_rows:
                cl = cur_cl.get((tbl, pk), 1) + 1
                if cl % 2 == 1:
                    cl += 1  # already deleted? keep even
                cur_cl[(tbl, pk)] = cl
                rows_up.setdefault(tbl, {})[pk] = cl
                clear_clocks(tbl, pk)
                emit(tbl, pk, SENTINEL, None, cl, cl)
                clock_put.setdefault(tbl, {}).setdefault(pk, {})[
                    SENTINEL
                ] = _clock_entry(changes[-1], cl)

            # creations/updates
            for key in order:
                tbl, pk, cid = key
                k2 = (tbl, pk)
                if cid == SENTINEL:
                    # row creation (or resurrection)
                    exists = k2 in cur_cl
                    prev_cl = cur_cl.get(k2, 0)
                    cl = prev_cl + 1 if prev_cl % 2 == 0 else prev_cl
                    if not exists or prev_cl % 2 == 0:
                        cur_cl[k2] = cl
                        rows_up.setdefault(tbl, {})[pk] = cl
                        if prev_cl % 2 == 0 and prev_cl > 0:
                            # resurrection: reset column clocks
                            clear_clocks(tbl, pk)
                        emit(tbl, pk, SENTINEL, None, cl, cl)
                        clock_put.setdefault(tbl, {}).setdefault(pk, {})[
                            SENTINEL
                        ] = _clock_entry(changes[-1], cl)
                    continue
                # column write on a (now) live row
                cl = cur_cl.get(k2, 1)
                col_version = cv_state.get(k2, {}).get(cid, 0) + 1
                emit(tbl, pk, cid, cells[key], col_version, cl)
                cv_state.setdefault(k2, {})[cid] = col_version
                clock_put.setdefault(tbl, {}).setdefault(pk, {})[cid] = (
                    _clock_entry(changes[-1], col_version)
                )

            if changes:
                next_dv += 1
            out.append(changes)
        return next_dv

    @contextlib.contextmanager
    def group_tx(self):
        """Leader scope for a group commit: ONE store-lock hold and ONE
        BEGIN IMMEDIATE..COMMIT shared by several `write_tx(nested=True)`
        sub-transactions (the r14 write-path coalescer).  A failure of
        the outer COMMIT itself rolls back every sub-tx in the batch;
        individual writer failures are contained by their savepoints."""
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                yield self
                import time as _time

                t0 = _time.monotonic()
                if self.chaos is not None:
                    # r18 slow/sick-disk injection: commit latency and
                    # transient I/O errors land HERE, where a real disk
                    # would surface them — the whole group aborts and
                    # every writer gets a typed error
                    self.chaos.on_commit()
                self._conn.execute("COMMIT")
                self.last_flush_secs = _time.monotonic() - t0
                _observe_commit_flush(self.last_flush_secs)
            except BaseException:
                _safe_rollback(self._conn)
                self._dv_cache.clear()  # bumps may have rolled back
                raise

    # -- serving changes (crsql_changes reads) ----------------------------

    def changes_for_versions(
        self,
        site: ActorId,
        start_version: int,
        end_version: int,
        conn: Optional[sqlite3.Connection] = None,
    ) -> Iterator[Tuple[int, List[Change]]]:
        """Yield (db_version, ordered changes) for every version in the
        range that still has live clock rows from `site`, newest first
        (the sync server scans db_version DESC, peer/mod.rs:620-700).
        Overwritten versions yield nothing — callers emit EmptySet."""
        c = conn or self._conn
        # Pass 1: the distinct live versions in range (index-only, small).
        # Pass 2: ONE version's rows at a time, newest first — a large
        # sync streams with bounded memory instead of materializing every
        # requested version up front (the reference reads grouped by
        # db_version DESC the same way, peer/mod.rs:620-700).
        versions: set = set()
        for tname in self.schema.tables:
            ct = _clock_table(tname)
            versions.update(
                row[0]
                for row in c.execute(
                    f'SELECT DISTINCT db_version FROM "{ct}"'
                    f" WHERE site_id = ? AND db_version BETWEEN ? AND ?",
                    (site.bytes16, start_version, end_version),
                )
            )
        for v in sorted(versions, reverse=True):
            changes: List[Change] = []
            for tname, t in self.schema.tables.items():
                ct, rt = _clock_table(tname), _rows_table(tname)
                rows = c.execute(
                    f'SELECT k.pk AS pk, k.cid AS cid,'
                    f" k.col_version AS col_version, k.seq AS seq,"
                    f' k.ts AS ts, r.cl AS cl FROM "{ct}" k'
                    f' JOIN "{rt}" r ON r.pk = k.pk'
                    f" WHERE k.site_id = ? AND k.db_version = ?",
                    (site.bytes16, v),
                ).fetchall()
                for row in rows:
                    val = None
                    cid = row["cid"]
                    if cid != SENTINEL:
                        val = self._current_value(c, t, bytes(row["pk"]), cid)
                    changes.append(
                        Change(
                            table=tname,
                            pk=bytes(row["pk"]),
                            cid=cid,
                            val=val,
                            col_version=row["col_version"],
                            db_version=v,
                            seq=row["seq"],
                            site_id=site.bytes16,
                            cl=row["cl"],
                            ts=Timestamp(row["ts"]),
                        )
                    )
            changes.sort(key=lambda ch: ch.seq)
            yield v, changes

    def last_seq_for_version(
        self,
        site: ActorId,
        version: int,
        conn: Optional[sqlite3.Connection] = None,
    ) -> Optional[int]:
        """Max seq ever assigned in `version` (needed because later writes
        can erase clock rows; tracked in __corro_state for local versions)."""
        row = (conn or self._conn).execute(
            "SELECT value FROM __corro_state WHERE key = ?",
            (f"last_seq:{site}:{version}",),
        ).fetchone()
        return row["value"] if row else None

    def buffered_last_seq(
        self,
        site: ActorId,
        version: int,
        conn: Optional[sqlite3.Connection] = None,
    ) -> Optional[int]:
        """The true last_seq a partially buffered version will end at
        (carried on every buffered row and in seq bookkeeping)."""
        row = (conn or self._conn).execute(
            "SELECT MAX(last_seq) AS ls FROM __corro_seq_bookkeeping"
            " WHERE site_id = ? AND db_version = ?",
            (site.bytes16, version),
        ).fetchone()
        return row["ls"] if row and row["ls"] is not None else None

    def buffered_seq_ranges(
        self,
        site: ActorId,
        version: int,
        conn: Optional[sqlite3.Connection] = None,
    ) -> RangeSet:
        """Seq ranges actually buffered for a partial version."""
        rows = (conn or self._conn).execute(
            "SELECT start_seq, end_seq FROM __corro_seq_bookkeeping"
            " WHERE site_id = ? AND db_version = ?",
            (site.bytes16, version),
        ).fetchall()
        return RangeSet([(r["start_seq"], r["end_seq"]) for r in rows])

    def record_last_seq(self, site: ActorId, version: int, last_seq: int) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO __corro_state VALUES (?, ?)",
            (f"last_seq:{site}:{version}", last_seq),
        )

    def _snapshot_data_rows(
        self,
        tbl: str,
        chs: Sequence[Change],
        st: Dict[bytes, dict],
    ) -> None:
        """Phase-A prefetch of current data-row values for a batch's pks.

        Equal-(cl, col_version) tie-breaks in phase B compare the incoming
        value against the current cell value (crsql's merge-equal-values
        rule, ref `util.rs:1206-1310`). The data table is only mutated at
        flush (phase C), so one chunked read per table here replaces a
        per-tie SELECT inside the decision loop. Rows whose unpacked-pk
        tuple does not round-trip through SQLite comparison (e.g. column
        affinity rewrote the stored value) simply stay unfetched
        (``disk is None``) and fall back to the per-row read.
        """
        # exact candidate set: a phase-B disk read can only happen for a
        # change whose col_version EQUALS the pre-batch clock value for
        # that (pk, cid) — in-batch wins and causal transitions route the
        # comparison through the s["vals"] cache instead. Everything else
        # never touches the data row, so fetch only the candidates.
        cand: set = set()
        tie_col_set: set = set()
        for ch in chs:
            if ch.cid == SENTINEL:
                continue
            cv = st[ch.pk]["clock"].get(ch.cid)
            if cv is not None and ch.col_version == cv:
                cand.add(ch.pk)
                tie_col_set.add(ch.cid)
        if not cand:
            return
        tie_cols = sorted(tie_col_set)
        t = self.schema.tables[tbl]
        unpack_cache = self._pk_unpack_cache
        by_tuple: Dict[tuple, bytes] = {}
        for pk in cand:
            u = unpack_cache.get(pk)
            if u is None:
                u = unpack_cache[pk] = tuple(unpack_columns(pk))
            by_tuple[u] = pk
        pk_cols = list(t.pk_cols)
        npk = len(pk_cols)
        col_sel = ", ".join(f'"{c}"' for c in pk_cols + tie_cols)
        tuples = [u for u in by_tuple if len(u) == npk]
        step = max(1, 800 // npk)
        conn = self._conn
        for i in range(0, len(tuples), step):
            chunk = tuples[i : i + step]
            if npk == 1:
                marks = ",".join("?" * len(chunk))
                where = f'"{pk_cols[0]}" IN ({marks})'
                args: List = [u[0] for u in chunk]
            else:
                row = "(" + ",".join("?" * npk) + ")"
                cols = ",".join(f'"{c}"' for c in pk_cols)
                values = ",".join([row] * len(chunk))
                where = f"({cols}) IN (VALUES {values})"
                args = [v for u in chunk for v in u]
            for r in conn.execute(
                f'SELECT {col_sel} FROM "{t.name}" WHERE {where}', args
            ):
                pk = by_tuple.get(tuple(r[k] for k in range(npk)))
                if pk is not None:
                    st[pk]["disk"] = {
                        c: r[npk + j] for j, c in enumerate(tie_cols)
                    }

    def _current_value(
        self, conn: sqlite3.Connection, t, pk: bytes, cid: str
    ) -> SqliteValue:
        where = " AND ".join(f'"{c}" IS ?' for c in t.pk_cols)
        row = conn.execute(
            f'SELECT "{cid}" AS v FROM "{t.name}" WHERE {where}',
            unpack_columns(pk),
        ).fetchone()
        return row["v"] if row is not None else None

    # -- remote change application ----------------------------------------

    def apply_changes(self, changes: Sequence[Change]) -> AppliedChanges:
        """Apply remote CRDT changes inside one transaction; returns the
        impactful subset (counterpart of `process_complete_version`,
        util.rs:1206-1310, with crsql's merge rules).

        Batched (round-2 redesign of the ingestion hot path): local clock/
        row state for every pk in the batch is bulk-read up front, the
        merge decisions run as pure in-memory passes over that snapshot
        (no SQL per change), and the *final* state is flushed with a
        handful of executemany statements. Semantics are pinned to the
        per-row reference implementation `_apply_one` by
        `tests/test_crdt_batch.py` (randomized equivalence)."""
        impactful: List[Change] = []
        changed_tables: Dict[str, int] = {}
        if self.chaos is not None:
            # r18 slow-disk injection on the ingest path: a sick-disk
            # node falls behind the cluster, not just its own clients
            self.chaos.on_apply()
        from corrosion_tpu.runtime.trace import timed_query

        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            # gate triggers off for the remote apply — a Python store,
            # restored unconditionally in the finally (r15: the old
            # __crdt_ctx UPDATE needed an interrupt-proof retry dance
            # to guarantee local writes kept replicating; a flag store
            # cannot fail)
            self._capture_flag[0] = 0
            try:
                # r23 statement profiler: the batched merge is ONE
                # shape — bulk reads + executemany flush, no useful
                # per-statement split
                with timed_query("apply batch", shape="apply:batch"):
                    impactful = self._apply_batch(changes, changed_tables)
                site_max: Dict[bytes, int] = {}
                for ch in changes:
                    if ch.db_version > site_max.get(ch.site_id, 0):
                        site_max[ch.site_id] = ch.db_version
                for site, version in site_max.items():
                    self._bump_db_version(ActorId(site), version)
                self._conn.execute("COMMIT")
            except BaseException:
                _safe_rollback(self._conn)
                self._dv_cache.clear()  # bumps may have rolled back
                raise
            finally:
                self._capture_flag[0] = 1
        return AppliedChanges(impactful, changed_tables)

    def _apply_batch(
        self, changes: Sequence[Change], changed_tables: Dict[str, int]
    ) -> List[Change]:
        """In-memory merge of a whole batch + bulk flush. Caller holds the
        lock and an open transaction."""
        conn = self._conn

        # -- phase A: bulk-read local state for every (table, pk) ----------
        by_table: Dict[str, List[Change]] = {}
        by_pos: Dict[str, List[int]] = {}
        for gidx, ch in enumerate(changes):
            t = self.schema.tables.get(ch.table)
            if t is None:
                continue  # unknown table: drop silently (schema lag)
            if ch.cid != SENTINEL and ch.cid not in t.columns:
                continue
            by_table.setdefault(ch.table, []).append(ch)
            by_pos.setdefault(ch.table, []).append(gidx)

        # per table: pk -> {"cl": int, "clock": {cid: col_version}}
        local: Dict[str, Dict[bytes, dict]] = {}
        for tbl, chs in by_table.items():
            rt, ct = _rows_table(tbl), _clock_table(tbl)
            pks = list({ch.pk for ch in chs})
            st: Dict[bytes, dict] = {
                pk: {"cl": 0, "clock": {}, "vals": {}, "disk": None}
                for pk in pks
            }
            for i in range(0, len(pks), 900):
                chunk = pks[i : i + 900]
                marks = ",".join("?" * len(chunk))
                for r in conn.execute(
                    f'SELECT pk, cl FROM "{rt}" WHERE pk IN ({marks})', chunk
                ):
                    st[bytes(r["pk"])]["cl"] = r["cl"]
                for r in conn.execute(
                    f'SELECT pk, cid, col_version FROM "{ct}"'
                    f" WHERE pk IN ({marks})",
                    chunk,
                ):
                    st[bytes(r["pk"])]["clock"][r["cid"]] = r["col_version"]
            self._snapshot_data_rows(tbl, chs, st)
            local[tbl] = st

        # -- phase B: sequential in-memory merge decisions -----------------
        # mutation plans per table (final-state, flushed once at the end);
        # clock/cell plans nest per pk so a causal transition resets a
        # row's pending writes with one dict pop instead of rescanning
        # the whole batch's flat plan (was O(batch) per transition)
        row_cl: Dict[str, Dict[bytes, int]] = {}  # rows-table upserts
        cleared: Dict[str, set] = {}  # pks whose non-sentinel clocks drop
        clock_final: Dict[str, Dict[bytes, Dict[str, tuple]]] = {}
        cell_final: Dict[str, Dict[bytes, Dict[str, SqliteValue]]] = {}
        row_delete: Dict[str, set] = {}
        row_ensure: Dict[str, set] = {}
        impactful: List[Change] = []

        for tbl in by_table:
            row_cl[tbl] = {}
            cleared[tbl] = set()
            clock_final[tbl] = {}
            cell_final[tbl] = {}
            row_delete[tbl] = set()
            row_ensure[tbl] = set()

        # Decisions are independent across tables (state is per
        # (table, pk)), so each table's changes merge separately — through
        # the native columnar engine (`native/crdt_batch.cpp`) when it is
        # available, else the pure-Python loop. Within a table, arrival
        # order is preserved; `impactful` keeps GLOBAL arrival order via
        # the per-table win masks + original positions.
        engine = _merge_engine()
        lib = self._merge_lib if engine in ("native", "array") else None
        array_merge = None
        if engine == "array":
            from corrosion_tpu.ops.crdt_merge import merge_table_array

            array_merge = merge_table_array
        win_global = [False] * len(changes)
        for tbl, chs in by_table.items():
            wins = None
            if array_merge is not None:
                wins = array_merge(
                    self, tbl, chs, local[tbl],
                    row_cl[tbl], cleared[tbl], clock_final[tbl],
                    cell_final[tbl], row_delete[tbl], row_ensure[tbl],
                )
            if wins is None and lib is not None:
                wins = self._merge_table_native(
                    lib, tbl, chs, local[tbl],
                    row_cl[tbl], cleared[tbl], clock_final[tbl],
                    cell_final[tbl], row_delete[tbl], row_ensure[tbl],
                )
            if wins is None:
                wins = self._merge_table_python(
                    tbl, chs, local[tbl],
                    row_cl[tbl], cleared[tbl], clock_final[tbl],
                    cell_final[tbl], row_delete[tbl], row_ensure[tbl],
                )
            n_wins = 0
            pos = by_pos[tbl]
            for j, w in enumerate(wins):
                if w:
                    win_global[pos[j]] = True
                    n_wins += 1
            if n_wins:
                changed_tables[tbl] = changed_tables.get(tbl, 0) + n_wins
        for gidx, ch in enumerate(changes):
            if win_global[gidx]:
                impactful.append(ch)

        # -- phase C: bulk flush of final state ----------------------------
        unpack_cache = self._pk_unpack_cache
        if len(unpack_cache) > 200_000:
            unpack_cache.clear()
        return self._flush_batch(
            by_table, row_cl, cleared, clock_final, cell_final,
            row_delete, row_ensure, impactful,
        )

    def _merge_table_python(
        self,
        tbl: str,
        chs: Sequence[Change],
        st: Dict[bytes, dict],
        rcl: Dict[bytes, int],
        clr: set,
        ckf: Dict[bytes, Dict[str, tuple]],
        clf: Dict[bytes, Dict[str, SqliteValue]],
        rdel: set,
        rens: set,
    ) -> List[bool]:
        """Reference decision loop for one table's changes (arrival order).

        Returns the per-change win mask; fills the caller's flush plans.
        (A numpy phase-B was prototyped for VERDICT #9 and measured SLOWER
        at real ingestion batch sizes; the columnar engine that replaced it
        is `native/crdt_batch.cpp`, for which this loop is the semantic
        reference and the fallback.)
        """
        conn = self._conn
        t = self.schema.tables[tbl]
        wins = [False] * len(chs)
        for i, ch in enumerate(chs):
            s = st[ch.pk]
            local_cl = s["cl"]
            if ch.cl < local_cl:
                continue
            win = False
            if ch.cl > local_cl:
                s["cl"] = ch.cl
                rcl[ch.pk] = ch.cl
                # clock rows reset on every causal transition; data cells
                # only reset when the transition is a delete (even cl) —
                # an odd re-create keeps surviving cell values
                s["clock"] = {}
                clr.add(ch.pk)
                ckf[ch.pk] = {SENTINEL: _clock_entry(ch, ch.cl)}
                s["clock"][SENTINEL] = ch.cl
                if ch.cl % 2 == 0:
                    # delete wins: the data row must go (flush deletes run
                    # before ensures, so a later re-create in this same
                    # batch still starts from a fresh row)
                    s["vals"] = {}
                    clf.pop(ch.pk, None)
                    rdel.add(ch.pk)
                    rens.discard(ch.pk)
                    win = True
                else:
                    rens.add(ch.pk)
                    if ch.cid != SENTINEL:
                        clf.setdefault(ch.pk, {})[ch.cid] = ch.val
                        s["vals"][ch.cid] = ch.val
                        ckf[ch.pk][ch.cid] = _clock_entry(
                            ch, ch.col_version
                        )
                        s["clock"][ch.cid] = ch.col_version
                    win = True
            else:
                # equal causal length
                if local_cl % 2 == 0 or ch.cid == SENTINEL:
                    continue
                local_cv = s["clock"].get(ch.cid, 0)
                if ch.col_version < local_cv:
                    continue
                if ch.col_version == local_cv and ch.cid in s["clock"]:
                    # a clock entry for this cid can only exist here if no
                    # causal transition happened in-batch (transitions
                    # reset s["clock"]), so the on-disk value is current
                    # unless an earlier equal-cl win cached it in s["vals"]
                    if ch.cid in s["vals"]:
                        cur = s["vals"][ch.cid]
                    elif s["disk"] is not None and ch.cid in s["disk"]:
                        cur = s["disk"][ch.cid]
                    else:
                        # tie cids are always in the prefetched union; if
                        # that invariant ever breaks, degrade to a per-row
                        # read rather than comparing against a wrong NULL
                        cur = self._current_value(conn, t, ch.pk, ch.cid)
                    if cmp_values(ch.val, cur) <= 0:
                        continue
                rens.add(ch.pk)
                clf.setdefault(ch.pk, {})[ch.cid] = ch.val
                s["vals"][ch.cid] = ch.val
                ckf.setdefault(ch.pk, {})[ch.cid] = _clock_entry(
                    ch, ch.col_version
                )
                s["clock"][ch.cid] = ch.col_version
                win = True
            wins[i] = win
        return wins

    def _merge_table_native(
        self,
        lib,
        tbl: str,
        chs: Sequence[Change],
        st: Dict[bytes, dict],
        rcl: Dict[bytes, int],
        clr: set,
        ckf: Dict[bytes, Dict[str, tuple]],
        clf: Dict[bytes, Dict[str, SqliteValue]],
        rdel: set,
        rens: set,
    ) -> Optional[List[bool]]:
        """Columnar merge of one table's changes through
        `native/crdt_batch.cpp::crdt_merge_batch`; None → caller must run
        the Python reference loop (value out of int64 range, missing
        prefetched tie value, or any native error)."""
        import ctypes
        from array import array

        n = len(chs)
        t = self.schema.tables[tbl]
        col_list = list(t.columns)
        col_idx = {c: k for k, c in enumerate(col_list)}

        pk_list: List[bytes] = []
        pk_idx: Dict[bytes, int] = {}
        for pk in st:
            pk_idx[pk] = len(pk_list)
            pk_list.append(pk)
        n_pks = len(pk_list)

        try:
            # single marshal pass: columnar scalars + the (pk, cid, cv)
            # grouping that decides which values can ever be tie-compared
            a_pk = array("i", bytes(4 * n))
            a_cid = array("i", bytes(4 * n))
            a_cv = array("q", bytes(8 * n))
            a_cl = array("q", bytes(8 * n))
            groups: Dict[tuple, int] = {}
            cand: Dict[bytes, set] = {}
            for i, ch in enumerate(chs):
                pk = ch.pk
                a_pk[i] = pk_idx[pk]
                a_cl[i] = ch.cl
                cid = ch.cid
                if cid == SENTINEL:
                    a_cid[i] = -1
                    continue
                a_cid[i] = col_idx[cid]
                cv = ch.col_version
                a_cv[i] = cv
                key = (pk, cid, cv)
                groups[key] = groups.get(key, 0) + 1
                if st[pk]["clock"].get(cid) == cv:
                    cand.setdefault(pk, set()).add(cid)

            # values reach C lazily: a change's value can only ever be
            # compared if (a) its (pk, cid, col_version) group has 2+
            # members (a later equal-cv change may tie against its cached
            # win), or (b) it ties against the snapshot clock (candidate
            # set). Everything else stays unencoded (VT 0 = absent; the
            # engine returns rc=1 if it ever needs one, falling back to
            # the Python loop).
            vt = bytearray(n)
            vi = array("q", bytes(8 * n))
            vr = array("d", bytes(8 * n))
            voff = array("q", bytes(8 * n))
            vlen = array("q", bytes(8 * n))
            arena = bytearray()
            for i, ch in enumerate(chs):
                cid = ch.cid
                if cid == SENTINEL:
                    continue
                pk = ch.pk
                if (
                    groups[(pk, cid, ch.col_version)] < 2
                    and not (
                        pk in cand and cid in cand[pk]
                        and st[pk]["clock"].get(cid) == ch.col_version
                    )
                ):
                    continue
                _encode_value(ch.val, i, vt, vi, vr, voff, vlen, arena)

            ck_pk = array("i")
            ck_cid = array("i")
            ck_cv = array("q")
            for pk, s in st.items():
                pi = pk_idx[pk]
                for cid, cv in s["clock"].items():
                    ci = col_idx.get(cid)
                    if ci is None:
                        continue  # sentinel / stale column rows
                    ck_pk.append(pi)
                    ck_cid.append(ci)
                    ck_cv.append(cv)
            n_clock = len(ck_pk)

            dk_pk_l: List[int] = []
            dk_cid_l: List[int] = []
            dk_vals: List[SqliteValue] = []
            conn = self._conn
            for pk, cids in cand.items():
                d = st[pk]["disk"]
                for cid in sorted(cids):
                    if d is not None and cid in d:
                        val = d[cid]
                    else:
                        val = self._current_value(conn, t, pk, cid)
                    dk_pk_l.append(pk_idx[pk])
                    dk_cid_l.append(col_idx[cid])
                    dk_vals.append(val)
            n_disk = len(dk_pk_l)
            dk_t = bytearray(n_disk)
            dk_i = (ctypes.c_int64 * n_disk)()
            dk_r = (ctypes.c_double * n_disk)()
            dk_off = (ctypes.c_int64 * n_disk)()
            dk_len = (ctypes.c_int64 * n_disk)()
            dk_arena = bytearray()
            for i, v in enumerate(dk_vals):
                _encode_value(v, i, dk_t, dk_i, dk_r, dk_off, dk_len,
                              dk_arena)

            c_local_cl = (ctypes.c_int64 * n_pks)(
                *[st[pk]["cl"] for pk in pk_list]
            )
            out_win = (ctypes.c_uint8 * n)()
            out_row_cl = (ctypes.c_int64 * n_pks)()
            out_flags = (ctypes.c_uint8 * n_pks)()
            out_sent = (ctypes.c_int32 * n_pks)()
            out_cell_pk = (ctypes.c_int32 * n)()
            out_cell_cid = (ctypes.c_int32 * n)()
            out_cell_idx = (ctypes.c_int32 * n)()
            out_n_cells = ctypes.c_int32(0)
            out_clock_pk = (ctypes.c_int32 * n)()
            out_clock_cid = (ctypes.c_int32 * n)()
            out_clock_idx = (ctypes.c_int32 * n)()
            out_n_clocks = ctypes.c_int32(0)

            # zero-copy views over the array-module buffers
            def u8v(buf, ln):
                return (ctypes.c_uint8 * ln).from_buffer(buf)

            def i32v(arr):
                return (ctypes.c_int32 * len(arr)).from_buffer(arr)

            def i64v(arr):
                return (ctypes.c_int64 * len(arr)).from_buffer(arr)

            def f64v(arr):
                return (ctypes.c_double * len(arr)).from_buffer(arr)

            rc = lib.crdt_merge_batch(
                n, i32v(a_pk), i32v(a_cid), i64v(a_cv), i64v(a_cl),
                u8v(vt, n),
                i64v(vi), f64v(vr), i64v(voff), i64v(vlen), bytes(arena),
                n_pks, c_local_cl,
                n_clock, i32v(ck_pk), i32v(ck_cid), i64v(ck_cv),
                n_disk,
                (ctypes.c_int32 * n_disk)(*dk_pk_l),
                (ctypes.c_int32 * n_disk)(*dk_cid_l),
                u8v(dk_t, n_disk),
                dk_i, dk_r, dk_off, dk_len, bytes(dk_arena),
                out_win, out_row_cl, out_flags, out_sent,
                out_cell_pk, out_cell_cid, out_cell_idx,
                ctypes.byref(out_n_cells),
                out_clock_pk, out_clock_cid, out_clock_idx,
                ctypes.byref(out_n_clocks),
            )
        except (OverflowError, ctypes.ArgumentError, ValueError):
            return None
        if rc != 0:
            if rc != 1:
                log.warning("native merge_batch returned rc=%d; falling "
                            "back to python loop", rc)
            return None

        # -- rebuild the flush plans from the native outputs ---------------
        F_ROWCL, F_CLEARED, F_DELETE, F_ENSURE = 1, 2, 4, 8
        for pi in range(n_pks):
            fl = out_flags[pi]
            if not fl and out_sent[pi] < 0:
                continue
            pk = pk_list[pi]
            if fl & F_ROWCL:
                rcl[pk] = out_row_cl[pi]
            if fl & F_CLEARED:
                clr.add(pk)
            if fl & F_DELETE:
                rdel.add(pk)
            if fl & F_ENSURE:
                rens.add(pk)
            si = out_sent[pi]
            if si >= 0:
                ch = chs[si]
                ckf[pk] = {SENTINEL: _clock_entry(ch, ch.cl)}
        for k in range(out_n_clocks.value):
            pk = pk_list[out_clock_pk[k]]
            cid = col_list[out_clock_cid[k]]
            ch = chs[out_clock_idx[k]]
            ckf.setdefault(pk, {})[cid] = _clock_entry(ch, ch.col_version)
        for k in range(out_n_cells.value):
            pk = pk_list[out_cell_pk[k]]
            cid = col_list[out_cell_cid[k]]
            ch = chs[out_cell_idx[k]]
            clf.setdefault(pk, {})[cid] = ch.val
        return [bool(out_win[i]) for i in range(n)]

    def _flush_batch(
        self,
        by_table: Dict[str, List[Change]],
        row_cl: Dict[str, Dict[bytes, int]],
        cleared: Dict[str, set],
        clock_final: Dict[str, Dict[bytes, Dict[str, tuple]]],
        cell_final: Dict[str, Dict[bytes, Dict[str, SqliteValue]]],
        row_delete: Dict[str, set],
        row_ensure: Dict[str, set],
        impactful: List[Change],
    ) -> List[Change]:
        conn = self._conn
        unpack_cache = self._pk_unpack_cache

        def unpacked(pk: bytes) -> tuple:
            got = unpack_cache.get(pk)
            if got is None:
                got = unpack_cache[pk] = tuple(unpack_columns(pk))
            return got

        for tbl in by_table:
            t = self.schema.tables[tbl]
            rt, ct = _rows_table(tbl), _clock_table(tbl)
            if row_cl[tbl]:
                conn.executemany(
                    f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                    " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                    list(row_cl[tbl].items()),
                )
            if cleared[tbl]:
                conn.executemany(
                    f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?',
                    [(pk, SENTINEL) for pk in cleared[tbl]],
                )
            if row_delete[tbl]:
                where = " AND ".join(f'"{c}" IS ?' for c in t.pk_cols)
                conn.executemany(
                    f'DELETE FROM "{t.name}" WHERE {where}',
                    [unpacked(pk) for pk in row_delete[tbl]],
                )
            if row_ensure[tbl]:
                cols = ", ".join(f'"{c}"' for c in t.pk_cols)
                marks = ", ".join("?" for _ in t.pk_cols)
                conn.executemany(
                    f'INSERT OR IGNORE INTO "{t.name}" ({cols})'
                    f" VALUES ({marks})",
                    [unpacked(pk) for pk in row_ensure[tbl]],
                )
            if cell_final[tbl]:
                # group cell writes by column: one executemany per cid
                where = " AND ".join(f'"{c}" IS ?' for c in t.pk_cols)
                by_cid: Dict[str, List[tuple]] = {}
                for pk, cells in cell_final[tbl].items():
                    for cid, val in cells.items():
                        by_cid.setdefault(cid, []).append(
                            (val, *unpacked(pk))
                        )
                for cid, rows in by_cid.items():
                    conn.executemany(
                        f'UPDATE "{t.name}" SET "{cid}" = ? WHERE {where}',
                        rows,
                    )
            if clock_final[tbl]:
                conn.executemany(
                    f'INSERT INTO "{ct}" (pk, cid, col_version, db_version,'
                    " seq, site_id, ts) VALUES (?,?,?,?,?,?,?)"
                    " ON CONFLICT (pk, cid) DO UPDATE SET"
                    " col_version = excluded.col_version,"
                    " db_version = excluded.db_version,"
                    " seq = excluded.seq, site_id = excluded.site_id,"
                    " ts = excluded.ts",
                    [
                        (pk, cid, cv, dbv, seq, site, ts)
                        for pk, entries in clock_final[tbl].items()
                        for cid, (cv, dbv, seq, site, ts) in entries.items()
                    ],
                )
        return impactful

    def _apply_one(self, ch: Change) -> bool:
        t = self.schema.tables.get(ch.table)
        if t is None:
            # unknown table: drop silently like cr-sqlite (schema lag)
            return False
        if ch.cid != SENTINEL and ch.cid not in t.columns:
            return False
        conn = self._conn
        rt, ct = _rows_table(ch.table), _clock_table(ch.table)
        row = conn.execute(
            f'SELECT cl FROM "{rt}" WHERE pk = ?', (ch.pk,)
        ).fetchone()
        local_cl = row["cl"] if row else 0

        if ch.cl < local_cl:
            return False  # stale causal length: row-level dominance
        if ch.cl > local_cl:
            if ch.cl % 2 == 0:
                # delete wins: drop data row, clear column clocks
                self._delete_row(t, ch.pk)
                conn.execute(
                    f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                    " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                    (ch.pk, ch.cl),
                )
                conn.execute(f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?',
                             (ch.pk, SENTINEL))
                self._upsert_clock(ct, ch, cid=SENTINEL)
                return True
            # (re)create: fresh causal epoch — reset column clocks
            conn.execute(
                f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                (ch.pk, ch.cl),
            )
            conn.execute(
                f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?', (ch.pk, SENTINEL)
            )
            self._ensure_data_row(t, ch.pk)
            self._upsert_clock(ct, ch, cid=SENTINEL)
            if ch.cid != SENTINEL:
                self._set_cell(t, ch.pk, ch.cid, ch.val)
                self._upsert_clock(ct, ch)
            return True

        # equal causal length
        if local_cl % 2 == 0:
            # both deleted; nothing to merge beyond clock freshness
            return False
        if ch.cid == SENTINEL:
            return False  # sentinel at same cl: no-op
        clock = conn.execute(
            f'SELECT col_version FROM "{ct}" WHERE pk = ? AND cid = ?',
            (ch.pk, ch.cid),
        ).fetchone()
        local_cv = clock["col_version"] if clock else 0
        if ch.col_version < local_cv:
            return False
        if ch.col_version == local_cv:
            if clock is None:
                pass  # no local write at all → incoming wins
            else:
                cur = self._current_value(conn, t, ch.pk, ch.cid)
                c = cmp_values(ch.val, cur)
                if c <= 0:
                    return False  # equal values merge; smaller loses
        self._ensure_data_row(t, ch.pk)
        self._set_cell(t, ch.pk, ch.cid, ch.val)
        self._upsert_clock(ct, ch)
        return True

    def _upsert_clock(self, ct: str, ch: Change, cid: Optional[str] = None) -> None:
        self._conn.execute(
            f'INSERT INTO "{ct}" (pk, cid, col_version, db_version, seq,'
            " site_id, ts) VALUES (?,?,?,?,?,?,?)"
            " ON CONFLICT (pk, cid) DO UPDATE SET"
            " col_version = excluded.col_version,"
            " db_version = excluded.db_version, seq = excluded.seq,"
            " site_id = excluded.site_id, ts = excluded.ts",
            (
                ch.pk,
                cid if cid is not None else ch.cid,
                ch.cl if cid is not None else ch.col_version,
                ch.db_version,
                ch.seq,
                ch.site_id,
                ch.ts.ntp64,
            ),
        )

    def _ensure_data_row(self, t, pk: bytes) -> None:
        pk_vals = unpack_columns(pk)
        cols = ", ".join(f'"{c}"' for c in t.pk_cols)
        marks = ", ".join("?" for _ in t.pk_cols)
        self._conn.execute(
            f'INSERT OR IGNORE INTO "{t.name}" ({cols}) VALUES ({marks})', pk_vals
        )

    def _set_cell(self, t, pk: bytes, cid: str, val: SqliteValue) -> None:
        where = " AND ".join(f'"{c}" IS ?' for c in t.pk_cols)
        self._conn.execute(
            f'UPDATE "{t.name}" SET "{cid}" = ? WHERE {where}',
            [val, *unpack_columns(pk)],
        )

    def _delete_row(self, t, pk: bytes) -> None:
        where = " AND ".join(f'"{c}" IS ?' for c in t.pk_cols)
        self._conn.execute(
            f'DELETE FROM "{t.name}" WHERE {where}', unpack_columns(pk)
        )

    # -- buffered partial versions ----------------------------------------

    def buffer_partial_changes(
        self,
        site: ActorId,
        version: int,
        changes: Sequence[Change],
        seqs: Tuple[int, int],
        last_seq: int,
        ts: Timestamp,
    ) -> None:
        """Stash an incomplete version's chunk (process_incomplete_version,
        util.rs:1070-1203)."""
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                for ch in changes:
                    self._conn.execute(
                        "INSERT OR REPLACE INTO __corro_buffered_changes"
                        " VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                        (
                            site.bytes16,
                            version,
                            ch.seq,
                            ch.table,
                            ch.pk,
                            ch.cid,
                            ch.val,
                            ch.col_version,
                            ch.cl,
                            last_seq,
                            ch.ts.ntp64,
                        ),
                    )
                # merge the seq range bookkeeping
                existing = self._conn.execute(
                    "SELECT start_seq, end_seq FROM __corro_seq_bookkeeping"
                    " WHERE site_id = ? AND db_version = ?",
                    (site.bytes16, version),
                ).fetchall()
                rs = RangeSet([(r["start_seq"], r["end_seq"]) for r in existing])
                rs.insert(seqs[0], seqs[1])
                self._conn.execute(
                    "DELETE FROM __corro_seq_bookkeeping"
                    " WHERE site_id = ? AND db_version = ?",
                    (site.bytes16, version),
                )
                for s, e in rs:
                    self._conn.execute(
                        "INSERT INTO __corro_seq_bookkeeping VALUES (?,?,?,?,?,?)",
                        (site.bytes16, version, s, e, last_seq, ts.ntp64),
                    )
                self._conn.execute("COMMIT")
            except BaseException:
                _safe_rollback(self._conn)
                raise

    def take_buffered_version(
        self,
        site: ActorId,
        version: int,
        conn: Optional[sqlite3.Connection] = None,
    ) -> List[Change]:
        """Read a buffered version's rows as Change objects (non-destructive;
        clearing is separate — process_fully_buffered_changes,
        util.rs:552-700)."""
        rows = (conn or self._conn).execute(
            "SELECT * FROM __corro_buffered_changes"
            " WHERE site_id = ? AND db_version = ? ORDER BY seq",
            (site.bytes16, version),
        ).fetchall()
        return [
            Change(
                table=r["tbl"],
                pk=bytes(r["pk"]),
                cid=r["cid"],
                val=r["val"],
                col_version=r["col_version"],
                db_version=version,
                seq=r["seq"],
                site_id=site.bytes16,
                cl=r["cl"],
                ts=Timestamp(r["ts"]),
            )
            for r in rows
        ]

    def clear_buffered_version(self, site: ActorId, version: int) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM __corro_buffered_changes"
                " WHERE site_id = ? AND db_version = ?",
                (site.bytes16, version),
            )
            self._conn.execute(
                "DELETE FROM __corro_seq_bookkeeping"
                " WHERE site_id = ? AND db_version = ?",
                (site.bytes16, version),
            )

    # -- bookkeeping persistence (GapStore) -------------------------------

    def gap_store(self) -> GapStore:
        return _SqliteGapStore(self._conn)

    def load_booked_versions(self, actor_id: ActorId) -> BookedVersions:
        """Rebuild bookkeeping for an actor from durable state
        (BookedVersions::from_conn, agent.rs:1293-1362)."""
        bv = BookedVersions(actor_id)
        bv.max = self.db_version_for(actor_id) or None
        for r in self._conn.execute(
            "SELECT db_version, start_seq, end_seq, last_seq, ts"
            " FROM __corro_seq_bookkeeping WHERE site_id = ?",
            (actor_id.bytes16,),
        ):
            bv.insert_partial(
                r["db_version"],
                PartialVersion(
                    seqs=RangeSet([(r["start_seq"], r["end_seq"])]),
                    last_seq=r["last_seq"],
                    ts=Timestamp(r["ts"]),
                ),
            )
        for r in self._conn.execute(
            "SELECT start, end FROM __corro_bookkeeping_gaps WHERE actor_id = ?",
            (actor_id.bytes16,),
        ):
            bv.needed.insert(r["start"], r["end"])
        return bv

    def present_versions(self, actor_id: ActorId) -> RangeSet:
        """Distinct db_versions this actor's changes actually occupy in the
        clock tables — ground truth for gap reconciliation (the admin
        ReconcileGaps repair, `klukai/src/admin.rs` Command::ReconcileGaps
        rebuilds `__corro_bookkeeping_gaps` against `crsql_changes`)."""
        present = RangeSet()
        with self._lock:
            for t in self.schema.tables:
                for r in self._conn.execute(
                    f'SELECT DISTINCT db_version FROM "{_clock_table(t)}"'
                    " WHERE site_id = ?",
                    (actor_id.bytes16,),
                ):
                    v = r["db_version"]
                    present.insert(v, v)
        return present

    def rewrite_gaps(self, actor_id: ActorId, needed: RangeSet) -> None:
        """Replace the persisted gap rows for an actor wholesale."""
        with self._lock:
            self._conn.execute(
                "DELETE FROM __corro_bookkeeping_gaps WHERE actor_id = ?",
                (actor_id.bytes16,),
            )
            self._conn.executemany(
                "INSERT INTO __corro_bookkeeping_gaps (actor_id, start,"
                ' "end") VALUES (?, ?, ?)',
                [(actor_id.bytes16, s, e) for s, e in needed],
            )
            self._conn.commit()

    # -- member-state persistence (__corro_members) ------------------------

    def update_member_rows(
        self,
        upserts: Sequence[Tuple[bytes, str, str, Optional[float], int]],
        deletes: Sequence[bytes],
    ) -> None:
        """Apply one member-state diff: rows are (actor_id, address,
        foca_state_json, rtt_min, updated_at) (broadcast/mod.rs:814-949)."""
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                if upserts:
                    self._conn.executemany(
                        "INSERT INTO __corro_members (actor_id, address,"
                        " foca_state, rtt_min, updated_at)"
                        " VALUES (?,?,?,?,?)"
                        " ON CONFLICT (actor_id) DO UPDATE SET"
                        " address = excluded.address,"
                        " foca_state = excluded.foca_state,"
                        " rtt_min = coalesce(excluded.rtt_min, rtt_min),"
                        " updated_at = excluded.updated_at",
                        list(upserts),
                    )
                if deletes:
                    self._conn.executemany(
                        "DELETE FROM __corro_members WHERE actor_id = ?",
                        [(d,) for d in deletes],
                    )
                self._conn.execute("COMMIT")
            except BaseException:
                _safe_rollback(self._conn)
                raise

    def member_state_rows(self) -> List[str]:
        """Persisted foca_state JSON blobs (util.rs:74-111 load)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT foca_state FROM __corro_members"
                " WHERE foca_state IS NOT NULL"
            ).fetchall()
        return [r["foca_state"] for r in rows]

    def random_member_addresses(self, count: int) -> List[str]:
        """Random persisted member addresses (bootstrap.rs:29-50)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT address FROM __corro_members"
                " ORDER BY RANDOM() LIMIT ?",
                (count,),
            ).fetchall()
        return [r["address"] for r in rows]

    def booked_actor_ids(self) -> List[ActorId]:
        """All sites we have any state for (bookie warm-up,
        run_root.rs:136-197). Takes the store lock: callers may run off the
        event loop (admin reconcile in a worker thread) and must not read
        the shared connection inside another thread's open transaction."""
        with self._lock:
            return self._booked_actor_ids_locked()

    def _booked_actor_ids_locked(self) -> List[ActorId]:
        ids = {
            bytes(r["site_id"])
            for r in self._conn.execute("SELECT site_id FROM __crdt_db_versions")
        }
        ids |= {
            bytes(r["site_id"])
            for r in self._conn.execute(
                "SELECT DISTINCT site_id FROM __corro_seq_bookkeeping"
            )
        }
        ids |= {
            bytes(r["actor_id"])
            for r in self._conn.execute(
                "SELECT DISTINCT actor_id FROM __corro_bookkeeping_gaps"
            )
        }
        return [ActorId(b) for b in sorted(ids)]


class WriteTx:
    """A local write transaction; captures triggers' pending log and turns
    it into broadcastable changes on commit (the `/v1/transactions` path:
    make_broadcastable_changes + insert_local_changes,
    `api/public/mod.rs:57-258`, change.rs:188)."""

    def __init__(
        self,
        store: CrdtStore,
        ts: Timestamp,
        nested: bool = False,
        savepoint: bool = True,
    ):
        self.store = store
        self.ts = ts
        self._done = False
        # nested=True: a sub-transaction of a group commit — the caller
        # (CrdtStore.group_tx leader) holds the store lock and the outer
        # BEGIN IMMEDIATE; this tx is a SAVEPOINT so a failed writer
        # rolls back alone without aborting its batchmates.
        # savepoint=False (nested only, r15): a SOLO group batch skips
        # the savepoint round-trip — with one writer there are no
        # batchmates to shield, and a failure aborts the whole group tx
        self._nested = nested
        self._savepoint = savepoint
        # r15 direct capture state: `_captured` is the in-memory
        # pending stream — (tbl, pk, cid, val) tuples in statement
        # order; `_pending_dirty` marks trigger-captured rows sitting
        # in `__crdt_pending` that must be drained (in rowseq order)
        # into `_captured` before anything is appended after them;
        # `_capture_off` shadows the in-tx `__crdt_ctx.capture` value
        # so toggles only run on transitions (restored before COMMIT —
        # a rollback restores the committed 1 on its own)
        self._direct = store.direct_capture and _capture_engine() == "direct"
        self._captured: List[tuple] = []
        self._pending_dirty = False
        self._capture_off = False
        # capture telemetry, flushed ONCE per commit (registry calls
        # are locked — too heavy for the per-statement hot path)
        self._n_direct = 0
        self._n_trigger = 0
        self._n_fallback = 0
        self._capture_secs = 0.0

    def __enter__(self) -> "WriteTx":
        self.store._lock.acquire()
        self.conn = self.store._conn
        if self._nested:
            if self._savepoint:
                self.conn.execute("SAVEPOINT __corro_wtx")
        else:
            self.conn.execute("BEGIN IMMEDIATE")
        return self

    def execute(self, sql: str, params=()) -> int:
        """Run one statement; returns its faithful rows_affected.

        sqlite3 reports -1 for statement classes that have no row count
        (DDL, SELECT) — report those as 0 rather than letting -1 leak
        into summed ExecResult.rows_affected; genuine DML counts
        (including a DELETE/UPDATE matching nothing → 0) pass through
        untouched.  `params` may be a sequence or a dict (named
        parameters), so the /v1/transactions named-param path shares
        this trace/timing point.

        r15: recognized INSERT/UPDATE/DELETE shapes on CRDT-tracked
        tables capture their written cells directly in memory
        (store/capture.py) instead of taking the trigger →
        `__crdt_pending` round-trip; raw/unrecognized SQL keeps the
        trigger path, and the two streams merge in statement order."""
        if self.store.chaos is not None:
            # r18 sick-disk injection: a transient SQLITE_BUSY here
            # aborts THIS writer's sub-transaction only (savepoint
            # isolation in a group commit)
            self.store.chaos.on_statement()
        if self._direct:
            shape = self.store.capture_shape(sql)
            if shape is not None:
                n = self._execute_captured(sql, shape, params, None)
                if n is not None:
                    return n
        return self._execute_raw(sql, params)

    def executemany(self, sql: str, rows: Sequence) -> int:
        """Bulk DML: one prepared statement stepped over many parameter
        rows (the write-side counterpart of the r10 matcher's
        executemany flushes — bulk ingest writers should prefer this
        over a Python loop of `execute`).  Returns total rows affected.

        On the direct-capture path the whole call runs inside a
        SAVEPOINT: a row that fails mid-batch rolls the batch back
        before raising, so the in-memory capture never diverges from
        partially-applied statements."""
        if self.store.chaos is not None:
            self.store.chaos.on_statement()
        rows = list(rows)
        if self._direct and rows:
            shape = self.store.capture_shape(sql)
            if shape is not None:
                n = self._execute_captured(sql, shape, None, rows)
                if n is not None:
                    return n
        return self._executemany_raw(sql, rows)

    # -- capture plumbing (r15) ----------------------------------------

    def _execute_raw(self, sql: str, params) -> int:
        """The pre-r15 statement path: AFTER triggers log written cells
        to `__crdt_pending`."""
        from corrosion_tpu.runtime.trace import timed_query

        self._ensure_capture(True)
        with timed_query(sql, shape="raw"):
            cur = self.conn.execute(
                sql, params if isinstance(params, dict) else tuple(params)
            )
        self._pending_dirty = True
        self._n_trigger += 1
        return cur.rowcount if cur.rowcount >= 0 else 0

    def _executemany_raw(self, sql: str, rows: list) -> int:
        from corrosion_tpu.runtime.trace import timed_query

        self._ensure_capture(True)
        with timed_query(sql, shape="raw"):
            cur = self.conn.executemany(sql, rows)
        self._pending_dirty = True
        self._n_trigger += 1
        return cur.rowcount if cur.rowcount >= 0 else 0

    def _flush_capture_metrics(self) -> None:
        """One registry round per commit for the per-statement capture
        counters (`corro.write.capture.{direct,trigger,fallback}.total`
        + `corro.write.capture.seconds`)."""
        from corrosion_tpu.runtime.metrics import METRICS

        if self._n_direct:
            METRICS.counter("corro.write.capture.direct.total").inc(
                self._n_direct
            )
            METRICS.histogram("corro.write.capture.seconds").observe(
                self._capture_secs
            )
        if self._n_trigger:
            METRICS.counter("corro.write.capture.trigger.total").inc(
                self._n_trigger
            )
        if self._n_fallback:
            METRICS.counter("corro.write.capture.fallback.total").inc(
                self._n_fallback
            )
        self._n_direct = self._n_trigger = self._n_fallback = 0
        self._capture_secs = 0.0

    def _ensure_capture(self, on: bool) -> None:
        """Transition the trigger gate (`CrdtStore._capture_flag`, read
        by the triggers' corro_capture_on()) only when needed — a plain
        Python store, unconditionally restored to ON in __exit__."""
        if self._capture_off == (not on):
            return
        self.store._capture_flag[0] = 1 if on else 0
        self._capture_off = not on

    def _drain_trigger_rows(self) -> None:
        """Move trigger-logged pending rows into the in-memory stream.
        Invariant: rows in `__crdt_pending` always postdate the last
        drained/direct append, so extending at the tail preserves the
        exact rowseq order a pure trigger run would have produced."""
        if not self._pending_dirty:
            return
        conn = self.conn
        rows = conn.execute(
            "SELECT tbl, pk, cid, val FROM __crdt_pending ORDER BY rowseq"
        ).fetchall()
        if rows:
            self._captured.extend(
                (r[0], bytes(r[1]), r[2], r[3]) for r in rows
            )
            conn.execute("DELETE FROM __crdt_pending")
        self._pending_dirty = False

    def _take_pending(self) -> list:
        """The merged capture stream for finalize, leaving the tx clean."""
        self._drain_trigger_rows()
        out, self._captured = self._captured, []
        return out

    def _preimage(
        self, meta, pk_tuples: list, cols: list
    ) -> Dict[tuple, dict]:
        """Current values of `cols` for the given pk tuples (absent key
        = no such row) — ONE chunked read replacing the per-cell state
        the triggers would have materialized."""
        conn = self.conn
        uniq = list(dict.fromkeys(pk_tuples))
        sel = ", ".join(f'"{c}"' for c in (*meta.pk_cols, *cols))
        npk = len(meta.pk_cols)
        out: Dict[tuple, dict] = {}
        if npk == 1:
            step = 900
            col = meta.pk_cols[0]
            for i in range(0, len(uniq), step):
                chunk = uniq[i : i + step]
                marks = ",".join("?" * len(chunk))
                for r in conn.execute(
                    f'SELECT {sel} FROM "{meta.name}" WHERE "{col}"'
                    f" IN ({marks})",
                    [u[0] for u in chunk],
                ):
                    out[(r[0],)] = {
                        c: r[npk + j] for j, c in enumerate(cols)
                    }
        else:
            step = max(1, 800 // npk)
            pk_sel = ",".join(f'"{c}"' for c in meta.pk_cols)
            row_marks = "(" + ",".join("?" * npk) + ")"
            for i in range(0, len(uniq), step):
                chunk = uniq[i : i + step]
                values = ",".join([row_marks] * len(chunk))
                for r in conn.execute(
                    f'SELECT {sel} FROM "{meta.name}"'
                    f" WHERE ({pk_sel}) IN (VALUES {values})",
                    [v for u in chunk for v in u],
                ):
                    out[tuple(r[k] for k in range(npk))] = {
                        c: r[npk + j] for j, c in enumerate(cols)
                    }
        return out

    def _execute_captured(
        self, sql: str, shape, params, many_rows: Optional[list]
    ) -> Optional[int]:
        """Run one recognized statement with triggers gated off and the
        written cells captured in memory.  None → value-level fallback:
        the statement has NOT run and the caller takes the trigger
        path.  Capture metrics accumulate on the tx and flush once per
        commit (`_flush_capture_metrics`) — this runs per statement on
        the hottest write path."""
        import time as _time

        from corrosion_tpu.runtime.trace import timed_query

        t0 = _time.monotonic()
        cap = _capture
        meta = shape.meta
        rows = many_rows if many_rows is not None else [params]
        kind = shape.kind
        if kind == "insert":
            plans = cap.plan_insert_rows(shape, rows, many_rows is None)
        elif kind == "update":
            plans = []
            for p in rows:
                plan = cap.plan_update_row(shape, p)
                if plan is None:
                    plans = None
                    break
                plans.append(plan)
        else:
            plans = []
            for p in rows:
                plan = cap.plan_delete_row(shape, p)
                if plan is None:
                    plans = None
                    break
                plans.append(plan)
        if plans is None:
            self._n_fallback += 1
            return None

        # pre-image: ONE read feeding existence + IS-NOT comparisons
        conn = self.conn
        live: Dict[tuple, Optional[dict]] = {}
        conflicty = kind == "insert" and shape.conflict in (
            "ignore", "nothing", "upsert",
        )
        if kind == "update":
            cols = [c for c, _ in shape.set_slots]
            live = self._preimage(meta, [p[0] for p in plans], cols)
        elif conflicty:
            cols = sorted({c for c, _ in shape.upsert_set})
            live = self._preimage(meta, [p[0] for p in plans], cols)
        elif kind == "delete" and many_rows is not None:
            live = self._preimage(meta, plans, [])

        self._ensure_capture(False)
        savepoint = many_rows is not None and len(rows) > 1
        if savepoint:
            conn.execute("SAVEPOINT __corro_cap")
            try:
                with timed_query(sql, shape=shape.stmt_key):
                    cur = conn.executemany(sql, rows)
            except BaseException:
                conn.execute("ROLLBACK TO __corro_cap")
                conn.execute("RELEASE SAVEPOINT __corro_cap")
                raise
            conn.execute("RELEASE SAVEPOINT __corro_cap")
        elif many_rows is not None:
            with timed_query(sql, shape=shape.stmt_key):
                cur = conn.executemany(sql, rows)
        else:
            with timed_query(sql, shape=shape.stmt_key):
                cur = conn.execute(
                    sql,
                    params if isinstance(params, dict) else tuple(params),
                )

        # emit the trigger-equivalent stream, in statement order
        if self._pending_dirty:
            self._drain_trigger_rows()
        captured = self._captured
        tbl = meta.name
        pack = pack_columns
        if kind == "insert":
            for pk_vals, cells, skip, assigns, assigns_pend in plans:
                if skip:
                    continue
                if pk_vals is None:
                    pk_vals = (cur.lastrowid,)
                if conflicty:
                    old = live.get(pk_vals)
                    if old is not None:
                        if shape.conflict == "upsert":
                            pk = pack(list(pk_vals))
                            for cid, _sv in cap._cells_update(
                                meta, old, assigns
                            ):
                                captured.append(
                                    (tbl, pk, cid, assigns_pend[cid])
                                )
                            old.update(assigns)
                        # ignore / nothing: the row was silently skipped
                        continue
                    # later rows of this batch now conflict against
                    # this fresh row: its cell values (pending domain —
                    # `values_distinct` compares int/real numerically,
                    # so the integral-float munge cannot flip a verdict)
                    live[pk_vals] = {
                        cid: v for cid, v in cells if cid != SENTINEL
                    }
                pk = pack(list(pk_vals))
                captured.extend((tbl, pk, cid, v) for cid, v in cells)
        elif kind == "update":
            for pk_vals, new, new_pend in plans:
                old = live.get(pk_vals)
                if old is None:
                    continue  # no row matched the pk
                cells = cap._cells_update(meta, old, new)
                if cells:
                    pk = pack(list(pk_vals))
                    for cid, _sv in cells:
                        captured.append((tbl, pk, cid, new_pend[cid]))
                old.update(new)
        else:  # delete
            if many_rows is None:
                if cur.rowcount >= 1:
                    pk = pack(list(plans[0]))
                    for cid, val in cap._cells_delete(meta):
                        captured.append((tbl, pk, cid, val))
            else:
                for pk_vals in plans:
                    if live.pop(pk_vals, None) is not None:
                        pk = pack(list(pk_vals))
                        for cid, val in cap._cells_delete(meta):
                            captured.append((tbl, pk, cid, val))

        self._n_direct += 1
        self._capture_secs += _time.monotonic() - t0
        return cur.rowcount if cur.rowcount >= 0 else 0

    def commit(self) -> Tuple[List[Change], int, int]:
        """Finalize: assign db_version/seqs, write clocks, return
        (changes, db_version, last_seq). db_version == 0 → no changes."""
        import time as _time

        from corrosion_tpu.runtime.metrics import METRICS

        conn = self.conn
        try:
            self._ensure_capture(True)
            self._flush_capture_metrics()
            pending = self._take_pending()
            t0 = _time.monotonic()
            changes = self._finalize_pending(pending)
            if pending:
                METRICS.histogram("corro.write.finalize.seconds").observe(
                    _time.monotonic() - t0
                )
            if self._nested:
                if self._savepoint:
                    conn.execute("RELEASE SAVEPOINT __corro_wtx")
            else:
                tc0 = _time.monotonic()
                if self.store.chaos is not None:
                    # r18 slow/sick-disk injection on the solo
                    # (group-commit-off) path — the group path's hook
                    # lives in group_tx
                    self.store.chaos.on_commit()
                conn.execute("COMMIT")
                self.store.last_flush_secs = _time.monotonic() - tc0
                _observe_commit_flush(self.store.last_flush_secs)
            self._done = True
            if changes:
                db_version = changes[0].db_version
                return changes, db_version, changes[-1].seq
            return [], 0, 0
        except BaseException:
            if self._nested:
                self._rollback_nested()
            else:
                _safe_rollback(conn)
                self.store._dv_cache.clear()  # bump may have rolled back
            self._done = True
            raise

    def commit_deferred(self) -> list:
        """Group-commit half-commit (nested mode only): capture + clear
        this sub-tx's pending log and release the savepoint WITHOUT
        finalizing — the leader finalizes the whole batch in one
        vectorized pass (`CrdtStore.finalize_group`), so the batch pays
        one probe/flush round instead of one per writer."""
        conn = self.conn
        try:
            self._ensure_capture(True)
            self._flush_capture_metrics()
            pending = self._take_pending()
            if self._savepoint:
                conn.execute("RELEASE SAVEPOINT __corro_wtx")
            self._done = True
            return pending
        except BaseException:
            self._rollback_nested()
            self._done = True
            raise

    def _rollback_nested(self) -> None:
        """Undo this sub-transaction only; the outer group tx lives on.
        If the OUTER transaction was already rolled back (interrupt),
        the savepoint is gone with it — nothing left to undo.  A
        savepoint-free solo sub-tx has nothing local to undo either:
        its failure propagates and aborts the whole group tx."""
        if not self._savepoint:
            return
        try:
            self.conn.execute("ROLLBACK TO __corro_wtx")
            self.conn.execute("RELEASE SAVEPOINT __corro_wtx")
        except sqlite3.OperationalError as e:
            log.debug("nested rollback raced outer rollback: %s", e)

    def rollback(self) -> None:
        if not self._done:
            if self._nested:
                self._rollback_nested()
            else:
                _safe_rollback(self.conn)
            self._done = True

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            if not self._done:
                if exc_type is None:
                    self.commit()
                else:
                    self.rollback()
        finally:
            # the capture gate is process state, not tx state: whatever
            # happened above, triggers must be live for the next writer
            self.store._capture_flag[0] = 1
            self.store._lock.release()
        return False

    def _finalize_pending(self, pending) -> List[Change]:
        if not pending:
            return []
        if _finalize_engine() == "percell":
            return self._finalize_pending_percell(pending)
        return self._finalize_pending_vector(pending)

    def _finalize_pending_vector(self, pending) -> List[Change]:
        """Vectorized finalize (r14): the `_apply_batch` shape on the
        local-commit side — one item's worth of `finalize_group`.
        Semantics are pinned byte/clock-identical to
        `_finalize_pending_percell` by tests/test_finalize_batch.py
        (randomized equivalence)."""
        changes, _dv, _ls = self.store.finalize_group(
            [(pending, self.ts)]
        )[0]
        return changes

    def _finalize_pending_percell(self, pending) -> List[Change]:
        """Per-cell reference finalize: one SELECT/upsert round-trip per
        pending cell.  The semantic reference the vectorized path is
        pinned against — do not optimize this loop."""
        store = self.store
        conn = self.conn
        site = store.site_id
        db_version = store.db_version_for(site) + 1

        # Dedupe cells: last write in the tx wins; deletes ('-1X') override
        # the row's other pending entries for sentinel handling.
        cells: Dict[Tuple[str, bytes, str], SqliteValue] = {}
        order: List[Tuple[str, bytes, str]] = []
        deleted_rows: Dict[Tuple[str, bytes], bool] = {}
        created_rows: Dict[Tuple[str, bytes], bool] = {}
        for r in pending:
            tbl, pk, cid, val = r
            if cid == SENTINEL + "X":  # delete marker from the del trigger
                deleted_rows[(tbl, pk)] = True
                created_rows.pop((tbl, pk), None)
                # drop any pending column writes for the row
                for key in [k for k in cells if k[0] == tbl and k[1] == pk]:
                    del cells[key]
                    order.remove(key)
                continue
            if cid == SENTINEL:
                created_rows[(tbl, pk)] = True
                deleted_rows.pop((tbl, pk), None)
            key = (tbl, pk, cid)
            if key not in cells:
                order.append(key)
            cells[key] = val

        changes: List[Change] = []
        seq = 0

        def emit(tbl: str, pk: bytes, cid: str, val, col_version: int, cl: int):
            nonlocal seq
            changes.append(
                Change(
                    table=tbl,
                    pk=pk,
                    cid=cid,
                    val=val,
                    col_version=col_version,
                    db_version=db_version,
                    seq=seq,
                    site_id=site.bytes16,
                    cl=cl,
                    ts=self.ts,
                )
            )
            seq += 1

        # deletes first: sentinel change with bumped-even cl
        for (tbl, pk) in deleted_rows:
            rt, ct = _rows_table(tbl), _clock_table(tbl)
            row = conn.execute(
                f'SELECT cl FROM "{rt}" WHERE pk = ?', (pk,)
            ).fetchone()
            cl = (row["cl"] if row else 1) + 1
            if cl % 2 == 1:
                cl += 1  # already deleted? keep even
            conn.execute(
                f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                (pk, cl),
            )
            conn.execute(
                f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?', (pk, SENTINEL)
            )
            emit(tbl, pk, SENTINEL, None, cl, cl)
            store._upsert_clock(ct, changes[-1])

        # creations/updates
        for key in order:
            tbl, pk, cid = key
            rt, ct = _rows_table(tbl), _clock_table(tbl)
            rrow = conn.execute(
                f'SELECT cl FROM "{rt}" WHERE pk = ?', (pk,)
            ).fetchone()
            if cid == SENTINEL:
                # row creation (or resurrection)
                prev_cl = rrow["cl"] if rrow else 0
                cl = prev_cl + 1 if prev_cl % 2 == 0 else prev_cl
                if rrow is None or prev_cl % 2 == 0:
                    conn.execute(
                        f'INSERT INTO "{rt}" (pk, cl) VALUES (?, ?)'
                        " ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
                        (pk, cl),
                    )
                    if prev_cl % 2 == 0 and prev_cl > 0:
                        # resurrection: reset column clocks
                        conn.execute(
                            f'DELETE FROM "{ct}" WHERE pk = ? AND cid != ?',
                            (pk, SENTINEL),
                        )
                    emit(tbl, pk, SENTINEL, None, cl, cl)
                    store._upsert_clock(ct, changes[-1])
                continue
            # column write on a (now) live row
            cl = rrow["cl"] if rrow else 1
            crow = conn.execute(
                f'SELECT col_version FROM "{ct}" WHERE pk = ? AND cid = ?',
                (pk, cid),
            ).fetchone()
            col_version = (crow["col_version"] if crow else 0) + 1
            emit(tbl, pk, cid, cells[key], col_version, cl)
            store._upsert_clock(ct, changes[-1])

        if changes:
            store._bump_db_version(site, db_version)
            store.record_last_seq(site, db_version, changes[-1].seq)
        return changes


class _SqliteGapStore:
    def __init__(self, conn: sqlite3.Connection):
        self._conn = conn

    def delete_gap(self, actor_id: ActorId, start: int, end: int) -> None:
        self._conn.execute(
            "DELETE FROM __corro_bookkeeping_gaps"
            " WHERE actor_id = ? AND start = ? AND end = ?",
            (actor_id.bytes16, start, end),
        )

    def insert_gap(self, actor_id: ActorId, start: int, end: int) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO __corro_bookkeeping_gaps VALUES (?, ?, ?)",
            (actor_id.bytes16, start, end),
        )


def _sql_pack(*args) -> bytes:
    return pack_columns(list(args))

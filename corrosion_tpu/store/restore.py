"""Backup + live restore of the store database under real SQLite file locks.

Counterparts:
  - `corrosion backup` (`klukai/src/main.rs:157-223`): `VACUUM INTO`, then
    scrub per-node state from the copy. The reference also rewrites the
    cr-sqlite site-id *ordinal* (its clock tables intern site ids); our
    clock tables store the 16-byte site id directly, so attribution
    survives a backup/restore with no rewrite.
  - `sqlite3_restore` (`klukai-types/src/sqlite3_restore.rs:57,152`):
    byte-range fcntl locks on SQLite's PENDING/RESERVED/SHARED lock bytes
    plus the WAL-shm lock bytes, so the database file can be swapped out
    from under a running process without corruption.
  - `corrosion restore` (`klukai/src/main.rs:224-330`): refuses when an
    agent is live (admin ping), optionally re-pins the self site id, then
    byte-copies under the full lock set.
"""

from __future__ import annotations

import fcntl
import os
import shutil
import sqlite3
import struct
import time
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

# Database file lock bytes (sqlite3_restore.rs:16-29)
PENDING = 0x40000000
RESERVED = 0x40000001
SHARED_FIRST = 0x40000002
SHARED_SIZE = 510

# SHM file lock bytes: WRITE..READ4 = 120..127, DMS = 128. Every live WAL
# connection holds a SHARED lock on DMS for its lifetime, so DMS must be
# taken shared, not exclusive, or locking against a live process always
# times out (sqlite3_restore.rs:185 takes a read lock there for the same
# reason).
SHM_FIRST = 120
SHM_COUNT = 8
SHM_DMS = 128
# A zeroed shm header (first 136 bytes: 2×48-byte WalIndexHdr + 40-byte
# WalCkptInfo) forces the next reader to re-run recovery
# (sqlite3_restore.rs:113-114).
SHM_HEADER_SIZE = 136


class RestoreError(Exception):
    pass


class LockTimedOut(RestoreError):
    pass


def _try_wrlock(fd: int, start: int, length: int) -> bool:
    try:
        fcntl.lockf(fd, fcntl.LOCK_EX | fcntl.LOCK_NB, length, start, os.SEEK_SET)
        return True
    except (BlockingIOError, PermissionError):
        return False


def _try_rdlock(fd: int, start: int, length: int) -> bool:
    try:
        fcntl.lockf(fd, fcntl.LOCK_SH | fcntl.LOCK_NB, length, start, os.SEEK_SET)
        return True
    except (BlockingIOError, PermissionError):
        return False


class _HeldLocks:
    def __init__(self):
        self.fds: List[int] = []

    def release(self) -> None:
        for fd in self.fds:
            try:
                fcntl.lockf(fd, fcntl.LOCK_UN, 0, 0, os.SEEK_SET)
            except OSError:
                pass
            try:
                os.close(fd)
            except OSError:
                pass
        self.fds = []


def lock_all(db_path: str, timeout: float = 30.0) -> _HeldLocks:
    """Exclusive byte-range locks on the db file's PENDING/RESERVED/SHARED
    bytes and all WAL-shm lock bytes — equivalent to holding every SQLite
    lock, so no reader or writer can proceed (sqlite3_restore.rs lock_all).
    Returns a handle whose .release() drops everything."""
    held = _HeldLocks()
    deadline = time.monotonic() + timeout

    def acquire(path: str, ranges, shared=()) -> None:
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        held.fds.append(fd)
        for start, length, trylock in [
            (s, l, _try_wrlock) for s, l in ranges
        ] + [(s, l, _try_rdlock) for s, l in shared]:
            while not trylock(fd, start, length):
                if time.monotonic() > deadline:
                    held.release()
                    raise LockTimedOut(
                        f"lock on {path} bytes {start}+{length} timed out"
                    )
                time.sleep(0.05)

    try:
        acquire(
            db_path,
            [
                (PENDING, 1),
                (RESERVED, 1),
                (SHARED_FIRST, SHARED_SIZE),
            ],
        )
        shm = db_path + "-shm"
        if os.path.exists(shm):
            acquire(
                shm,
                [(SHM_FIRST, SHM_COUNT)],
                shared=[(SHM_DMS, 1)],
            )
    except BaseException:
        held.release()
        raise
    return held


@dataclass
class Restored:
    old_len: int
    new_len: int
    is_wal: bool


def _is_wal(db_path: str) -> bool:
    """SQLite header bytes 18/19 are the read/write format: 2 = WAL."""
    with open(db_path, "rb") as f:
        hdr = f.read(20)
    if len(hdr) < 20:
        raise RestoreError(f"header read too short ({len(hdr)} bytes)")
    read_fmt, write_fmt = hdr[18], hdr[19]
    if read_fmt != write_fmt:
        raise RestoreError(
            f"read/write format mismatch: {read_fmt} != {write_fmt}"
        )
    return read_fmt == 2


def restore(src: str, dst: str, timeout: float = 30.0) -> Restored:
    """Copy `src` over `dst` while holding every SQLite lock on `dst`,
    then drop stale -wal/-shm files so the next reader starts clean
    (sqlite3_restore.rs:57-150)."""
    old_len = os.path.getsize(dst) if os.path.exists(dst) else 0
    locks = lock_all(dst, timeout)
    try:
        is_wal = _is_wal(src)
        tmp = dst + ".restore-tmp"
        shutil.copyfile(src, tmp)
        expected = os.path.getsize(src)
        actual = os.path.getsize(tmp)
        if expected != actual:
            os.unlink(tmp)
            raise RestoreError(
                f"inconsistent copy: expected {expected}, got {actual}"
            )
        os.replace(tmp, dst)
        # Live WAL connections keep fds/mappings to the old -wal/-shm
        # inodes, so neither file may be unlinked (a survivor would rebuild
        # the shared shm index from a wal inode new connections can't see).
        # Instead truncate the wal in place and zero the shm header: every
        # connection, old or new, then agrees on an empty wal and re-runs
        # recovery on next use (sqlite3_restore.rs:113-114).
        wal = dst + "-wal"
        if os.path.exists(wal):
            with open(wal, "r+b") as f:
                f.truncate(0)
        shm = dst + "-shm"
        if os.path.exists(shm):
            with open(shm, "r+b") as f:
                f.write(b"\x00" * SHM_HEADER_SIZE)
        return Restored(old_len=old_len, new_len=actual, is_wal=is_wal)
    finally:
        locks.release()


def backup(db_path: str, out_path: str) -> None:
    """`VACUUM INTO` + scrub per-node state from the copy
    (main.rs:157-223). The copy keeps all CRDT clocks and bookkeeping —
    those are cluster state — but drops member snapshots and consul
    bookkeeping, which are per-process."""
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    if os.path.exists(out_path):
        raise RestoreError(f"backup target exists: {out_path}")
    conn = sqlite3.connect(db_path)
    try:
        conn.execute("VACUUM INTO ?", (out_path,))
    finally:
        conn.close()

    copy = sqlite3.connect(out_path)
    try:
        copy.execute("DELETE FROM __corro_members")
        for tbl in ("__corro_consul_services", "__corro_consul_checks"):
            try:
                copy.execute(f"DROP TABLE {tbl}")
            except sqlite3.OperationalError:
                pass
        copy.commit()
        copy.execute("PRAGMA journal_mode = WAL")
        copy.execute("PRAGMA wal_checkpoint(TRUNCATE)")
    finally:
        copy.close()


def set_self_site_id(db_path: str, site_id_hex: str) -> None:
    """Re-pin the restored database's self identity (`corrosion restore
    --self-actor-id`, main.rs:224-330 site-id swap)."""
    import uuid

    blob = uuid.UUID(site_id_hex).bytes
    conn = sqlite3.connect(db_path)
    try:
        conn.execute("UPDATE __crdt_site SET site_id = ? WHERE id = 1", (blob,))
        conn.commit()
    finally:
        conn.close()

"""CRDT storage: schema engine, sqlite-backed store, version bookkeeping."""

from corrosion_tpu.store.bookkeeping import (
    PartialVersion,
    BookedVersions,
    VersionsSnapshot,
    Booked,
    Bookie,
)

__all__ = [
    "PartialVersion",
    "BookedVersions",
    "VersionsSnapshot",
    "Booked",
    "Bookie",
]

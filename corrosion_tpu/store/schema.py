"""Schema engine: parse, constrain, diff, and apply CRR table schemas.

Counterpart of `klukai-types/src/schema.rs`. The reference parses SQL with
sqlite3-parser; we let SQLite itself parse by applying the DDL to a scratch
in-memory database and introspecting pragmas — same accepted syntax as the
storage engine, zero extra dependencies.

Constraints on CRR tables (schema.rs:115-172):
  - every table needs a primary key; no PK expressions
  - no UNIQUE indexes / unique column constraints (other than the PK)
  - no foreign keys
  - NOT NULL non-pk columns must have a DEFAULT
`apply_schema` (schema.rs:285-667) diffs old vs new: creates new tables,
adds columns, creates/drops/replaces indexes; destructive ops (dropping
tables/columns) are refused.
"""

from __future__ import annotations

import re
import sqlite3
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class SchemaError(Exception):
    pass


@dataclass
class Column:
    name: str
    sql_type: str
    nullable: bool
    default: Optional[str]  # raw SQL default expression text
    primary_key: bool
    pk_order: int = 0  # 1-based position within the pk, 0 if not pk


@dataclass
class Table:
    name: str
    columns: Dict[str, Column]  # ordered
    raw_sql: str
    indexes: Dict[str, "Index"] = field(default_factory=dict)

    @property
    def pk_cols(self) -> List[str]:
        pks = [c for c in self.columns.values() if c.primary_key]
        pks.sort(key=lambda c: c.pk_order)
        return [c.name for c in pks]

    @property
    def non_pk_cols(self) -> List[str]:
        return [c.name for c in self.columns.values() if not c.primary_key]


@dataclass
class Index:
    name: str
    table: str
    raw_sql: str
    unique: bool


@dataclass
class Schema:
    tables: Dict[str, Table] = field(default_factory=dict)

    def table(self, name: str) -> Table:
        try:
            return self.tables[name]
        except KeyError:
            raise SchemaError(f"unknown table {name!r}") from None


_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_RESERVED_PREFIXES = ("__corro_", "__crdt_", "sqlite_", "crsql_")


def parse_sql(sql: str) -> Schema:
    """Parse CREATE TABLE / CREATE INDEX statements into a Schema by
    executing them against a scratch in-memory SQLite database."""
    scratch = sqlite3.connect(":memory:")
    try:
        try:
            scratch.executescript(sql)
        except sqlite3.Error as e:
            raise SchemaError(f"invalid schema SQL: {e}") from e

        schema = Schema()
        rows = scratch.execute(
            "SELECT type, name, tbl_name, sql FROM sqlite_master"
            " WHERE name NOT LIKE 'sqlite_%' ORDER BY rowid"
        ).fetchall()
        for typ, name, tbl_name, raw in rows:
            if typ == "table":
                schema.tables[name] = _introspect_table(scratch, name, raw)
            elif typ == "index":
                if raw is None:
                    continue  # auto-indexes (pk/unique) have NULL sql
                unique = bool(
                    re.match(r"(?is)\s*create\s+unique\s+index", raw)
                )
                idx = Index(name=name, table=tbl_name, raw_sql=raw, unique=unique)
                if tbl_name in schema.tables:
                    schema.tables[tbl_name].indexes[name] = idx
            elif typ in ("view", "trigger"):
                raise SchemaError(f"{typ}s are not allowed in CRR schemas: {name}")
        _constrain(scratch, schema)
        return schema
    finally:
        scratch.close()


def _introspect_table(conn: sqlite3.Connection, name: str, raw: str) -> Table:
    cols: Dict[str, Column] = {}
    for cid, cname, ctype, notnull, dflt, pk in conn.execute(
        f'PRAGMA table_info("{name}")'
    ):
        cols[cname] = Column(
            name=cname,
            sql_type=ctype or "",
            nullable=not notnull,
            default=dflt,
            primary_key=pk > 0,
            pk_order=pk,
        )
    return Table(name=name, columns=cols, raw_sql=raw)


def _constrain(conn: sqlite3.Connection, schema: Schema) -> None:
    """Enforce CRR-compatibility constraints (schema.rs:115-172)."""
    for t in schema.tables.values():
        if not _IDENT_RE.match(t.name):
            raise SchemaError(f"invalid table name {t.name!r}")
        if t.name.startswith(_RESERVED_PREFIXES):
            raise SchemaError(f"table name {t.name!r} uses a reserved prefix")
        if not t.pk_cols:
            raise SchemaError(f"table {t.name!r} requires a primary key")
        # WITHOUT ROWID etc are fine; pk expressions are impossible in
        # sqlite CREATE TABLE (only via indexes, checked below)
        for c in t.columns.values():
            if not _IDENT_RE.match(c.name):
                raise SchemaError(
                    f"{t.name}.{c.name!r}: invalid column name"
                    " (identifiers must match [A-Za-z_][A-Za-z0-9_]*)"
                )
            if c.name.startswith(_RESERVED_PREFIXES) or c.name == "-1":
                raise SchemaError(f"{t.name}.{c.name!r}: reserved column name")
            if not c.primary_key and not c.nullable and c.default is None:
                raise SchemaError(
                    f"{t.name}.{c.name}: NOT NULL columns need a DEFAULT"
                    " (conflict-free inserts must be able to fill them)"
                )
        # unique indexes (incl. UNIQUE column constraints → auto indexes)
        for r in conn.execute(f'PRAGMA index_list("{t.name}")'):
            # row: (seq, name, unique, origin, partial); origin 'pk' is fine
            _, iname, unique, origin, _ = r
            if unique and origin != "pk":
                raise SchemaError(
                    f"table {t.name!r}: UNIQUE indexes are not allowed"
                    " (uniqueness cannot be enforced across sites)"
                )
        if conn.execute(f'PRAGMA foreign_key_list("{t.name}")').fetchall():
            raise SchemaError(f"table {t.name!r}: foreign keys are not allowed")


@dataclass
class SchemaDiff:
    new_tables: List[Table] = field(default_factory=list)
    new_columns: List[Tuple[str, Column, str]] = field(default_factory=list)
    # (table, column, raw ADD COLUMN sql)
    new_indexes: List[Index] = field(default_factory=list)
    dropped_indexes: List[str] = field(default_factory=list)
    changed_indexes: List[Index] = field(default_factory=list)
    # tables whose column definitions changed (type/default/nullability):
    # applied via the 12-step rebuild (schema.rs:528-596) — the user table
    # is recreated and data copied; clock/rows CRDT state is untouched
    # because it lives in separate __crdt tables keyed by pk
    rebuild_tables: List[Table] = field(default_factory=list)


def diff_schemas(old: Schema, new: Schema) -> SchemaDiff:
    """Compute the migration from `old` to `new`; destructive changes are
    refused (schema.rs:242-258)."""
    d = SchemaDiff()
    for name, t in new.tables.items():
        if name not in old.tables:
            d.new_tables.append(t)
            continue
        ot = old.tables[name]
        for cname in ot.columns:
            if cname not in t.columns:
                raise SchemaError(
                    f"dropping column {name}.{cname} is destructive — refused"
                )
        if ot.pk_cols != t.pk_cols:
            raise SchemaError(f"changing the primary key of {name} is not supported")
        needs_rebuild = False
        new_cols: List[Tuple[str, Column, str]] = []
        for cname, c in t.columns.items():
            if cname not in ot.columns:
                if not c.nullable and c.default is None:
                    raise SchemaError(
                        f"new column {name}.{cname} must be nullable or have a default"
                    )
                decl = f'"{cname}" {c.sql_type}'
                if c.default is not None:
                    decl += f" DEFAULT {c.default}"
                if not c.nullable:
                    decl += " NOT NULL"
                new_cols.append((name, c, decl))
            else:
                oc = ot.columns[cname]
                if (
                    (oc.sql_type or "").upper() != (c.sql_type or "").upper()
                    or str(oc.default) != str(c.default)
                    or oc.nullable != c.nullable
                ):
                    # changed column definition → whole-table rebuild
                    # (schema.rs:528-596), not a refusal
                    needs_rebuild = True
        if needs_rebuild:
            # the rebuild recreates the table from the NEW definition
            # (including any added columns and its indexes) — don't also
            # emit piecewise column/index deltas for it
            d.rebuild_tables.append(t)
            continue
        d.new_columns.extend(new_cols)
        # indexes
        for iname, idx in t.indexes.items():
            if iname not in ot.indexes:
                d.new_indexes.append(idx)
            elif _norm_sql(ot.indexes[iname].raw_sql) != _norm_sql(idx.raw_sql):
                d.changed_indexes.append(idx)
        for iname in ot.indexes:
            if iname not in t.indexes:
                d.dropped_indexes.append(iname)
    for name in old.tables:
        if name not in new.tables:
            raise SchemaError(f"dropping table {name} is destructive — refused")
    return d


def _norm_sql(sql: str) -> str:
    return re.sub(r"\s+", " ", sql.strip().lower())

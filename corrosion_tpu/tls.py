"""TLS certificate generation for gossip transport security.

Counterpart of `klukai-types/src/tls.rs:17-100` (rcgen-based CA / server /
client certificate generation) and the `corrosion tls {ca,server,client}
generate` CLI commands (`klukai/src/command/tls.rs`). Uses the
`cryptography` package (baked into the image) instead of rcgen.
"""

from __future__ import annotations

import datetime
import ipaddress
from pathlib import Path
from typing import Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

CA_COMMON_NAME = "Corrosion TPU Root CA"
_ONE_DAY = datetime.timedelta(days=1)
_TEN_YEARS = datetime.timedelta(days=3650)


def _write_pem(path: Path, data: bytes) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(data)


def _key_pems(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def generate_ca(
    cert_path: str, key_path: str
) -> Tuple[x509.Certificate, ec.EllipticCurvePrivateKey]:
    """Self-signed CA (tls.rs:17-40)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, CA_COMMON_NAME)]
    )
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - _ONE_DAY)
        .not_valid_after(_now() + _TEN_YEARS)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            True,
        )
        .sign(key, hashes.SHA256())
    )
    _write_pem(Path(cert_path), cert.public_bytes(serialization.Encoding.PEM))
    _write_pem(Path(key_path), _key_pems(key))
    return cert, key


def _load_ca(
    ca_cert_path: str, ca_key_path: str
) -> Tuple[x509.Certificate, ec.EllipticCurvePrivateKey]:
    cert = x509.load_pem_x509_certificate(Path(ca_cert_path).read_bytes())
    key = serialization.load_pem_private_key(
        Path(ca_key_path).read_bytes(), password=None
    )
    return cert, key


def _issue(
    ca_cert: x509.Certificate,
    ca_key,
    common_name: str,
    san: Optional[x509.SubjectAlternativeName],
    extended_usage: x509.ExtendedKeyUsage,
) -> Tuple[x509.Certificate, ec.EllipticCurvePrivateKey]:
    key = ec.generate_private_key(ec.SECP256R1())
    builder = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        )
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_now() - _ONE_DAY)
        .not_valid_after(_now() + _TEN_YEARS)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), True)
        .add_extension(extended_usage, False)
    )
    if san is not None:
        builder = builder.add_extension(san, False)
    cert = builder.sign(ca_key, hashes.SHA256())
    return cert, key


def generate_server_cert(
    ca_cert_path: str,
    ca_key_path: str,
    ip: str,
    cert_path: str = "./server-cert.pem",
    key_path: str = "./server-key.pem",
) -> None:
    """Server cert with the gossip IP as SAN (tls.rs:42-75)."""
    ca_cert, ca_key = _load_ca(ca_cert_path, ca_key_path)
    try:
        san_entry: x509.GeneralName = x509.IPAddress(
            ipaddress.ip_address(ip)
        )
    except ValueError:
        san_entry = x509.DNSName(ip)
    cert, key = _issue(
        ca_cert,
        ca_key,
        common_name=ip,
        san=x509.SubjectAlternativeName([san_entry]),
        extended_usage=x509.ExtendedKeyUsage(
            [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH]
        ),
    )
    _write_pem(Path(cert_path), cert.public_bytes(serialization.Encoding.PEM))
    _write_pem(Path(key_path), _key_pems(key))


def generate_client_cert(
    ca_cert_path: str,
    ca_key_path: str,
    cert_path: str = "./client-cert.pem",
    key_path: str = "./client-key.pem",
) -> None:
    """Client cert for mTLS gossip (tls.rs:77-100)."""
    ca_cert, ca_key = _load_ca(ca_cert_path, ca_key_path)
    cert, key = _issue(
        ca_cert,
        ca_key,
        common_name="corrosion-tpu-client",
        san=None,
        extended_usage=x509.ExtendedKeyUsage(
            [x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]
        ),
    )
    _write_pem(Path(cert_path), cert.public_bytes(serialization.Encoding.PEM))
    _write_pem(Path(key_path), _key_pems(key))


def build_ssl_contexts(tls_cfg):
    """(server_ctx, client_ctx) for the gossip TCP lanes from a
    `runtime.config.GossipTlsConfig`.

    Mirrors the reference's rustls endpoint setup
    (`klukai-agent/src/api/peer/mod.rs:152-373`): the server presents
    cert_file/key_file and, with `mtls`, requires + verifies client
    certificates against ca_file; the client verifies the server against
    ca_file unless `insecure` (SkipServerVerification,
    `peer/mod.rs:386-442`), and presents client_cert_file when configured.
    """
    import ssl

    if not tls_cfg.cert_file or not tls_cfg.key_file:
        raise ValueError("gossip TLS requires cert_file and key_file")

    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(tls_cfg.cert_file, tls_cfg.key_file)
    if tls_cfg.mtls:
        if not tls_cfg.ca_file:
            raise ValueError("mtls requires ca_file")
        server.verify_mode = ssl.CERT_REQUIRED
        server.load_verify_locations(tls_cfg.ca_file)

    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if tls_cfg.insecure:
        client.check_hostname = False
        client.verify_mode = ssl.CERT_NONE
    else:
        if not tls_cfg.ca_file:
            raise ValueError("non-insecure TLS requires ca_file to verify peers")
        client.load_verify_locations(tls_cfg.ca_file)
    if tls_cfg.client_cert_file and tls_cfg.client_key_file:
        client.load_cert_chain(tls_cfg.client_cert_file, tls_cfg.client_key_file)
    return server, client

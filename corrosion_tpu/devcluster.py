"""Devcluster: topology DSL → N local agents + measurement harness.

Counterpart of `klukai-devcluster` (`src/topology/mod.rs:22` edge parser,
`src/main.rs:107-232` config generation + process spawning): parse
`A -> B` lines into a bootstrap graph, generate per-node configs with
random ports, launch the nodes — here either as in-process agents (fast,
deterministic, used by tests and the convergence bench) or as real
`python -m corrosion_tpu agent` subprocesses like the reference's built
binaries.

The measurement harness fills the BASELINE.md "reference point to
measure" rows: time-to-stable-membership and broadcast propagation
latency for a small CPU devcluster; the 10⁴–10⁶ member rungs run on the
batched SWIM kernel instead (corrosion_tpu.models.cluster.ClusterSim —
the same protocol in array form, sharded over the TPU mesh).
"""

from __future__ import annotations

import asyncio
import re
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

_EDGE = re.compile(r"^\s*([A-Za-z][A-Za-z0-9_]*)\s*->\s*([A-Za-z][A-Za-z0-9_]*)\s*$")


class TopologyError(Exception):
    pass


@dataclass
class Topology:
    """Graph edges: node -> nodes it bootstraps from (topology/mod.rs)."""

    edges: Dict[str, List[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, text: str) -> "Topology":
        topo = cls()
        for lineno, line in enumerate(text.splitlines(), 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            m = _EDGE.match(line)
            if m is None:
                raise TopologyError(f"line {lineno}: expected 'A -> B', got {line!r}")
            a, b = m.group(1), m.group(2)
            self_edges = topo.edges.setdefault(a, [])
            if b not in self_edges:
                self_edges.append(b)
            topo.edges.setdefault(b, [])
        return topo

    def nodes(self) -> List[str]:
        return sorted(self.edges)

    def responders(self) -> List[str]:
        """Nodes with no outgoing bootstrap edges — started first."""
        return [n for n in self.nodes() if not self.edges[n]]

    def initiators(self) -> List[str]:
        return [n for n in self.nodes() if self.edges[n]]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- in-process cluster ----------------------------------------------------


class DevCluster:
    """All topology nodes as in-process agents over loopback TCP (or an
    in-memory network): the harness for convergence measurements and
    multi-node tests without process overhead."""

    def __init__(
        self,
        topology: Topology,
        schema_sql: str = "",
        network=None,
        swim_config=None,
    ):
        self.topology = topology
        self.schema_sql = schema_sql
        self.network = network
        self.swim_config = swim_config
        self.agents: Dict[str, object] = {}
        self.started_at: Optional[float] = None
        self._db_paths: List[str] = []

    async def start(self) -> None:
        from corrosion_tpu.agent.run import run, setup
        from corrosion_tpu.runtime.config import Config

        self.started_at = time.monotonic()
        addrs: Dict[str, str] = {}

        async def boot(name: str) -> None:
            from corrosion_tpu.runtime.tmpdb import fresh_db_path

            cfg = Config()
            # file-backed, not :memory: (see runtime/tmpdb.py: the
            # shared-cache in-memory fallback has no real WAL and flakes
            # concurrent read+apply)
            cfg.db.path = fresh_db_path(name)
            self._db_paths.append(cfg.db.path)
            if self.network is not None:
                cfg.gossip.bind_addr = name
            else:
                cfg.gossip.bind_addr = "127.0.0.1:0"
            cfg.gossip.bootstrap = [
                addrs[peer]
                for peer in self.topology.edges[name]
                if peer in addrs
            ]
            agent = await setup(cfg, network=self.network)
            if self.swim_config is not None:
                agent.membership.config = self.swim_config
            if self.schema_sql:
                agent.store.apply_schema_sql(self.schema_sql)
            await run(agent)
            self.agents[name] = agent
            addrs[name] = agent.actor.addr

        # responders first, then initiators (main.rs:163-172)
        for name in self.topology.responders():
            await boot(name)
        for name in self.topology.initiators():
            await boot(name)

    async def stop(self) -> None:
        import glob
        import os

        from corrosion_tpu.agent.run import shutdown

        for agent in self.agents.values():
            await shutdown(agent)
        self.agents.clear()
        for path in self._db_paths:
            # escape: node names feed the path prefix, and a glob
            # metacharacter (e.g. an IPv6 '[::1]' bind addr) must not
            # break cleanup or match another cluster's files
            for f in glob.glob(glob.escape(path) + "*"):
                try:
                    os.unlink(f)  # db + -wal/-shm sidecars
                except OSError:
                    pass
        self._db_paths.clear()

    # -- measurements ------------------------------------------------------

    def membership_counts(self) -> Dict[str, int]:
        return {
            name: agent.membership.cluster_size
            for name, agent in self.agents.items()
        }

    def converged(self) -> bool:
        n = len(self.agents)
        return all(c == n for c in self.membership_counts().values())

    async def wait_converged(self, timeout: float = 60.0) -> float:
        """Seconds from cluster start to full membership convergence —
        the BASELINE 'time-to-stable-membership' metric."""
        assert self.started_at is not None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return time.monotonic() - self.started_at
            await asyncio.sleep(0.02)
        raise TimeoutError(
            f"membership did not converge: {self.membership_counts()}"
        )

    async def measure_broadcast_latency(
        self, writer: str, table: str, rowid: int, value: str,
        timeout: float = 30.0,
    ) -> Dict[str, float]:
        """Write on one node; seconds until each other node sees the row
        via epidemic broadcast (BASELINE propagation-latency row)."""
        from corrosion_tpu.agent.run import make_broadcastable_changes

        agent = self.agents[writer]
        t0 = time.monotonic()
        await make_broadcastable_changes(
            agent,
            lambda tx: [
                tx.execute(
                    f"INSERT OR REPLACE INTO {table} (id, text) VALUES (?, ?)",
                    [rowid, value],
                )
            ],
        )
        latency: Dict[str, float] = {writer: 0.0}
        pending = {n for n in self.agents if n != writer}
        deadline = t0 + timeout
        while pending and time.monotonic() < deadline:
            for name in list(pending):
                conn = self.agents[name].store.read_conn()
                try:
                    row = conn.execute(
                        f"SELECT text FROM {table} WHERE id = ?", (rowid,)
                    ).fetchone()
                finally:
                    conn.close()
                if row is not None and row[0] == value:
                    latency[name] = time.monotonic() - t0
                    pending.discard(name)
            if pending:
                await asyncio.sleep(0.01)
        if pending:
            raise TimeoutError(f"broadcast never reached: {sorted(pending)}")
        return latency


# -- subprocess cluster ----------------------------------------------------


class ProcessCluster:
    """Real `corrosion agent` subprocesses, like the reference spawning
    built binaries (main.rs run_corrosion)."""

    def __init__(
        self,
        topology: Topology,
        state_dir: str,
        schema_sql: str = "",
    ):
        self.topology = topology
        self.state_dir = Path(state_dir)
        self.schema_sql = schema_sql
        self.procs: Dict[str, subprocess.Popen] = {}
        self.api_ports: Dict[str, int] = {}
        self.admin_paths: Dict[str, str] = {}

    def generate_configs(self) -> Dict[str, Path]:
        """Random ports + bootstrap edges per node (main.rs:110-160)."""
        gossip_ports = {n: free_port() for n in self.topology.nodes()}
        configs: Dict[str, Path] = {}
        for name in self.topology.nodes():
            node_dir = self.state_dir / name
            node_dir.mkdir(parents=True, exist_ok=True)
            schema_path = node_dir / "schema.sql"
            schema_path.write_text(self.schema_sql)
            api_port = free_port()
            self.api_ports[name] = api_port
            admin = node_dir / "admin.sock"
            self.admin_paths[name] = str(admin)
            bootstrap = ", ".join(
                f'"127.0.0.1:{gossip_ports[p]}"'
                for p in self.topology.edges[name]
            )
            cfg = node_dir / "config.toml"
            cfg.write_text(
                f"""
[db]
path = "{node_dir / 'state.db'}"
schema_paths = ["{schema_path}"]

[api]
bind_addr = ["127.0.0.1:{api_port}"]

[gossip]
bind_addr = "127.0.0.1:{gossip_ports[name]}"
bootstrap = [{bootstrap}]

[admin]
uds_path = "{admin}"
"""
            )
            configs[name] = cfg
        return configs

    def start(self, env: Optional[dict] = None) -> None:
        configs = self.generate_configs()
        order = self.topology.responders() + self.topology.initiators()
        for name in order:
            log_path = self.state_dir / name / "agent.log"
            self.procs[name] = subprocess.Popen(
                [sys.executable, "-m", "corrosion_tpu",
                 "-c", str(configs[name]), "agent"],
                stdout=open(log_path, "w"),
                stderr=subprocess.STDOUT,
                env=env,
            )

    def wait_up(self, timeout: float = 60.0) -> None:
        """Block until every node's API port accepts AND its admin socket
        answers a ping.

        The admin check is load-bearing: the agent binds the admin socket
        before the API listener, but callers that connect to admin.sock the
        instant the API port opens were racing socket creation on slow
        machines (the r3 flake).  Ready means both surfaces answer.
        """
        deadline = time.monotonic() + timeout
        for name, port in self.api_ports.items():
            while time.monotonic() < deadline:
                try:
                    s = socket.create_connection(("127.0.0.1", port), 0.2)
                    s.close()
                    break
                except OSError:
                    if self.procs[name].poll() is not None:
                        raise RuntimeError(f"node {name} exited early")
                    time.sleep(0.1)
            else:
                raise TimeoutError(f"node {name} api never came up")
        for name, path in self.admin_paths.items():
            while time.monotonic() < deadline:
                if self._admin_ping(path):
                    break
                if self.procs[name].poll() is not None:
                    raise RuntimeError(f"node {name} exited early")
                time.sleep(0.1)
            else:
                raise TimeoutError(f"node {name} admin never answered ping")

    @staticmethod
    def _admin_ping(path: str) -> bool:
        """Synchronous UDS ping using the admin frame protocol
        (4-byte BE length + JSON; admin.py read_frame/write_frame)."""
        import struct

        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(1.0)
            s.connect(path)
            body = b'{"cmd":"ping"}'
            s.sendall(struct.pack(">I", len(body)) + body)
            hdr = s.recv(4)
            ok = len(hdr) == 4
            s.close()
            return ok
        except OSError:
            return False

    def stop(self, timeout: float = 15.0) -> None:
        import signal as _signal

        for p in self.procs.values():
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in self.procs.values():
            try:
                p.wait(timeout)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()


async def run_devcluster_cli(cfg, topology_path: str, schema_sql: str) -> int:
    """`corrosion devcluster TOPOLOGY` — spawn and babysit the cluster."""
    import tempfile

    topo = Topology.parse(Path(topology_path).read_text())
    state_dir = tempfile.mkdtemp(prefix="corrosion-devcluster-")
    cluster = ProcessCluster(topo, state_dir, schema_sql)
    cluster.start()
    try:
        cluster.wait_up()
        print(f"devcluster up: {len(topo.nodes())} nodes, state in {state_dir}")
        for name, port in sorted(cluster.api_ports.items()):
            print(f"  {name}: api 127.0.0.1:{port}"
                  f" admin {cluster.admin_paths[name]}")
        while True:
            await asyncio.sleep(1)
            for name, p in cluster.procs.items():
                if p.poll() is not None:
                    print(f"node {name} exited ({p.returncode}); stopping")
                    return 1
    except KeyboardInterrupt:
        return 0
    finally:
        cluster.stop()

"""lane-parity: SwimState <-> PViewState <-> mesh routing drift.

The bug class this guards: every kernel round so far (telemetry r7,
flight ring r8, Lifeguard r9) edited `ops/swim.py` and
`ops/swim_pview.py` in lockstep — 30+ protocol lanes duplicated by
hand, with `parallel/mesh.py` routing the non-per-member lanes BY NAME.
One missed edit ships a kernel whose states silently disagree on lane
names, dtypes or ordering (a wire-format change for every state
snapshot), or a new replicated lane that the mesh happily member-shards.
This checker is the static precursor of the ROADMAP's lane-registry
refactor: it parses both state NamedTuples, their init constructors and
the mesh's by-name special cases, and fails on any divergence outside
the two documented ones.

Documented divergences (everything else must match exactly):
- the table lane: dense `view` [N, N] int16  <->  pview `slot_packed`
  [N, K] int32 (packed words need 31 bits) — same position in the
  carry, different representation by design;
- the r6 at-rest int16 diet: pview `buf_key`/`buf_sent`/`susp_inc` are
  LANE_DTYPE (int16) where the dense kernel keeps int32.

Also pinned here: `FLIGHT_LANES = KERNEL_EVENTS + FLIGHT_CENSUS` in
that order (ring-row wire format), the census builder's arity matching
FLIGHT_CENSUS, and the shared `_event_vector`/`_census_frame` imports
(one lane-layout implementation, not two).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

DENSE = "corrosion_tpu/ops/swim.py"
PVIEW = "corrosion_tpu/ops/swim_pview.py"
MESH = "corrosion_tpu/parallel/mesh.py"
METRICS = "corrosion_tpu/runtime/metrics.py"

# (dense_name, pview_name) pairs allowed to differ at the same position
ALLOWED_NAME_PAIRS = {("view", "slot_packed")}
# fields allowed to differ in dtype (dense, pview)
ALLOWED_DTYPE_DIVERGENCE = {
    "view/slot_packed": ("int16", "int32"),  # packed words need 31 bits
    "buf_key": ("int32", "int16"),  # r6 at-rest diet
    "buf_sent": ("int32", "int16"),
    "susp_inc": ("int32", "int16"),
}

_DTYPE_KW_RE = re.compile(r"dtype\s*=\s*([A-Za-z_][A-Za-z_.0-9]*)")


@dataclass
class LaneInfo:
    name: str
    dtype: Optional[str]  # canonical token ("int32", "bool", ...) or None
    kind: str  # "member" | "other" | "scalar"
    line: int


class _KernelModel:
    """Parsed lane layout of one kernel module."""

    def __init__(self, sf) -> None:
        self.path = sf.path
        self.tree = sf.tree
        self.consts = self._module_dtype_consts()
        self.state_class = self._find_state_class()
        self.fields = self._state_fields()
        self.lanes = self._resolve_lanes()

    def _module_dtype_consts(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    src = ast.unparse(node.value)
                    m = re.fullmatch(r"jnp\.(\w+)", src)
                    if m:
                        out[t.id] = m.group(1)
        return out

    def _find_state_class(self) -> Optional[ast.ClassDef]:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef) and node.name.endswith(
                "State"
            ):
                return node
        return None

    def _state_fields(self) -> List[Tuple[str, int]]:
        if self.state_class is None:
            return []
        return [
            (stmt.target.id, stmt.lineno)
            for stmt in self.state_class.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
        ]

    def _init_constructor(self) -> Optional[ast.Call]:
        """The `return <State>(...)` call of the init builder — the one
        place every lane's dtype/shape is spelled out."""
        if self.state_class is None:
            return None
        best: Optional[Tuple[bool, ast.Call, ast.FunctionDef]] = None
        for fn in ast.walk(self.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id == self.state_class.name
                    and node.value.keywords
                ):
                    is_init = "init" in fn.name
                    if best is None or (is_init and not best[0]):
                        best = (is_init, node.value, fn)
        if best is None:
            return None
        self._init_fn = best[2]
        return best[1]

    def _resolve_expr(
        self, fn: ast.FunctionDef, value: ast.AST
    ) -> ast.AST:
        """Chase one level of local-name indirection to the first
        construction that names a dtype (`buf_key = jnp.zeros(...,
        dtype=...)` ... later `buf_key = buf_key.at[...]...`)."""
        if not isinstance(value, ast.Name):
            return value
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == value.id
                        and "dtype=" in ast.unparse(node.value)
                    ):
                        return node.value
        return value

    def _dtype_of(self, expr: ast.AST) -> Optional[str]:
        src = ast.unparse(expr)
        m = _DTYPE_KW_RE.search(src)
        if m:
            token = m.group(1)
            token = token.split("jnp.")[-1]
            return self.consts.get(token, token)
        # jnp.int32(0)-style scalar casts
        m = re.match(r"jnp\.(\w+)\(", src)
        if m and m.group(1) in (
            "int8", "int16", "int32", "int64",
            "uint8", "uint16", "uint32", "uint64",
            "float16", "float32", "float64", "bool_",
        ):
            return m.group(1)
        return None

    def _kind_of(self, expr: ast.AST) -> str:
        """'member' if the first constructed dim is `n`, 'other' for
        non-member arrays (events/ring), 'scalar' when no array
        construction is visible (the tick counter)."""
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("zeros", "ones", "full", "empty")
                and node.args
            ):
                first = node.args[0]
                if isinstance(first, ast.Tuple) and first.elts:
                    first = first.elts[0]
                if isinstance(first, ast.Name) and first.id == "n":
                    return "member"
                return "other"
        return "scalar"

    def _resolve_lanes(self) -> Dict[str, LaneInfo]:
        ctor = self._init_constructor()
        out: Dict[str, LaneInfo] = {}
        by_name = dict(self.fields)
        if ctor is None:
            return out
        for kw in ctor.keywords:
            if kw.arg is None:
                continue
            expr = self._resolve_expr(self._init_fn, kw.value)
            out[kw.arg] = LaneInfo(
                name=kw.arg,
                dtype=self._dtype_of(expr),
                kind=self._kind_of(expr),
                line=by_name.get(kw.arg, kw.value.lineno),
            )
        return out


def _mesh_replicated_names(sf) -> Optional[Tuple[List[str], int]]:
    """The by-name replicated-lane tuple in `_state_shardings`
    (`... or name in ("events", "ring")`)."""
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Compare)
            and len(node.ops) == 1
            and isinstance(node.ops[0], ast.In)
            and isinstance(node.left, ast.Name)
            and node.left.id == "name"
            and isinstance(node.comparators[0], (ast.Tuple, ast.List, ast.Set))
        ):
            names = [
                e.value
                for e in node.comparators[0].elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            return names, node.lineno
    return None


def _tuple_const(tree: ast.AST, name: str) -> Optional[List[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return [
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                ]
    return None


class LaneParityChecker(Checker):
    rule = "lane-parity"
    description = (
        "SwimState/PViewState lane names, dtypes and ordering stay in "
        "lockstep with each other and with parallel/mesh.py's by-name "
        "replication routing"
    )

    def __init__(
        self,
        dense: str = DENSE,
        pview: str = PVIEW,
        mesh: str = MESH,
        metrics: str = METRICS,
    ):
        self.dense = dense
        self.pview = pview
        self.mesh = mesh
        self.metrics = metrics

    def _finding(
        self, path: str, line: int, symbol: str, message: str, snippet: str
    ) -> Finding:
        return Finding(
            rule=self.rule,
            path=path,
            line=line,
            symbol=symbol,
            message=message,
            snippet=snippet,
        )

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        dense_sf, pview_sf = ctx.file(self.dense), ctx.file(self.pview)
        mesh_sf = ctx.file(self.mesh)
        if dense_sf is None or pview_sf is None:
            return findings
        d, p = _KernelModel(dense_sf), _KernelModel(pview_sf)

        # 1. field-name ordering, modulo the allowed table-lane pair
        d_names = [n for n, _ in d.fields]
        p_names = [n for n, _ in p.fields]
        for i in range(max(len(d_names), len(p_names))):
            dn = d_names[i] if i < len(d_names) else "<missing>"
            pn = p_names[i] if i < len(p_names) else "<missing>"
            if dn == pn or (dn, pn) in ALLOWED_NAME_PAIRS:
                continue
            findings.append(
                self._finding(
                    self.pview,
                    p.fields[i][1] if i < len(p.fields) else 0,
                    f"{p.state_class.name if p.state_class else '?'}",
                    f"lane #{i} diverges: dense carries {dn!r}, pview "
                    f"carries {pn!r} — state field order is a wire "
                    "format; add the lane to both kernels (or extend "
                    "ALLOWED_NAME_PAIRS with a justification)",
                    f"lane#{i}:{dn}!={pn}",
                )
            )

        # 2. dtype parity, modulo the documented int16 diet
        for dn, pn in zip(d_names, p_names):
            key = dn if dn == pn else f"{dn}/{pn}"
            di, pi = d.lanes.get(dn), p.lanes.get(pn)
            if di is None or pi is None or di.dtype is None or pi.dtype is None:
                continue
            if di.dtype == pi.dtype:
                continue
            if ALLOWED_DTYPE_DIVERGENCE.get(key) == (di.dtype, pi.dtype):
                continue
            findings.append(
                self._finding(
                    self.pview,
                    pi.line,
                    f"{p.state_class.name}.{pn}",
                    f"lane {key!r} dtype diverges: dense={di.dtype} "
                    f"pview={pi.dtype} — at-rest dtype is a wire format "
                    "(extend ALLOWED_DTYPE_DIVERGENCE only with a "
                    "measured diet rationale like r6's int16 lanes)",
                    f"dtype:{key}:{di.dtype}!={pi.dtype}",
                )
            )

        # 3. mesh by-name replication routing covers exactly the
        #    non-per-member array lanes of BOTH kernels
        if mesh_sf is not None:
            mesh_info = _mesh_replicated_names(mesh_sf)
            if mesh_info is None:
                findings.append(
                    self._finding(
                        self.mesh, 0, "_state_shardings",
                        "could not locate the by-name replicated-lane "
                        "tuple (`name in (...)`) — lane-parity cannot "
                        "verify replication routing",
                        "mesh:no-replicated-tuple",
                    )
                )
            else:
                replicated, mesh_line = mesh_info
                for model in (d, p):
                    for lane in model.lanes.values():
                        if lane.kind == "other" and lane.name not in replicated:
                            findings.append(
                                self._finding(
                                    self.mesh,
                                    mesh_line,
                                    "_state_shardings",
                                    f"{model.path} lane {lane.name!r} is "
                                    "not per-member (leading dim is not "
                                    "n) but missing from mesh.py's "
                                    "replicated-by-name tuple — it would "
                                    "be member-sharded and all-gathered "
                                    "wrong",
                                    f"mesh:unrouted:{lane.name}",
                                )
                            )
                    for name in replicated:
                        lane = model.lanes.get(name)
                        if lane is None:
                            findings.append(
                                self._finding(
                                    self.mesh,
                                    mesh_line,
                                    "_state_shardings",
                                    f"mesh.py replicates lane {name!r} "
                                    f"by name but {model.path} has no "
                                    "such state field",
                                    f"mesh:orphan:{name}:{model.path}",
                                )
                            )
                        elif lane.kind == "member":
                            findings.append(
                                self._finding(
                                    self.mesh,
                                    mesh_line,
                                    "_state_shardings",
                                    f"mesh.py replicates {name!r} but "
                                    f"{model.path} constructs it "
                                    "per-member (leading dim n) — a "
                                    "member lane must be sharded, not "
                                    "replicated",
                                    f"mesh:misrouted:{name}:{model.path}",
                                )
                            )

        # 4. ring-row wire format: FLIGHT_LANES = KERNEL_EVENTS +
        #    FLIGHT_CENSUS in that order, census builder arity matches
        metrics_sf = ctx.file(self.metrics)
        if metrics_sf is not None:
            ok = False
            for node in ast.walk(metrics_sf.tree):
                if (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "FLIGHT_LANES"
                        for t in node.targets
                    )
                    and isinstance(node.value, ast.BinOp)
                    and isinstance(node.value.op, ast.Add)
                ):
                    left = ast.unparse(node.value.left)
                    right = ast.unparse(node.value.right)
                    ok = left == "KERNEL_EVENTS" and right == "FLIGHT_CENSUS"
            if not ok:
                findings.append(
                    self._finding(
                        self.metrics, 0, "FLIGHT_LANES",
                        "FLIGHT_LANES must be exactly KERNEL_EVENTS + "
                        "FLIGHT_CENSUS (ring-row order is a wire format "
                        "for every drained snapshot)",
                        "flight-lanes-order",
                    )
                )
            census = _tuple_const(metrics_sf.tree, "FLIGHT_CENSUS")
            if census is not None:
                for node in ast.walk(dense_sf.tree):
                    if (
                        isinstance(node, ast.FunctionDef)
                        and node.name == "_census_frame"
                    ):
                        for sub in ast.walk(node):
                            if (
                                isinstance(sub, ast.Call)
                                and ast.unparse(sub.func) == "jnp.stack"
                                and sub.args
                                and isinstance(sub.args[0], ast.List)
                            ):
                                got = len(sub.args[0].elts)
                                if got != len(census):
                                    findings.append(
                                        self._finding(
                                            self.dense,
                                            sub.lineno,
                                            "_census_frame",
                                            f"census frame stacks {got} "
                                            "lanes but FLIGHT_CENSUS "
                                            f"names {len(census)} — the "
                                            "ring row and its schema "
                                            "disagree",
                                            "census-arity",
                                        )
                                    )

        # 5. one lane-layout implementation: the pview kernel must share
        #    the dense kernel's _event_vector/_census_frame (or import
        #    the canonical KERNEL_EVENTS itself), never hand-roll order
        shared = set()
        for node in ast.walk(pview_sf.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    shared.add(alias.name)
        if not (
            {"_event_vector", "_census_frame"} <= shared
            or "KERNEL_EVENTS" in shared
        ):
            findings.append(
                self._finding(
                    self.pview, 0, "<module>",
                    "pview kernel neither imports the dense kernel's "
                    "_event_vector/_census_frame nor KERNEL_EVENTS — "
                    "a hand-rolled lane order will drift",
                    "pview:no-shared-lane-impl",
                )
            )
        return findings

"""kernel-purity: host escapes inside ops/* jitted tick code.

The bug class: a host sync or host materialization inside a traced
kernel body — `.item()` / `float()` on a traced value, `np.*` on device
data, `time.*` inside the tick, or a Python `if` branching on a traced
array (trace-time constant-folds one arm, or dies with a
ConcretizationTypeError at the worst possible shape).  These are the
copy/host-sync bugs PR 1's fused-tick restructure and the memguard tests
chase after the fact; this checker catches them at lint time.

Scope: functions REACHABLE FROM A JIT ROOT in `corrosion_tpu/ops/*.py`.
A jit root is a function wrapped by `@jax.jit`, `@functools.partial(
jax.jit, ...)`, or a module-level `name = functools.partial(jax.jit,
...)(fn)` / `jax.jit(fn)` application.  Reachability follows same-module
calls and bare-name references (a function handed to `pl.pallas_call` or
`lax.scan` is traced too).  Host-side wrappers in the same files
(`stats_and_events`, `merge_table_array`) are NOT in the closure and may
do host work freely.

Traced-value approximation (documented, deliberately simple):
- In a jit root, every parameter NOT named in `static_argnames` /
  `static_argnums` is a traced root.
- A local name assigned from an expression containing a traced name or
  a `jnp.*` / `jax.*` call is traced ("taint-lite": one forward pass,
  no fixpoint across reassignments-before-definition).
- In non-root traced functions parameter staticness is unknown, so only
  locally-derived taint (`jnp.*`/`jax.*` results) is tracked — branchy
  helpers keyed off static params stay clean, `if jnp.any(mask):` does
  not.
- `x is None` / `x is not None` tests are exempt: tracers are never
  None, so optionality branching is trace-safe.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from corrosion_tpu.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    enclosing_symbols,
)

SCOPE = ("corrosion_tpu/ops",)

# modules whose mere use inside a traced body is a host escape
_HOST_MODULES = {"np", "numpy", "time"}
# builtins that force a concrete value out of a tracer
_CONCRETIZERS = {"float", "int", "bool", "complex"}


def _jit_roots(tree: ast.Module) -> Dict[str, Set[str]]:
    """function name -> set of STATIC parameter names, for every
    function the module jits (decorator or wrapper-application form)."""

    def _static_names(call: ast.Call, fn: ast.FunctionDef) -> Set[str]:
        names: Set[str] = set()
        params = [a.arg for a in fn.args.args]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        names.add(elt.value)
            elif kw.arg == "static_argnums":
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, int
                    ):
                        if 0 <= elt.value < len(params):
                            names.add(params[elt.value])
        return names

    def _is_jit_call(call: ast.Call) -> bool:
        # jax.jit(...) or functools.partial(jax.jit, ...)
        src = ast.unparse(call.func)
        if src.endswith("jax.jit") or src == "jit":
            return True
        if src.endswith("functools.partial") or src == "partial":
            return bool(call.args) and ast.unparse(call.args[0]).endswith(
                "jit"
            )
        return False

    fns = {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
    }
    roots: Dict[str, Set[str]] = {}
    for fn in fns.values():
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_call(dec):
                roots[fn.name] = _static_names(dec, fn)
            elif ast.unparse(dec).endswith("jax.jit"):
                roots[fn.name] = set()
    # wrapper-application form: X = functools.partial(jax.jit, ...)(fn)
    # and X = jax.jit(fn)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        target = node.args[0]
        if not (isinstance(target, ast.Name) and target.id in fns):
            continue
        f = node.func
        if isinstance(f, ast.Call) and _is_jit_call(f):
            roots[target.id] = _static_names(f, fns[target.id])
        elif isinstance(f, ast.Attribute) and ast.unparse(f).endswith(
            "jax.jit"
        ):
            roots[target.id] = set()
    return roots


def _called_names(fn: ast.FunctionDef) -> Set[str]:
    """Bare names called OR referenced inside fn (a reference covers
    functions handed to lax.scan / pallas_call / while_loop)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
    return out


class _TaintVisitor(ast.NodeVisitor):
    """Flags host escapes inside ONE traced function (nested defs
    included — they trace with their parent)."""

    def __init__(
        self,
        checker: "KernelPurityChecker",
        path: str,
        symbol: str,
        traced_params: Set[str],
        findings: List[Finding],
    ):
        self.checker = checker
        self.path = path
        self.symbol = symbol
        self.tainted: Set[str] = set(traced_params)
        self.findings = findings

    # array metadata is static at trace time — `x.shape[0]` of a traced
    # x is an ordinary Python int, not a tracer
    _STATIC_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))

    def _expr_tainted(self, node: ast.AST) -> bool:
        stack = [node]
        while stack:
            sub = stack.pop()
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr in self._STATIC_ATTRS
            ):
                continue  # don't descend: metadata reads are static
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if isinstance(sub, ast.Call):
                src = ast.unparse(sub.func)
                if src.startswith(("jnp.", "jax.")):
                    return True
            stack.extend(ast.iter_child_nodes(sub))
        return False

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                rule=KernelPurityChecker.rule,
                path=self.path,
                line=getattr(node, "lineno", 0),
                symbol=self.symbol,
                message=message,
                snippet=Checker.snippet_of(node),
            )
        )

    # -- taint propagation --------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if self._expr_tainted(node.value):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        self.tainted.add(sub.id)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self._expr_tainted(node.value) and isinstance(
            node.target, ast.Name
        ):
            self.tainted.add(node.target.id)

    # -- escapes ------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "item":
            self._flag(node, ".item() forces a device->host sync")
        if (
            isinstance(f, ast.Name)
            and f.id in _CONCRETIZERS
            and node.args
            and self._expr_tainted(node.args[0])
        ):
            self._flag(
                node,
                f"{f.id}() on a traced value concretizes it "
                "(host sync / trace-time constant)",
            )
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in _HOST_MODULES
        ):
            mod = node.value.id
            what = (
                "wall-clock reads are invisible to the trace"
                if mod == "time"
                else "host/numpy materialization in traced code"
            )
            self._flag(node, f"{mod}.{node.attr}: {what}")
        self.generic_visit(node)

    def _check_test(self, node, kind: str) -> None:
        test = node.test
        # `x is (not) None` alone is trace-safe (tracers are never None)
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return
        if self._expr_tainted(test):
            self._flag(
                node,
                f"Python `{kind}` on a traced value — use jnp.where / "
                "lax.cond (trace-time branch freezes one arm)",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node, "while")
        self.generic_visit(node)


class KernelPurityChecker(Checker):
    rule = "kernel-purity"
    description = (
        "no host syncs / host materialization / Python branches on "
        "traced values inside ops/* jit-reachable code"
    )

    def __init__(self, scope=SCOPE):
        self.scope = scope

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for sf in ctx.walk(*self.scope):
            tree = sf.tree
            # module-level functions only: nested defs are visited
            # through their parent's visitor (shared taint state), and
            # keeping the closure at module granularity avoids name
            # collisions between unrelated nested `body`/`cond` helpers
            fns = {
                n.name: n
                for n in tree.body
                if isinstance(n, ast.FunctionDef)
            }
            roots = _jit_roots(tree)
            # closure over same-module calls/references
            traced: Set[str] = set(roots)
            frontier = list(roots)
            while frontier:
                name = frontier.pop()
                fn = fns.get(name)
                if fn is None:
                    continue
                for callee in _called_names(fn) & set(fns):
                    if callee not in traced:
                        traced.add(callee)
                        frontier.append(callee)
            symbols = enclosing_symbols(tree)
            for name in sorted(traced):
                fn = fns.get(name)
                if fn is None:
                    continue
                static = roots.get(name, set())
                traced_params = (
                    {a.arg for a in fn.args.args} - static
                    if name in roots
                    else set()
                )
                _TaintVisitor(
                    self,
                    sf.path,
                    symbols.get(fn, name),
                    traced_params,
                    findings,
                ).visit(fn)
        return findings

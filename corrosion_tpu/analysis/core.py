"""Checker framework: file cache, findings, noqa suppression, baseline.

Design rules (what keeps the suite honest AND cheap):

- ONE parse per file.  `AnalysisContext` loads and `ast.parse`s every
  scanned file once; checkers share the cache.  The whole repo pass is
  a few hundred milliseconds — cheap enough for tier-1.
- Findings are keyed WITHOUT line numbers (`rule|path|symbol|snippet`),
  so unrelated edits above a grandfathered finding do not churn the
  committed baseline.
- Suppression is per-finding and self-documenting: a
  `# corro: noqa[rule]` comment on the flagged statement's first line.
  Blanket per-file opt-outs are deliberately not offered.
- The baseline (`ANALYSIS_BASELINE.json`) is for *proven-benign*
  grandfathered findings only; every entry carries a one-line
  justification and STALE entries (no longer firing) fail the run so
  the list can only shrink deliberately (same two-direction hygiene as
  the metrics table lint).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

BASELINE_FILE = "ANALYSIS_BASELINE.json"

_NOQA_RE = re.compile(r"#\s*corro:\s*noqa\[([a-z0-9_,\- ]+)\]")


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


@dataclass(frozen=True)
class Finding:
    rule: str  # checker id, e.g. "async-blocking"
    path: str  # repo-relative posix path
    line: int  # 1-based line of the flagged node (0 = whole-file)
    symbol: str  # enclosing Class.method / function / "<module>"
    message: str
    snippet: str = ""  # normalized source of the flagged node

    @property
    def key(self) -> str:
        """Line-free stable identity used by the baseline."""
        return f"{self.rule}|{self.path}|{self.symbol}|{self.snippet}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: str  # repo-relative
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def noqa_rules(self, line: int) -> List[str]:
        """Rules suppressed on `line` (1-based) via `# corro: noqa[rule]`."""
        if 1 <= line <= len(self.lines):
            m = _NOQA_RE.search(self.lines[line - 1])
            if m:
                return [r.strip() for r in m.group(1).split(",")]
        return []


class AnalysisContext:
    """Shared parsed-file cache + repo location for one analysis run."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or repo_root()
        self._files: Dict[str, SourceFile] = {}

    def file(self, rel: str) -> Optional[SourceFile]:
        """Parsed source for one repo-relative path (None if unreadable)."""
        rel = rel.replace(os.sep, "/")
        if rel not in self._files:
            path = os.path.join(self.root, rel)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                tree = ast.parse(text)
            except (OSError, SyntaxError):
                self._files[rel] = None  # type: ignore[assignment]
                return None
            self._files[rel] = SourceFile(
                path=rel, text=text, tree=tree, lines=text.splitlines()
            )
        return self._files[rel]

    def walk(self, *tops: str) -> List[SourceFile]:
        """Every parseable .py file under the given repo-relative dirs."""
        out: List[SourceFile] = []
        for top in tops:
            top_abs = os.path.join(self.root, top)
            if os.path.isfile(top_abs):
                sf = self.file(top)
                if sf is not None:
                    out.append(sf)
                continue
            for dirpath, _dirs, files in sorted(os.walk(top_abs)):
                for fn in sorted(files):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), self.root
                    ).replace(os.sep, "/")
                    sf = self.file(rel)
                    if sf is not None:
                        out.append(sf)
        return out

    def read_text(self, rel: str) -> str:
        """Raw text of any repo file ('' if unreadable) — for checkers
        that cross-reference non-Python artifacts (COMPONENTS.md,
        tests)."""
        try:
            with open(os.path.join(self.root, rel), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


class Checker:
    """One rule.  Subclasses set `rule`/`description` and implement
    `run(ctx)` returning raw findings; the driver applies noqa and
    baseline filtering afterwards (checkers stay filter-agnostic)."""

    rule: str = "abstract"
    description: str = ""

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError

    # -- shared AST helpers -------------------------------------------------

    @staticmethod
    def snippet_of(node: ast.AST, limit: int = 72) -> str:
        try:
            s = ast.unparse(node)
        except Exception:
            s = type(node).__name__
        s = " ".join(s.split())
        return s[:limit]


def enclosing_symbols(tree: ast.AST) -> Dict[ast.AST, str]:
    """node -> dotted enclosing symbol name, for stable finding keys."""
    out: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                name = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = name
                visit(child, name)
            else:
                out[child] = prefix or "<module>"
                visit(child, prefix)

    visit(tree, "")
    return out


# -- baseline ---------------------------------------------------------------


def load_baseline(root: str) -> Dict[str, str]:
    """key -> justification; empty when the file is absent."""
    path = os.path.join(root, BASELINE_FILE)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["key"]: e.get("justification", "") for e in data["entries"]}


def save_baseline(
    root: str, findings: List[Finding], keep: Dict[str, str]
) -> str:
    """Re-bank: current findings become the baseline.  Justifications of
    surviving entries are preserved; new entries get an UNREVIEWED
    placeholder that a human must replace before committing."""
    entries = []
    seen = set()
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append(
            {
                "key": f.key,
                "justification": keep.get(
                    f.key, "UNREVIEWED — justify or fix before committing"
                ),
            }
        )
    path = os.path.join(root, BASELINE_FILE)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(
            {"version": 1, "entries": entries}, f, indent=2, sort_keys=True
        )
        f.write("\n")
    return path


# -- driver -----------------------------------------------------------------


def all_checkers() -> List[Checker]:
    # imported lazily so `core` has no checker-module dependencies
    from corrosion_tpu.analysis.actuators import ActuatorDisciplineChecker
    from corrosion_tpu.analysis.blocking import AsyncBlockingChecker
    from corrosion_tpu.analysis.capture_parity import CaptureParityChecker
    from corrosion_tpu.analysis.codecext import CodecExtChecker
    from corrosion_tpu.analysis.finalize_parity import FinalizeParityChecker
    from corrosion_tpu.analysis.lockcheck import LockDisciplineChecker
    from corrosion_tpu.analysis.metricsdoc import MetricsDocChecker
    from corrosion_tpu.analysis.parity import LaneParityChecker
    from corrosion_tpu.analysis.profiler_safety import ProfilerSafetyChecker
    from corrosion_tpu.analysis.purity import KernelPurityChecker
    from corrosion_tpu.analysis.timeouts import TimeoutDisciplineChecker

    return [
        KernelPurityChecker(),
        LaneParityChecker(),
        AsyncBlockingChecker(),
        LockDisciplineChecker(),
        CodecExtChecker(),
        CaptureParityChecker(),
        FinalizeParityChecker(),
        MetricsDocChecker(),
        TimeoutDisciplineChecker(),
        ActuatorDisciplineChecker(),
        ProfilerSafetyChecker(),
    ]


@dataclass
class AnalysisResult:
    new: List[Finding]  # fail the run
    baselined: List[Tuple[Finding, str]]  # grandfathered (justified)
    suppressed: List[Finding]  # # corro: noqa[rule]
    stale_keys: List[str]  # baseline entries that no longer fire

    @property
    def ok(self) -> bool:
        return not self.new and not self.stale_keys


def run_analysis(
    ctx: Optional[AnalysisContext] = None,
    checkers: Optional[List[Checker]] = None,
    baseline: Optional[Dict[str, str]] = None,
) -> AnalysisResult:
    ctx = ctx or AnalysisContext()
    checkers = checkers if checkers is not None else all_checkers()
    baseline = (
        baseline if baseline is not None else load_baseline(ctx.root)
    )

    raw: List[Finding] = []
    for checker in checkers:
        raw.extend(checker.run(ctx))

    new: List[Finding] = []
    baselined: List[Tuple[Finding, str]] = []
    suppressed: List[Finding] = []
    fired_keys = set()
    for f in raw:
        fired_keys.add(f.key)
        sf = ctx.file(f.path)
        if sf is not None and f.rule in sf.noqa_rules(f.line):
            suppressed.append(f)
        elif f.key in baseline:
            baselined.append((f, baseline[f.key]))
        else:
            new.append(f)

    active_rules = {c.rule for c in checkers}
    stale = [
        k
        for k in sorted(baseline)
        if k not in fired_keys and k.split("|", 1)[0] in active_rules
    ]
    return AnalysisResult(
        new=new, baselined=baselined, suppressed=suppressed, stale_keys=stale
    )

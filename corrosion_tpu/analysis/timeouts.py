"""timeout-discipline: network awaits in agent//api/ must carry deadlines.

The bug class (r18 chaos matrix, zombie-node scenario): a peer whose
kernel keeps accepting bytes while its event loop is stalled turns any
unbounded `await stream.recv()` / `await stream.send(...)` /
`await transport.open_bi(...)` into a hang — the sync round stalls, the
serve permit pins, the broadcast loop wedges behind one uni stream.
The repo's discipline is that EVERY await on a cross-node wait is
directly wrapped in `asyncio.wait_for(...)` with a module deadline
constant (RECV_TIMEOUT / SEND_TIMEOUT / OPEN_TIMEOUT) or routed through
a helper that applies one (`AdaptiveChunkSize.timed_send`).

What is flagged: a direct `await X.<m>(...)` in `agent/` or `api/`
where `m` is one of the network-wait methods (`recv`, `send`,
`finish`, `open_bi`, `send_uni`) — unless

- the receiver is an in-process channel: its trailing name segment
  starts with ``tx_``/``rx_`` (`runtime/channels.py` senders/receivers
  — local backpressure by design, closed on shutdown, never a peer),
- or the await's value is an `asyncio.wait_for(...)` call (the fix).

Deliberately NOT flagged: datagram sends (`send_datagram` — UDP
fire-and-forget, no peer round-trip to wait on) and anything already
behind a helper whose receiver is not stream/transport-shaped (the
method-name set keeps the rule precise instead of guessing types).
"""

from __future__ import annotations

import ast
from typing import List

from corrosion_tpu.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    enclosing_symbols,
)

SCOPE = (
    "corrosion_tpu/agent",
    "corrosion_tpu/api",
)

# the cross-node wait surface: BiStream + Transport methods whose
# completion depends on a REMOTE peer making progress
NETWORK_METHODS = {"recv", "send", "finish", "open_bi", "send_uni"}

_CHANNEL_PREFIXES = ("tx_", "rx_")


def _receiver_tail(func: ast.Attribute) -> str:
    """Last name segment of the receiver expression: `agent.tx_bcast`
    -> 'tx_bcast', `stream` -> 'stream', `self.transport` ->
    'transport'."""
    v = func.value
    if isinstance(v, ast.Attribute):
        return v.attr
    if isinstance(v, ast.Name):
        return v.id
    return ""


class TimeoutDisciplineChecker(Checker):
    rule = "timeout-discipline"
    description = (
        "awaits on network/stream reads and cross-node waits in agent/ "
        "and api/ must be bounded by asyncio.wait_for (zombie-node "
        "hang class)"
    )

    def __init__(self, scope=SCOPE):
        self.scope = scope

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for sf in ctx.walk(*self.scope):
            symbols = enclosing_symbols(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Await):
                    continue
                call = node.value
                if not isinstance(call, ast.Call):
                    continue
                func = call.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr not in NETWORK_METHODS:
                    continue
                tail = _receiver_tail(func)
                if tail.startswith(_CHANNEL_PREFIXES):
                    continue  # in-process channel, not a peer wait
                findings.append(
                    Finding(
                        rule=self.rule,
                        path=sf.path,
                        line=node.lineno,
                        symbol=symbols.get(node, "<module>"),
                        message=(
                            f"unbounded await on network wait "
                            f".{func.attr}() — a zombie peer (sockets "
                            "open, loop stalled) hangs this forever; "
                            "wrap in asyncio.wait_for with a module "
                            "deadline constant"
                        ),
                        snippet=self.snippet_of(node),
                    )
                )
        return findings

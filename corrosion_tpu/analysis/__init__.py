"""corro-analyze: AST-based static analysis over the corrosion-tpu tree.

Every bug class this repo has paid for by hand is statically visible —
the r7 GIL-racy metric mutations, the r10 blocking-SQL-in-async matcher
deaths, the per-PR lockstep edits of the 30+ protocol lanes in both SWIM
kernels.  This package turns those into lint-time failures: a small
`Checker` framework (`core.py`), one checker module per rule, a committed
`ANALYSIS_BASELINE.json` for grandfathered findings, and per-finding
`# corro: noqa[rule]` suppressions.  `scripts/corro_lint.py` is the one
driver; `tests/test_static_analysis.py` is the tier-1 gate.

Rules (see COMPONENTS.md "Static analysis" for the full table):
    kernel-purity   host syncs / host materialization / Python control
                    flow on traced values inside ops/* jitted tick code
    lane-parity     SwimState <-> PViewState <-> parallel/mesh.py lane
                    name/dtype/ordering drift (the lane-registry
                    refactor's static precursor)
    async-blocking  blocking SQL / sleeps / file I/O directly in
                    `async def` bodies under agent/, api/, pubsub/
    lock-discipline state mutated from both worker-thread and event-loop
                    contexts without a lock
    codec-ext       every version-gated codec ext has a read path, a
                    write path and a compat test
    metrics-doc     emitted series <-> COMPONENTS.md observability table
                    (both directions; the former scripts/lint_metrics.py)
    capture-parity  trigger DDL <-> direct-capture lockstep (r15)
    finalize-parity native crdt_finalize_batch ABI <-> Python glue
                    lockstep + counted columnar fallback (r24)
    timeout-discipline  network awaits in agent//api/ carry wait_for
                    deadlines (r18: the zombie-node hang class)
    actuator-discipline  remediation actuators declare cooldown /
                    max_per_hour / reversibility and honor dry-run (r22)
    profiler-safety code reachable from the stack sampler's hot path
                    takes no lock but _fold_lock, calls no asyncio and
                    allocates nothing per sample (r23)
"""

from corrosion_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Checker,
    Finding,
    all_checkers,
    load_baseline,
    run_analysis,
    save_baseline,
)

"""lock-discipline: thread/loop-shared state mutated without a lock.

The bug class: the r7 metrics races — `Counter.inc` was `self.value +=
1` with the agent-metrics worker thread and the event loop both calling
it, silently losing increments under the GIL's bytecode-boundary
switches.  The repo's pattern since: any state touched from BOTH a
worker thread (`asyncio.to_thread`, `run_in_executor`,
`threading.Thread`) and the event loop takes an instance lock
(runtime/metrics.py per-instrument locks, records.py FlightRecorder),
or copies under the GIL in ONE C-level call with a comment
(member_store's `dict(...)` snapshot idiom).

Static evidence model (documented approximation — honest about what a
name-based analysis can and cannot see):

1. THREAD ENTRY POINTS: any function/method referenced as the callable
   of `asyncio.to_thread(f, ...)`, `loop.run_in_executor(pool, f)`,
   `threading.Thread(target=f)` or `threading.Timer(t, f)`, anywhere in
   the scanned tree.  `self.m` / `obj.m` references resolve by method
   name against every scanned class that defines `m` (cross-object
   aliasing is invisible to AST analysis; the baseline absorbs the
   rare false match with a justification).
2. Closure within a class: a thread-entered method taints the methods
   it `self.`-calls.
3. MUTATIONS: assignments/augmented assignments to `self.<attr>`,
   `self.<attr>[...] = ...`, and mutating container methods
   (`.append/.add/.update/...`) on `self.<attr>`, recorded per method
   with whether they sit under a `with`/`async with` whose context
   expression mentions a lock (`lock`/`mutex`/`cond`, case-insensitive).
4. FINDING: an attribute mutated WITHOUT a lock in a thread-entered
   method AND also mutated (locked or not) in a method outside the
   thread closure -> both sides race.  Module-level mutable globals get
   the same treatment with module functions in place of methods.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

SCOPE = ("corrosion_tpu",)

_MUTATORS = {
    "append", "add", "update", "pop", "popitem", "clear", "extend",
    "insert", "remove", "discard", "setdefault", "appendleft",
}
_LOCK_TOKENS = ("lock", "mutex", "cond", "sem")


def _is_lockish(expr_src: str) -> bool:
    low = expr_src.lower()
    return any(tok in low for tok in _LOCK_TOKENS)


def _thread_entry_names(ctx: AnalysisContext, scope) -> Set[str]:
    """Names of functions/methods handed to worker threads anywhere."""
    out: Set[str] = set()

    def callable_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    for sf in ctx.walk(*scope):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            src = ast.unparse(node.func)
            target: Optional[ast.AST] = None
            if src.endswith("to_thread") and node.args:
                target = node.args[0]
            elif src.endswith("run_in_executor") and len(node.args) >= 2:
                target = node.args[1]
            elif src.endswith(("threading.Thread", "Thread")):
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif src.endswith(("threading.Timer", "Timer")):
                if len(node.args) >= 2:
                    target = node.args[1]
            if target is not None:
                name = callable_name(target)
                if name:
                    out.add(name)
    return out


@dataclass
class _Mutation:
    attr: str
    line: int
    locked: bool
    snippet: str


class _MethodScanner(ast.NodeVisitor):
    """Mutations of `self.<attr>` (or of module globals, when
    `owner_names` is given) inside one function, with lock context."""

    def __init__(self, owner_names: Optional[Set[str]] = None):
        self.owner_names = owner_names  # None => scan `self.`
        self.mutations: List[_Mutation] = []
        self.self_calls: Set[str] = set()
        self._lock_depth = 0

    def _target_attr(self, node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
        """('attrname', flagged_node) when node mutates tracked state."""
        if self.owner_names is None:
            # self.X = / self.X[...] = / self.X.mutator()
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr, node
            if isinstance(node, ast.Subscript):
                return self._target_attr(node.value)
        else:
            if isinstance(node, ast.Name) and node.id in self.owner_names:
                return node.id, node
            if isinstance(node, ast.Subscript):
                return self._target_attr(node.value)
        return None

    def _record(self, node: ast.AST, hit: Tuple[str, ast.AST]) -> None:
        self.mutations.append(
            _Mutation(
                attr=hit[0],
                line=getattr(node, "lineno", 0),
                locked=self._lock_depth > 0,
                snippet=Checker.snippet_of(node),
            )
        )

    def _visit_with(self, node) -> None:
        lockish = any(
            _is_lockish(ast.unparse(item.context_expr))
            for item in node.items
        )
        if lockish:
            self._lock_depth += 1
        self.generic_visit(node)
        if lockish:
            self._lock_depth -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            hit = self._target_attr(t)
            # plain rebinding of self.X = ... in __init__-style code is
            # not a container mutation; only subscript stores and
            # augmented ops are read-modify-write.  BUT a rebind of a
            # tracked attr from a thread IS a racy publish when the
            # loop mutates the same attr, so record subscript stores
            # and rebinds alike — __init__ noise is filtered by the
            # "both contexts mutate" rule (no __init__ runs on a
            # worker thread).
            if hit is not None and isinstance(t, ast.Subscript):
                self._record(node, hit)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        hit = self._target_attr(node.target)
        if hit is not None:
            self._record(node, hit)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _MUTATORS:
                hit = self._target_attr(f.value)
                if hit is not None:
                    self._record(node, hit)
            # track self.method() calls for the thread closure
            if (
                isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and self.owner_names is None
            ):
                self.self_calls.add(f.attr)
        self.generic_visit(node)

    # nested defs execute in the same context they were created in
    # often enough (closures run by the enclosing method); keep
    # descending — their mutations belong to the enclosing method's
    # context for this analysis.


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = (
        "state mutated from both worker-thread and event-loop contexts "
        "must hold a lock"
    )

    def __init__(self, scope=SCOPE):
        self.scope = scope

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        thread_entries = _thread_entry_names(ctx, self.scope)

        for sf in ctx.walk(*self.scope):
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    findings.extend(
                        self._check_class(sf, node, thread_entries)
                    )
            findings.extend(self._check_globals(sf, thread_entries))
        return findings

    def _check_class(
        self, sf, cls: ast.ClassDef, thread_entries: Set[str]
    ) -> List[Finding]:
        methods = {
            m.name: m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        scans: Dict[str, _MethodScanner] = {}
        for name, m in methods.items():
            sc = _MethodScanner()
            sc.visit(m)
            scans[name] = sc

        # thread closure within the class.  Only SYNC methods can be
        # to_thread/run_in_executor targets — an `async def` sharing a
        # name with a threaded method elsewhere (every class has a
        # `close`) must not be swept in by the name match.
        threaded: Set[str] = {
            n
            for n in methods
            if n in thread_entries
            and isinstance(methods[n], ast.FunctionDef)
        }
        frontier = list(threaded)
        while frontier:
            n = frontier.pop()
            for callee in scans[n].self_calls:
                if callee in methods and callee not in threaded:
                    threaded.add(callee)
                    frontier.append(callee)
        if not threaded:
            return []

        by_attr_thread: Dict[str, List[Tuple[str, _Mutation]]] = {}
        by_attr_loop: Dict[str, List[Tuple[str, _Mutation]]] = {}
        for name, sc in scans.items():
            side = by_attr_thread if name in threaded else by_attr_loop
            if name == "__init__":
                continue  # construction precedes sharing
            for mut in sc.mutations:
                side.setdefault(mut.attr, []).append((name, mut))

        findings: List[Finding] = []
        for attr, tmuts in sorted(by_attr_thread.items()):
            unlocked = [
                (n, m) for n, m in tmuts if not m.locked
            ]
            loop_side = by_attr_loop.get(attr, [])
            if not unlocked or not loop_side:
                continue
            tn, tm = unlocked[0]
            ln, _lm = loop_side[0]
            findings.append(
                Finding(
                    rule=self.rule,
                    path=sf.path,
                    line=tm.line,
                    symbol=f"{cls.name}.{tn}",
                    message=(
                        f"{cls.name}.{attr} is mutated without a lock in "
                        f"{tn}() (runs on a worker thread via "
                        f"to_thread/run_in_executor) AND in {ln}() on the "
                        "event loop — the r7 GIL-race class; guard both "
                        "sides with one threading.Lock"
                    ),
                    snippet=f"{attr}:{tm.snippet}",
                )
            )
        return findings

    def _check_globals(
        self, sf, thread_entries: Set[str]
    ) -> List[Finding]:
        tree = sf.tree
        globals_: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                v = node.value
                mutable = isinstance(v, (ast.Dict, ast.List, ast.Set))
                if isinstance(v, ast.Call):
                    fn = v.func
                    nm = (
                        fn.id
                        if isinstance(fn, ast.Name)
                        else getattr(fn, "attr", "")
                    )
                    mutable = nm in (
                        "dict", "list", "set", "deque",
                        "defaultdict", "Counter", "OrderedDict",
                    )
                if mutable:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and not t.id.isupper():
                            # UPPER_CASE module constants (lookup tables
                            # populated at import) are excluded
                            globals_.add(t.id)
        if not globals_:
            return []
        fns = {
            n.name: n
            for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        thread_muts: List[Tuple[str, _Mutation]] = []
        loop_muts: List[Tuple[str, _Mutation]] = []
        for name, fn in fns.items():
            sc = _MethodScanner(owner_names=globals_)
            sc.visit(fn)
            side = (
                thread_muts if name in thread_entries else loop_muts
            )
            side.extend((name, m) for m in sc.mutations)
        findings: List[Finding] = []
        flagged: Set[str] = set()
        for tn, tm in thread_muts:
            if tm.locked or tm.attr in flagged:
                continue
            others = [n for n, m in loop_muts if m.attr == tm.attr]
            if not others:
                continue
            flagged.add(tm.attr)
            findings.append(
                Finding(
                    rule=self.rule,
                    path=sf.path,
                    line=tm.line,
                    symbol=tn,
                    message=(
                        f"module global {tm.attr!r} is mutated without "
                        f"a lock in thread-entered {tn}() and in "
                        f"{others[0]}() on the event loop — guard with "
                        "one module lock"
                    ),
                    snippet=f"{tm.attr}:{tm.snippet}",
                )
            )
        return findings

"""async-blocking: blocking calls directly in `async def` bodies.

The bug class: the r10 matcher deaths — synchronous SQLite work on the
event loop starved heartbeats and subscription streams until the whole
pubsub plane cascaded.  The repo's discipline is to route blocking work
through `asyncio.to_thread` / `loop.run_in_executor` / the bounded
`DiffExecutor`; this checker enforces it where the loops live
(`agent/`, `api/`, `pubsub/`).

What counts as blocking when called with the *async function itself* as
the nearest enclosing function (calls inside nested sync `def`s and
lambdas are exempt — those are exactly the bodies handed to worker
threads):

- sqlite cursor/connection work: `.execute/.executemany/.executescript/
  .fetchone/.fetchall/.commit/.rollback`, `sqlite3.connect`
- `time.sleep` (any import alias of the `time` module)
- file I/O: builtin `open`, `Path.read_text/write_text/read_bytes/
  write_bytes/unlink/mkdir/touch`, `shutil.rmtree/copy*/move`,
  `os.remove/rename/replace/makedirs`
- `subprocess.run/call/check_call/check_output/Popen`

Deliberately NOT flagged (documented tolerance): µs-scale stat calls
(`Path.exists/is_dir/iterdir/stat`) and in-memory helpers whose names
collide with the list but resolve to non-blocking imports
(`dataclasses.replace` vs `os.replace` — import-resolved per module).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from corrosion_tpu.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    enclosing_symbols,
)

SCOPE = (
    "corrosion_tpu/agent",
    "corrosion_tpu/api",
    "corrosion_tpu/pubsub",
)

_SQLITE_METHODS = {
    "execute", "executemany", "executescript",
    "fetchone", "fetchall", "commit", "rollback",
}
_PATH_METHODS = {
    "read_text", "write_text", "read_bytes", "write_bytes",
    "unlink", "mkdir", "touch",
}
_SHUTIL_FNS = {"rmtree", "copy", "copy2", "copytree", "move"}
_OS_FNS = {"remove", "rename", "replace", "makedirs", "rmdir"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}


def _module_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> module it refers to ('time', 'os', ...), plus
    names imported FROM modules ('replace' -> 'dataclasses')."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = node.module
    return out


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walks one async def; does NOT descend into nested function
    scopes (sync defs/lambdas are thread bodies, nested async defs get
    their own visit from the checker's top-level walk)."""

    def __init__(self, checker, sf, symbol, aliases, findings):
        self.checker = checker
        self.sf = sf
        self.symbol = symbol
        self.aliases = aliases
        self.findings = findings

    def visit_FunctionDef(self, node):  # nested sync def: thread body
        return

    def visit_AsyncFunctionDef(self, node):
        return  # visited separately with its own symbol

    def visit_Lambda(self, node):
        return

    def _flag(self, node: ast.Call, message: str) -> None:
        self.findings.append(
            Finding(
                rule=AsyncBlockingChecker.rule,
                path=self.sf.path,
                line=node.lineno,
                symbol=self.symbol,
                message=message,
                snippet=Checker.snippet_of(node),
            )
        )

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            base = ast.unparse(f.value)
            base_mod = self.aliases.get(base, base)
            attr = f.attr
            if attr == "sleep" and base_mod == "time":
                self._flag(
                    node,
                    "time.sleep blocks the event loop — "
                    "await asyncio.sleep instead",
                )
            elif attr in _SQLITE_METHODS and base_mod not in (
                "asyncio", "anyio"
            ):
                self._flag(
                    node,
                    f".{attr}() (blocking SQL) directly in an async "
                    "body — route through asyncio.to_thread / the "
                    "DiffExecutor (the r10 matcher-death class)",
                )
            elif attr == "connect" and base_mod == "sqlite3":
                self._flag(
                    node,
                    "sqlite3.connect opens and locks a database file "
                    "on the event loop — open it on a worker thread",
                )
            elif attr in _PATH_METHODS and base_mod in ("Path", "pathlib"):
                self._flag(
                    node,
                    f"Path.{attr} is synchronous file I/O on the "
                    "event loop — wrap in asyncio.to_thread",
                )
            elif attr in _SHUTIL_FNS and base_mod == "shutil":
                self._flag(
                    node,
                    f"shutil.{attr} is synchronous (possibly large) "
                    "file-tree I/O on the event loop — wrap in "
                    "asyncio.to_thread",
                )
            elif attr in _OS_FNS and base_mod == "os":
                self._flag(
                    node,
                    f"os.{attr} is synchronous file I/O on the event "
                    "loop — wrap in asyncio.to_thread",
                )
            elif attr in _SUBPROCESS_FNS and base_mod == "subprocess":
                self._flag(
                    node,
                    f"subprocess.{attr} blocks the loop — use "
                    "asyncio.create_subprocess_exec",
                )
            # Path(...).read_text() — receiver is a Call, not a Name
            elif attr in _PATH_METHODS and isinstance(f.value, ast.Call):
                callee = ast.unparse(f.value.func)
                if callee == "Path" or callee.endswith(".Path"):
                    self._flag(
                        node,
                        f"Path.{attr} is synchronous file I/O on the "
                        "event loop — wrap in asyncio.to_thread",
                    )
        elif isinstance(f, ast.Name):
            mod = self.aliases.get(f.id)
            if f.id == "open" and mod is None:
                self._flag(
                    node,
                    "builtin open() in an async body — wrap the file "
                    "work in asyncio.to_thread",
                )
            elif f.id == "sleep" and mod == "time":
                self._flag(
                    node,
                    "time.sleep blocks the event loop — "
                    "await asyncio.sleep instead",
                )
            elif f.id == "rmtree" and mod == "shutil":
                self._flag(
                    node,
                    "shutil.rmtree on the event loop — wrap in "
                    "asyncio.to_thread",
                )
        self.generic_visit(node)


class AsyncBlockingChecker(Checker):
    rule = "async-blocking"
    description = (
        "no blocking SQL / sleeps / file I/O directly in async def "
        "bodies under agent/, api/, pubsub/"
    )

    def __init__(self, scope=SCOPE):
        self.scope = scope

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for sf in ctx.walk(*self.scope):
            aliases = _module_aliases(sf.tree)
            symbols = enclosing_symbols(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    visitor = _AsyncBodyVisitor(
                        self,
                        sf,
                        symbols.get(node, node.name),
                        aliases,
                        findings,
                    )
                    for stmt in node.body:
                        visitor.visit(stmt)
        return findings

"""actuator-discipline: every registered remediation actuator is safe
to fire unattended.

The bug class (r22 remediation plane): an actuator is a lever the
supervisor pulls WITHOUT a human in the loop, so a sloppy one is worse
than no automation — an uncooled actuator flaps (act, fail to help,
act again next tick, forever); an actuator blind to the chaos CENSUS
"fixes" drill injections and poisons the A/B recovery numbers; an
actuator that leaves no flight frame makes the post-incident question
"what did the machine do to itself?" unanswerable.

The discipline, checkable per `Actuator(...)` registration:

- `cooldown_secs=` must be present, and when it is a literal it must
  be positive (config-sourced expressions like
  ``cfg.sync_cooldown_secs`` are accepted — their positivity is the
  config's contract).
- `act=` must name a module-level function (resolvable for this scan —
  lambdas and imported callables hide the body), and that body must
  contain BOTH disciplined calls:
  - ``CENSUS.snapshot()`` — the drill marker check against the chaos
    census, so every action/event records whether it ran under an
    injected fault;
  - ``FLIGHT.record_host_frame(...)`` — the flight-recorder emit, so
    incident dumps carry the action.

Deliberately NOT flagged: `Actuator(...)` constructions outside
`corrosion_tpu/` (tests build synthetic probe actuators on purpose)
and the `Actuator` dataclass definition itself.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from corrosion_tpu.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    enclosing_symbols,
)

SCOPE = ("corrosion_tpu",)

# the two calls an act body must make, receiver -> method
_REQUIRED_CALLS = {
    "CENSUS": "snapshot",
    "FLIGHT": "record_host_frame",
}


def _module_functions(tree: ast.AST) -> Dict[str, ast.AST]:
    return {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _body_calls(fn: ast.AST, receiver: str, method: str) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == method
            and isinstance(f.value, ast.Name)
            and f.value.id == receiver
        ):
            return True
    return False


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class ActuatorDisciplineChecker(Checker):
    rule = "actuator-discipline"
    description = (
        "every Actuator(...) registration must carry a positive "
        "cooldown, and its act body must check the chaos CENSUS "
        "(drill marker) and emit a FLIGHT frame (remediation plane "
        "safety discipline)"
    )

    def __init__(self, scope=SCOPE):
        self.scope = scope

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for sf in ctx.walk(*self.scope):
            symbols = enclosing_symbols(sf.tree)
            funcs = _module_functions(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "Actuator"
                ):
                    continue
                findings.extend(
                    self._check_registration(sf, symbols, funcs, node)
                )
        return findings

    def _check_registration(
        self, sf, symbols, funcs, node: ast.Call
    ) -> List[Finding]:
        out: List[Finding] = []

        def flag(message: str) -> None:
            out.append(
                Finding(
                    rule=self.rule,
                    path=sf.path,
                    line=node.lineno,
                    symbol=symbols.get(node, "<module>"),
                    message=message,
                    snippet=self.snippet_of(node),
                )
            )

        name_kw = _kwarg(node, "name")
        label = (
            name_kw.value
            if isinstance(name_kw, ast.Constant)
            and isinstance(name_kw.value, str)
            else "<actuator>"
        )

        cd = _kwarg(node, "cooldown_secs")
        if cd is None:
            flag(
                f"actuator {label!r} registered without cooldown_secs "
                "— an uncooled actuator flaps (acts every supervisor "
                "tick); pass a positive cooldown"
            )
        elif isinstance(cd, ast.Constant) and (
            not isinstance(cd.value, (int, float))
            or isinstance(cd.value, bool)
            or cd.value <= 0
        ):
            flag(
                f"actuator {label!r} has non-positive cooldown_secs="
                f"{cd.value!r} — the cooldown gate is what stops "
                "act/flap loops; use a positive number"
            )

        act = _kwarg(node, "act")
        fn = (
            funcs.get(act.id)
            if isinstance(act, ast.Name)
            else None
        )
        if fn is None:
            flag(
                f"actuator {label!r} act= is not a module-level "
                "function (lambda/imported callable) — the discipline "
                "scan cannot verify its CENSUS drill check and FLIGHT "
                "emit; define the act in this module"
            )
            return out
        for receiver, method in _REQUIRED_CALLS.items():
            if not _body_calls(fn, receiver, method):
                what = (
                    "chaos drill marker check"
                    if receiver == "CENSUS"
                    else "flight-recorder emit"
                )
                flag(
                    f"actuator {label!r} act `{fn.name}` never calls "
                    f"{receiver}.{method}(...) — every act needs the "
                    f"{what} so unattended actions stay attributable"
                )
        return out

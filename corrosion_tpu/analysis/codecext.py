"""codec-ext: every version-gated codec extension is exhaustive.

The bug class: the r11/r12 trailing-ext pattern (envelope ext v1/v2 in
`types/codec.py`, `_SWIM_EXT_V1` in `net/gossip_codec.py`) demands that
each version gate have BOTH directions implemented — a write path that
emits the gated block and a read path that tolerates its absence — and
an old<->new compat test pinning both, because the compat story is
re-proved by hand every PR that touches an envelope.  A gate with a
writer and no reader (or vice versa) ships a one-way wire format; a
gate no test references loses its compat pin silently the next time the
test file is reorganized.

Mechanics: module-level integer constants matching `*_EXT_V<n>` (or
`_EXT_*` / `*_VERSION_*` gates, conservatively: name contains "EXT" and
ends in a version digit) are collected from the codec modules.  For
each gate:

- WRITE PATH: the constant is referenced inside a function whose name
  contains "encode"/"write";
- READ PATH: referenced inside a function whose name contains
  "decode"/"read";
- COMPAT TEST: the gate's referencing functions — plus their
  same-module callers, one hop, since ext helpers are private
  (`_write_envelope_ext` is reached via `encode_uni_payload`) — include
  at least one name that appears in the configured test files.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

CODEC_FILES = (
    "corrosion_tpu/types/codec.py",
    "corrosion_tpu/net/gossip_codec.py",
)
TEST_FILES = ("tests/test_codec.py", "tests/test_net.py")

_GATE_RE = re.compile(r"^_?[A-Z0-9_]*EXT[A-Z0-9_]*?_?V?\d+$")


def _gate_constants(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Name)
                and _GATE_RE.match(t.id)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
            ):
                out[t.id] = node.lineno
    return out


class CodecExtChecker(Checker):
    rule = "codec-ext"
    description = (
        "every version-gated codec ext has a write path, a read path "
        "and a compat test referencing it"
    )

    def __init__(self, codec_files=CODEC_FILES, test_files=TEST_FILES):
        self.codec_files = codec_files
        self.test_files = test_files

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        test_text = "\n".join(
            ctx.read_text(t) for t in self.test_files
        )
        for rel in self.codec_files:
            sf = ctx.file(rel)
            if sf is None:
                continue
            gates = _gate_constants(sf.tree)
            if not gates:
                continue
            fns = {
                n.name: n
                for n in sf.tree.body
                if isinstance(n, ast.FunctionDef)
            }
            # function -> referenced gate names; function -> called fns
            refs: Dict[str, Set[str]] = {}
            calls: Dict[str, Set[str]] = {}
            for name, fn in fns.items():
                r: Set[str] = set()
                c: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Name):
                        if node.id in gates:
                            r.add(node.id)
                        if node.id in fns:
                            c.add(node.id)
                refs[name] = r
                calls[name] = c
            callers: Dict[str, Set[str]] = {n: set() for n in fns}
            for name, callees in calls.items():
                for callee in callees:
                    callers[callee].add(name)

            for gate, line in sorted(gates.items()):
                writers = [
                    n
                    for n, r in refs.items()
                    if gate in r and ("encode" in n or "write" in n)
                ]
                readers = [
                    n
                    for n, r in refs.items()
                    if gate in r and ("decode" in n or "read" in n)
                ]
                if not writers:
                    findings.append(
                        Finding(
                            rule=self.rule,
                            path=rel,
                            line=line,
                            symbol=gate,
                            message=(
                                f"version gate {gate} has no write path "
                                "(no encode*/write* function references "
                                "it) — a read-only gate is dead compat "
                                "surface or a missing emitter"
                            ),
                            snippet=f"{gate}:no-writer",
                        )
                    )
                if not readers:
                    findings.append(
                        Finding(
                            rule=self.rule,
                            path=rel,
                            line=line,
                            symbol=gate,
                            message=(
                                f"version gate {gate} has no read path "
                                "(no decode*/read* function references "
                                "it) — new peers would emit bytes old "
                                "and new readers both drop"
                            ),
                            snippet=f"{gate}:no-reader",
                        )
                    )
                # compat test: referencing fns + their 1-hop callers
                surface = {
                    n for n, r in refs.items() if gate in r
                }
                for n in list(surface):
                    surface |= callers.get(n, set())
                tested = any(
                    re.search(rf"\b{re.escape(n)}\b", test_text)
                    for n in surface
                ) or gate in test_text
                if surface and not tested:
                    findings.append(
                        Finding(
                            rule=self.rule,
                            path=rel,
                            line=line,
                            symbol=gate,
                            message=(
                                f"version gate {gate}: none of its "
                                "read/write functions "
                                f"({', '.join(sorted(surface))}) appear "
                                f"in {' / '.join(self.test_files)} — "
                                "the old<->new compat pin is missing"
                            ),
                            snippet=f"{gate}:no-compat-test",
                        )
                    )
        return findings

"""finalize-parity: the native finalize ABI stays in lockstep with its
Python glue.

The bug class (new with r24's `CORRO_FINALIZE=native`): the local-commit
decision loop now exists in TWO languages — the columnar Python phase B
(`_phase_b_columnar`, store/crdt.py) and its C++ transcription
(`crdt_finalize_batch`, native/crdt_batch.cpp) — glued by a hand-rolled
flat-array ABI (`_phase_b_native`).  The randomized equivalence pins in
tests/test_finalize_batch.py prove value parity for the mixes they
generate, but only on hosts that can BUILD the .so; a structural drift
(the cpp sentinel id diverging from the Python intern convention, an
ABI field added on one side only, the counted columnar fallback quietly
dropped) would ship green on a no-compiler CI host and corrupt clocks
on the first host with g++.

Mechanics (Python side pure AST; cpp side raw-text markers via
`ctx.read_text`, the COMPONENTS.md precedent — no C parser exists
here and none is needed for lockstep pins):

- GLUE SIDE: when `_finalize_engine` declares "native", the
  `_phase_b_native` builder must exist, reference `SENTINEL` and the
  `write_change_cells` batch encoder (the same conventions
  capture-parity pins on the columnar engine), delegate to
  `_phase_b_columnar` for its fallback, and count that fallback on the
  `corro.write.finalize.native.unavailable` series.  The module must
  pin `_NATIVE_FINALIZE_ABI` and `_NATIVE_SENTINEL_CID` as int
  literals — they are the Python half of the cross-language contract.
- NATIVE SIDE: native/crdt_batch.cpp must export `crdt_finalize_batch`
  under `extern "C"`, `#define FINALIZE_ABI_VERSION` equal to the
  Python `_NATIVE_FINALIZE_ABI`, define `FIN_CID_SENTINEL` equal to
  `_NATIVE_SENTINEL_CID`, and still contain the even/odd causal-length
  decision arithmetic (`% 2 == 0` live-row tests and the `& 1` delete
  bump) — the convention every engine's emitted `cl` encodes.

Findings anchor on the side owning the drifted half — the store module
(missing builder / dropped fallback / missing pins) or the cpp file
(missing export / ABI or sentinel drift) — where a
`# corro: noqa[finalize-parity]` (or the cpp-comment equivalent on the
flagged line) belongs next to the contract being waived.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

CRDT_FILE = "corrosion_tpu/store/crdt.py"
CPP_FILE = "native/crdt_batch.cpp"

UNAVAILABLE_METRIC = "corro.write.finalize.native.unavailable"

_ABI_RE = re.compile(r"#define\s+FINALIZE_ABI_VERSION\s+(-?\d+)")
_SENT_RE = re.compile(
    r"FIN_CID_SENTINEL\s*=\s*(-?\d+)"
)
_EXPORT_RE = re.compile(
    r'extern\s+"C"[^;{]*\bint\s+crdt_finalize_batch\s*\(', re.S
)


def _find_function(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef) and n.name == name:
            return n
    return None


def _module_int(tree: ast.Module, name: str) -> Optional[int]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                v = node.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return v.value
                if (
                    isinstance(v, ast.UnaryOp)
                    and isinstance(v.op, ast.USub)
                    and isinstance(v.operand, ast.Constant)
                    and isinstance(v.operand.value, int)
                ):
                    return -v.operand.value
    return None


def _string_constants(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


class FinalizeParityChecker(Checker):
    rule = "finalize-parity"
    description = (
        "the native finalize ABI (crdt_finalize_batch) stays in "
        "lockstep with its Python glue and fallback accounting"
    )

    def __init__(self, crdt=CRDT_FILE, cpp=CPP_FILE):
        self.crdt = crdt
        self.cpp = cpp

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        crdt_sf = ctx.file(self.crdt)
        if crdt_sf is None:
            return findings

        engine_fn = _find_function(crdt_sf.tree, "_finalize_engine")
        declares_native = engine_fn is not None and "native" in set(
            _string_constants(engine_fn)
        )
        if not declares_native:
            return findings  # no native engine declared, nothing to pin

        def py_finding(line, symbol, message, snippet):
            findings.append(
                Finding(
                    rule=self.rule, path=self.crdt, line=line,
                    symbol=symbol, message=message, snippet=snippet,
                )
            )

        # -- glue side ------------------------------------------------------
        native_fn = _find_function(crdt_sf.tree, "_phase_b_native")
        if native_fn is None:
            py_finding(
                engine_fn.lineno, "_finalize_engine",
                "`_finalize_engine` accepts 'native' but no "
                "`_phase_b_native` builder exists — the selected engine "
                "would be undefined",
                "missing-native-builder",
            )
            return findings
        names = {
            n.id for n in ast.walk(native_fn) if isinstance(n, ast.Name)
        }
        attrs = {
            n.attr for n in ast.walk(native_fn)
            if isinstance(n, ast.Attribute)
        }
        if "SENTINEL" not in names:
            py_finding(
                native_fn.lineno, "_phase_b_native",
                "`_phase_b_native` never references SENTINEL — the "
                "sentinel-cid intern convention has drifted away from "
                "the row-lifecycle contract the other engines share",
                "native-sentinel-drift",
            )
        if "write_change_cells" not in names:
            py_finding(
                native_fn.lineno, "_phase_b_native",
                "`_phase_b_native` does not encode through "
                "`write_change_cells` — cell bytes would fork from the "
                "single-cell truth the equivalence pins assume",
                "native-encoder-drift",
            )
        if "_phase_b_columnar" not in names | attrs:
            py_finding(
                native_fn.lineno, "_phase_b_native",
                "`_phase_b_native` no longer delegates to "
                "`_phase_b_columnar` — no-compiler hosts would lose "
                "their finalize engine instead of degrading",
                "native-fallback-drift",
            )
        if UNAVAILABLE_METRIC not in set(_string_constants(native_fn)):
            py_finding(
                native_fn.lineno, "_phase_b_native",
                "`_phase_b_native` does not count its columnar "
                f"fallback on `{UNAVAILABLE_METRIC}` — degraded hosts "
                "would be invisible to fleet dashboards",
                "native-fallback-uncounted",
            )

        py_abi = _module_int(crdt_sf.tree, "_NATIVE_FINALIZE_ABI")
        py_sent = _module_int(crdt_sf.tree, "_NATIVE_SENTINEL_CID")
        for pin, name in ((py_abi, "_NATIVE_FINALIZE_ABI"),
                          (py_sent, "_NATIVE_SENTINEL_CID")):
            if pin is None:
                py_finding(
                    1, "<module>",
                    f"`{name}` int pin is missing from the store module "
                    "— the Python half of the native finalize contract "
                    "is undeclared",
                    f"missing-pin:{name}",
                )

        # -- native side ----------------------------------------------------
        text = ctx.read_text(self.cpp)
        if not text:
            py_finding(
                native_fn.lineno, "_phase_b_native",
                f"`{self.cpp}` is missing while `_finalize_engine` "
                "declares 'native' — the engine cannot exist",
                "missing-native-source",
            )
            return findings

        def cpp_finding(line, symbol, message, snippet):
            findings.append(
                Finding(
                    rule=self.rule, path=self.cpp, line=line,
                    symbol=symbol, message=message, snippet=snippet,
                )
            )

        m = _EXPORT_RE.search(text)
        if m is None:
            cpp_finding(
                1, "crdt_finalize_batch",
                "no `extern \"C\"` export of `crdt_finalize_batch` — "
                "the ctypes glue would load a library without its "
                "entrypoint",
                "missing-native-export",
            )
        m = _ABI_RE.search(text)
        if m is None or (py_abi is not None and int(m.group(1)) != py_abi):
            cpp_finding(
                _line_of(text, m.start()) if m else 1,
                "FINALIZE_ABI_VERSION",
                "FINALIZE_ABI_VERSION "
                + (f"= {m.group(1)} " if m else "is missing ")
                + f"while the Python glue pins _NATIVE_FINALIZE_ABI = "
                f"{py_abi} — the flat-array layout may have changed on "
                "one side only",
                "abi-version-drift",
            )
        m = _SENT_RE.search(text)
        if m is None or (py_sent is not None and int(m.group(1)) != py_sent):
            cpp_finding(
                _line_of(text, m.start()) if m else 1,
                "FIN_CID_SENTINEL",
                "FIN_CID_SENTINEL "
                + (f"= {m.group(1)} " if m else "is missing ")
                + f"while the Python glue interns SENTINEL as "
                f"{py_sent} — sentinel cells would be treated as a "
                "regular column on one side",
                "sentinel-id-drift",
            )
        if "% 2 == 0" not in text or "& 1" not in text:
            cpp_finding(
                1, "crdt_finalize_batch",
                "the even/odd causal-length decision arithmetic "
                "(`% 2 == 0` live tests, `& 1` delete bump) is gone "
                "from the cpp decision loop — the cl parity convention "
                "every engine encodes would fork",
                "decision-arithmetic-missing",
            )
        return findings

"""metrics-doc: emitted series <-> COMPONENTS.md table, both directions.

The former `scripts/lint_metrics.py` (r7), folded into the corro-analyze
framework so one driver runs every rule — the shim at the old path
re-exports `scan_call_sites`/`parse_components_table`/`lint` unchanged
for existing callers.  The contract is unchanged: every series the code
can emit (`<registry>.counter/gauge/histogram/latency("literal")`, with
f-string names matched as one-label wildcards) must have a row in the
COMPONENTS.md observability table, and every row must still have an
emitting call site — the inventory IS the contract, dashboards must not
rot silently.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

_CALL_RE = re.compile(
    r"\.(counter|gauge|histogram|latency)\(\s*(f?)\"([^\"\n]+)\"", re.S
)
_TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")

TABLE_BEGIN = "<!-- metrics-table:begin -->"
TABLE_END = "<!-- metrics-table:end -->"

SCAN_DIRS = ("corrosion_tpu", "scripts")
COMPONENTS = "COMPONENTS.md"


def scan_call_sites(
    root: str,
) -> Tuple[Dict[str, Set[str]], List[str]]:
    """(literal series name -> emitting files, f-string wildcard
    regexes) — regex-based on raw text, deliberately: call sites inside
    strings/templates counted the same way the r7 tool did, so the fold
    is drop-in."""
    literals: Dict[str, Set[str]] = {}
    wildcards: List[str] = []
    for top in SCAN_DIRS:
        for dirpath, _dirs, files in os.walk(os.path.join(root, top)):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                for m in _CALL_RE.finditer(text):
                    is_f, name = m.group(2), m.group(3)
                    if is_f:
                        # {expr} holes become wildcards over one label
                        # segment; the pattern must cover >= 1 table row
                        pat = "^" + re.sub(
                            r"\\\{[^}]*\\\}", "[^.]+", re.escape(name)
                        ) + "$"
                        wildcards.append(pat)
                    else:
                        literals.setdefault(name, set()).add(rel)
    return literals, wildcards


def parse_components_table(root: str) -> List[str]:
    """Backticked series names from column 1 of the fenced table."""
    path = os.path.join(root, COMPONENTS)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if TABLE_BEGIN not in text or TABLE_END not in text:
        raise SystemExit(
            f"{COMPONENTS} is missing the {TABLE_BEGIN}/{TABLE_END} "
            "markers around the observability table"
        )
    section = text.split(TABLE_BEGIN, 1)[1].split(TABLE_END, 1)[0]
    names = []
    for line in section.splitlines():
        m = _TABLE_ROW_RE.match(line.strip())
        if m:
            names.append(m.group(1))
    return names


def lint(root: str) -> List[str]:
    """Drift complaints (empty = clean) — same strings the r7 tool
    printed, so operators' muscle memory and the shim both survive."""
    literals, wildcards = scan_call_sites(root)
    table = parse_components_table(root)
    table_set = set(table)
    problems: List[str] = []

    dupes = {n for n in table_set if table.count(n) > 1}
    for n in sorted(dupes):
        problems.append(f"duplicate table row: {n}")

    for name in sorted(literals):
        if name not in table_set:
            where = ", ".join(sorted(literals[name]))
            problems.append(
                f"emitted but undocumented: {name} ({where}) — add a row "
                "to the COMPONENTS.md observability table"
            )

    covered_by_wildcard: Set[str] = set()
    for pat in wildcards:
        hits = {n for n in table_set if re.match(pat, n)}
        if not hits:
            problems.append(
                f"f-string call site matches no table row: /{pat}/"
            )
        covered_by_wildcard |= hits

    for name in sorted(table_set):
        if name not in literals and name not in covered_by_wildcard:
            problems.append(
                f"documented but never emitted: {name} — remove the row "
                "or restore the call site"
            )
    return problems


class MetricsDocChecker(Checker):
    rule = "metrics-doc"
    description = (
        "metric series emitted by the tree and the COMPONENTS.md "
        "observability table match exactly, both directions"
    )

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        return [
            Finding(
                rule=self.rule,
                path=COMPONENTS,
                line=0,
                symbol="observability-table",
                message=problem,
                snippet=problem[:72],
            )
            for problem in lint(ctx.root)
        ]

"""capture-parity: trigger DDL and direct-capture metadata in lockstep.

The bug class (new with r15's direct change capture): local writes are
captured by TWO parallel implementations — the generated AFTER-trigger
DDL (`CrdtStore._create_triggers`, store/crdt.py) for raw SQL, and the
in-memory statement planner (store/capture.py) for recognized shapes —
and the randomized equivalence test only proves the shapes it happens
to generate.  A structural drift (a fourth trigger kind added without a
capture counterpart, a `_cells_*` builder iterating a different column
source than the trigger DDL, a changed delete-marker spelling) would
silently fork the replication streams for some statement class.

Mechanics (pure AST, no imports of the checked modules):

- TRIGGER SIDE: `_create_triggers`/`_drop_triggers` are scanned for the
  `__crdt_<suffix>` trigger-name suffixes (string constants, including
  f-string fragments), the column-source attributes they iterate
  (`non_pk_cols`, `pk_cols`), and the `{SENTINEL}X` delete-marker
  f-string (a FormattedValue of SENTINEL immediately followed by a
  constant starting with "X").
- CAPTURE SIDE: `CAPTURED_KINDS` must be a dict literal whose values
  cover every trigger suffix; every kind needs a `_cells_<kind>`
  builder; the insert/update builders must reference the same
  `non_pk_cols` column source the DDL iterates; `DELETE_MARKER` must be
  the `SENTINEL + "X"` expression matching the DDL marker.
- FINALIZE SIDE (r21): the columnar phase B is a THIRD consumer of the
  same conventions — `_dedupe_pending` must still recognize the
  `SENTINEL + "X"` marker the DDL emits (it is how captured deletes
  reach the finalize at all), and `_phase_b_columnar` must reference
  `SENTINEL` (the sentinel-kind decision batch) and the
  `write_change_cells` batch encoder so the columnar builders cannot
  drift away from the trigger/capture cell conventions unnoticed.

Findings anchor on the module owning the drifted contract — the capture
module (CAPTURED_KINDS / DELETE_MARKER / the drifting `_cells_*` def)
or the store module (`_dedupe_pending` / `_phase_b_columnar`) — where a
`# corro: noqa[capture-parity]` belongs next to the contract being
waived.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from corrosion_tpu.analysis.core import AnalysisContext, Checker, Finding

CRDT_FILE = "corrosion_tpu/store/crdt.py"
CAPTURE_FILE = "corrosion_tpu/store/capture.py"

# trigger-NAME fragments only (`..."{name}__crdt_ins"...`): the closing
# quote keeps internal-table references (__crdt_pending, __crdt_clock)
# out of the kind set
_SUFFIX_RE = re.compile(r'__crdt_([a-z]+)"')


def _string_constants(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value


def _attr_names(node: ast.AST) -> Set[str]:
    return {
        n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)
    }


def _has_sentinel_x(node: ast.AST) -> bool:
    """An f-string fragment `...{SENTINEL}X...` (the delete marker)."""
    for n in ast.walk(node):
        if not isinstance(n, ast.JoinedStr):
            continue
        parts = n.values
        for i, p in enumerate(parts[:-1]):
            if (
                isinstance(p, ast.FormattedValue)
                and isinstance(p.value, ast.Name)
                and p.value.id == "SENTINEL"
            ):
                nxt = parts[i + 1]
                if (
                    isinstance(nxt, ast.Constant)
                    and isinstance(nxt.value, str)
                    and nxt.value.startswith("X")
                ):
                    return True
    return False


def _find_function(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef) and n.name == name:
            return n
    return None


def _module_assign(tree: ast.Module, name: str) -> Optional[ast.Assign]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                return node
    return None


class CaptureParityChecker(Checker):
    rule = "capture-parity"
    description = (
        "trigger-DDL kinds/column sources/markers stay in lockstep with "
        "the direct-capture statement metadata"
    )

    def __init__(self, crdt=CRDT_FILE, capture=CAPTURE_FILE):
        self.crdt = crdt
        self.capture = capture

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        crdt_sf = ctx.file(self.crdt)
        cap_sf = ctx.file(self.capture)
        if crdt_sf is None or cap_sf is None:
            return findings

        def cap_finding(line, symbol, message, snippet):
            findings.append(
                Finding(
                    rule=self.rule, path=self.capture, line=line,
                    symbol=symbol, message=message, snippet=snippet,
                )
            )

        # -- trigger side ---------------------------------------------------
        creator = _find_function(crdt_sf.tree, "_create_triggers")
        dropper = _find_function(crdt_sf.tree, "_drop_triggers")
        ddl_suffixes: Set[str] = set()
        ddl_attrs: Set[str] = set()
        ddl_marker = False
        for fn in (creator, dropper):
            if fn is None:
                continue
            for s in _string_constants(fn):
                ddl_suffixes.update(_SUFFIX_RE.findall(s))
        if dropper is not None:
            # the drop loop's ("ins", "upd", "del") tuple names every
            # generated trigger kind even where the name is split
            # across f-string fragments in the creator
            for n in ast.walk(dropper):
                if isinstance(n, (ast.Tuple, ast.List)):
                    for el in n.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            ddl_suffixes.add(el.value)
        if creator is not None:
            ddl_attrs = _attr_names(creator)
            ddl_marker = _has_sentinel_x(creator)
        if creator is None or not ddl_suffixes:
            return findings  # nothing to be in lockstep with

        # -- capture side ---------------------------------------------------
        kinds_assign = _module_assign(cap_sf.tree, "CAPTURED_KINDS")
        kinds: Dict[str, str] = {}
        kinds_line = 1
        if kinds_assign is None or not isinstance(
            kinds_assign.value, ast.Dict
        ):
            cap_finding(
                1, "<module>",
                "CAPTURED_KINDS dict literal is missing — the "
                "capture module no longer declares which trigger "
                "kinds it mirrors",
                "CAPTURED_KINDS:missing",
            )
        else:
            kinds_line = kinds_assign.lineno
            for k, v in zip(
                kinds_assign.value.keys, kinds_assign.value.values
            ):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    kinds[str(k.value)] = str(v.value)

        covered = set(kinds.values())
        for suffix in sorted(ddl_suffixes):
            if suffix not in covered:
                cap_finding(
                    kinds_line, "CAPTURED_KINDS",
                    f"trigger kind '__crdt_{suffix}' (store/crdt.py "
                    "_create_triggers) has no CAPTURED_KINDS entry — "
                    "the direct-capture path would silently miss the "
                    "statement class this trigger logs",
                    f"uncovered-trigger-kind:{suffix}",
                )
        for kind, suffix in sorted(kinds.items()):
            if suffix not in ddl_suffixes:
                cap_finding(
                    kinds_line, "CAPTURED_KINDS",
                    f"CAPTURED_KINDS maps '{kind}' to trigger suffix "
                    f"'{suffix}' which no generated trigger uses — "
                    "stale capture metadata",
                    f"stale-capture-kind:{kind}",
                )

        # per-kind cell builders + column-source lockstep
        for kind in sorted(kinds):
            fn = _find_function(cap_sf.tree, f"_cells_{kind}")
            if fn is None:
                cap_finding(
                    kinds_line, "CAPTURED_KINDS",
                    f"no `_cells_{kind}` builder for captured kind "
                    f"'{kind}' — the trigger body has no in-memory "
                    "counterpart",
                    f"missing-cells-builder:{kind}",
                )
                continue
            if kind in ("insert", "update") and "non_pk_cols" in ddl_attrs:
                if "non_pk_cols" not in _attr_names(fn):
                    cap_finding(
                        fn.lineno, f"_cells_{kind}",
                        f"`_cells_{kind}` does not iterate "
                        "`non_pk_cols` while the trigger DDL does — "
                        "the two capture paths emit different column "
                        "sets or orders",
                        f"column-source-drift:{kind}",
                    )

        # delete-marker lockstep
        if ddl_marker:
            marker = _module_assign(cap_sf.tree, "DELETE_MARKER")
            ok = False
            line = kinds_line
            if marker is not None:
                line = marker.lineno
                v = marker.value
                ok = (
                    isinstance(v, ast.BinOp)
                    and isinstance(v.op, ast.Add)
                    and isinstance(v.left, ast.Name)
                    and v.left.id == "SENTINEL"
                    and isinstance(v.right, ast.Constant)
                    and v.right.value == "X"
                )
            if not ok:
                cap_finding(
                    line, "DELETE_MARKER",
                    "DELETE_MARKER is not `SENTINEL + \"X\"` while the "
                    "trigger DDL emits the '{SENTINEL}X' row-delete "
                    "marker — deletes would fork between the paths",
                    "delete-marker-drift",
                )

        # -- finalize side (r21 columnar phase B lockstep) ------------------
        def crdt_finding(line, symbol, message, snippet):
            findings.append(
                Finding(
                    rule=self.rule, path=self.crdt, line=line,
                    symbol=symbol, message=message, snippet=snippet,
                )
            )

        def _has_marker_binop(fn) -> bool:
            return any(
                isinstance(n, ast.BinOp)
                and isinstance(n.op, ast.Add)
                and isinstance(n.left, ast.Name)
                and n.left.id == "SENTINEL"
                and isinstance(n.right, ast.Constant)
                and n.right.value == "X"
                for n in ast.walk(fn)
            )

        if ddl_marker:
            dedupe = _find_function(crdt_sf.tree, "_dedupe_pending")
            if dedupe is not None and not _has_marker_binop(dedupe):
                crdt_finding(
                    dedupe.lineno, "_dedupe_pending",
                    "`_dedupe_pending` no longer recognizes the "
                    "`SENTINEL + \"X\"` marker the trigger DDL emits — "
                    "captured row deletes would never reach finalize",
                    "finalize-marker-drift",
                )

        engine_fn = _find_function(crdt_sf.tree, "_finalize_engine")
        columnar = _find_function(crdt_sf.tree, "_phase_b_columnar")
        declares_columnar = engine_fn is not None and "columnar" in set(
            _string_constants(engine_fn)
        )
        if declares_columnar and columnar is None:
            crdt_finding(
                engine_fn.lineno, "_finalize_engine",
                "`_finalize_engine` accepts 'columnar' but no "
                "`_phase_b_columnar` builder exists — the default "
                "finalize engine would be undefined",
                "missing-columnar-builder",
            )
        if columnar is not None:
            names = {
                n.id for n in ast.walk(columnar)
                if isinstance(n, ast.Name)
            }
            if "SENTINEL" not in names:
                crdt_finding(
                    columnar.lineno, "_phase_b_columnar",
                    "`_phase_b_columnar` never references SENTINEL — "
                    "the sentinel-kind decision batch has drifted away "
                    "from the trigger/capture row-lifecycle convention",
                    "columnar-sentinel-drift",
                )
            if "write_change_cells" not in names:
                crdt_finding(
                    columnar.lineno, "_phase_b_columnar",
                    "`_phase_b_columnar` does not encode through "
                    "`write_change_cells` — cell bytes would fork from "
                    "the `write_change_fields` single-cell truth the "
                    "equivalence pins assume",
                    "columnar-encoder-drift",
                )
        return findings

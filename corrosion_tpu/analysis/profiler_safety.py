"""profiler-safety: code reachable from the stack sampler's hot path
stays allocation-free, lock-free (but for the sanctioned fold lock) and
asyncio-free.

The bug class (r23 continuous profiling plane): the sampler thread runs
inside every other subsystem's timing — `sys._current_frames()` at
67 Hz while the event loop schedules, the store commits and the fanout
drains.  A sampler that takes the wrong lock can deadlock against the
thread it is observing (the classic in-process profiler failure); one
that calls asyncio APIs races the loop it samples; one that allocates
per sample (comprehensions, f-strings, sorting, json, logging) turns
the observer into measurable load and invalidates its own overhead
budget.  None of these survive review as a *convention* — the r22
actuator-discipline lesson is that unattended machinery needs its
safety contract CHECKED, not documented.

The contract, enforced over `runtime/profiler.py` +
`runtime/profstore.py`:

- the scan walks the call graph reachable from `sample_once` by name:
  a called name (including simple `alias = obj.method` rebinding) that
  matches a function defined in the scanned files joins the reachable
  set.  Functions suffixed ``_coldpath`` are exempt BY NAME — they are
  bounded by cache size or window cadence (tid-cache miss, frame
  intern miss, window seal, the per-block adapt pass), not by the
  sample rate, and the suffix makes the exemption grep-able.
- inside reachable code the checker rejects:
  - any ``asyncio.*`` call (the `_current_tasks` dict read is the
    sanctioned lock-free alternative),
  - acquiring any lock other than ``_fold_lock`` (``with <lock>:`` or
    ``.acquire()``),
  - traversing ``agent`` / ``.store`` objects (the sampler observes
    stacks, never the object graph they run on),
  - per-sample allocation beyond the fold-map update: comprehensions,
    generator expressions, f-strings, ``sorted``, ``json.*``,
    logging, and registry/METRICS calls (metrics flush belongs in
    `_adapt_coldpath`).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from corrosion_tpu.analysis.core import (
    AnalysisContext,
    Checker,
    Finding,
    enclosing_symbols,
)

SCOPE = (
    "corrosion_tpu/runtime/profiler.py",
    "corrosion_tpu/runtime/profstore.py",
)

ROOTS = ("sample_once",)

# the one lock the sampler may take (profstore's fold-map guard)
SANCTIONED_LOCK = "_fold_lock"

COLD_SUFFIX = "_coldpath"

_ALLOC_NODES = (
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
    ast.JoinedStr,
)

_REGISTRY_METHODS = {"counter", "gauge", "histogram", "latency"}
_LOGGING_ROOTS = {"log", "logging", "logger"}


def _root_name(node: ast.AST) -> str:
    """Leftmost Name id of an attribute chain ('' when not a chain)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _called_names(fn: ast.AST) -> Set[str]:
    """Names this function CALLS: direct call-position names plus
    simple `alias = obj.method` rebinds later called through the
    alias — the hot path's `add = self.ring.add_sample` idiom must
    not hide an edge from the scan."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            v = node.value
            if isinstance(v, ast.Attribute):
                aliases[node.targets[0].id] = v.attr
            elif isinstance(v, ast.Name):
                aliases[node.targets[0].id] = v.id
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            out.add(f.id)
            if f.id in aliases:
                out.add(aliases[f.id])
        elif isinstance(f, ast.Attribute):
            out.add(f.attr)
    return out


class ProfilerSafetyChecker(Checker):
    rule = "profiler-safety"
    description = (
        "code reachable from the stack sampler's hot path "
        "(sample_once and everything it calls, `_coldpath`-suffixed "
        "functions exempt) must not call asyncio, must not take any "
        "lock but the sanctioned _fold_lock, must not traverse "
        "agent/.store, and must not allocate per sample "
        "(comprehensions, f-strings, sorted, json, logging, registry "
        "calls)"
    )

    def __init__(self, scope=SCOPE, roots=ROOTS):
        self.scope = scope
        self.roots = roots

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        # one def table across every scanned file (the sampler half
        # lives in profiler.py, the fold-map half in profstore.py)
        files = [sf for sf in (ctx.file(p) for p in self.scope) if sf]
        defs: Dict[str, List[Tuple[object, ast.AST]]] = {}
        for sf in files:
            for node in ast.walk(sf.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    defs.setdefault(node.name, []).append((sf, node))

        reachable: Dict[str, List[Tuple[object, ast.AST]]] = {}
        work = [r for r in self.roots if r in defs]
        while work:
            name = work.pop()
            if name in reachable:
                continue
            reachable[name] = defs[name]
            for _sf, fn in defs[name]:
                for called in sorted(_called_names(fn)):
                    if called.endswith(COLD_SUFFIX):
                        continue  # bounded by cache/cadence, not rate
                    if called in defs and called not in reachable:
                        work.append(called)

        findings: List[Finding] = []
        for name in sorted(reachable):
            for sf, fn in reachable[name]:
                findings.extend(self._check_fn(sf, fn))
        return findings

    def _check_fn(self, sf, fn: ast.AST) -> List[Finding]:
        symbols = enclosing_symbols(sf.tree)
        out: List[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            out.append(
                Finding(
                    rule=self.rule,
                    path=sf.path,
                    line=getattr(node, "lineno", fn.lineno),
                    symbol=symbols.get(fn, fn.name),
                    message=f"sampler-reachable `{fn.name}`: {message}",
                    snippet=self.snippet_of(node),
                )
            )

        for node in ast.walk(fn):
            if isinstance(node, _ALLOC_NODES):
                what = (
                    "f-string"
                    if isinstance(node, ast.JoinedStr)
                    else "comprehension/generator"
                )
                flag(
                    node,
                    f"per-sample {what} allocates on every tick — "
                    "build strings with %-format/concat or move the "
                    "work to a `_coldpath` function",
                )
                continue
            if isinstance(node, ast.withitem):
                ce = node.context_expr
                held = (
                    ce.attr if isinstance(ce, ast.Attribute)
                    else ce.id if isinstance(ce, ast.Name) else ""
                )
                if "lock" in held.lower() and held != SANCTIONED_LOCK:
                    flag(
                        ce,
                        f"acquires `{held}` — the sampler may only "
                        f"take {SANCTIONED_LOCK} (any other lock can "
                        "deadlock against the thread being sampled)",
                    )
                continue
            if isinstance(node, ast.Attribute) and node.attr in (
                "agent", "store"
            ):
                flag(
                    node,
                    f"traverses `.{node.attr}` — the sampler reads "
                    "stacks, never the agent/store object graph",
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            root = _root_name(f)
            if root == "asyncio":
                flag(
                    node,
                    "calls an asyncio API — resolve tasks via the "
                    "lock-free `_current_tasks` dict read instead",
                )
            elif isinstance(f, ast.Attribute) and f.attr == "acquire":
                held = (
                    f.value.attr
                    if isinstance(f.value, ast.Attribute)
                    else root
                )
                if held != SANCTIONED_LOCK:
                    flag(
                        node,
                        f"acquires `{held or '<lock>'}` — the sampler "
                        f"may only take {SANCTIONED_LOCK}",
                    )
            elif isinstance(f, ast.Name) and f.id == "sorted":
                flag(
                    node,
                    "per-sample sorted() allocates — sort on the "
                    "read/serving side, never while sampling",
                )
            elif root == "json":
                flag(
                    node,
                    "per-sample json call — serialization belongs on "
                    "the serving side",
                )
            elif root in _LOGGING_ROOTS:
                flag(
                    node,
                    "per-sample logging — a hot sampler log line is "
                    "self-inflicted load; log from cold paths only",
                )
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in _REGISTRY_METHODS
                and (
                    root in ("METRICS", "reg", "registry")
                    or (
                        isinstance(f.value, ast.Attribute)
                        and f.value.attr == "registry"
                    )
                )
            ):
                flag(
                    node,
                    "per-sample registry call — metrics flush belongs "
                    "in `_adapt_coldpath` (per block, not per sample)",
                )
        return out

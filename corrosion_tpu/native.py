"""Build + load the native CRDT SQLite extension.

The reference embeds prebuilt cr-sqlite binaries and loads them via
SQLite's extension loader (`klukai-types/src/sqlite.rs:27-31,125-143`).
We compile our own C++ extension (`native/crdt_ext.cpp`) on first use
with the system toolchain and cache the .so next to the source; every
`CrdtStore` connection then loads it so the write-capture triggers call
native `crdt_pack` instead of a Python callback.

If compilation is impossible (no g++, no SQLite headers), the store
falls back to the pure-Python functions — same semantics, slower
trigger path.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

_SRC = Path(__file__).resolve().parent.parent / "native" / "crdt_ext.cpp"
_BUILD_DIR = _SRC.parent / "build"
_SO = _BUILD_DIR / "crdtext.so"
ENTRYPOINT = "sqlite3_crdtext_init"

_lock = threading.Lock()
_built: Optional[str] = None  # path, or "" = build failed (don't retry)


def _sqlite_include_dir() -> Optional[Path]:
    """SQLite headers aren't installed system-wide in this image, but
    tensorflow vendors them; resolve without importing tensorflow."""
    for name in ("sqlite3ext.h",):
        # 1. standard include dirs
        for d in (
            Path(sysconfig.get_paths()["include"]),
            Path("/usr/include"),
            Path("/usr/local/include"),
        ):
            if (d / name).exists():
                return d
        # 2. tensorflow's bundled copy
        spec = importlib.util.find_spec("tensorflow")
        if spec is not None and spec.origin:
            cand = (
                Path(spec.origin).parent
                / "include"
                / "external"
                / "org_sqlite"
            )
            if (cand / name).exists():
                return cand
    return None


def extension_path() -> Optional[str]:
    """Compile (once) and return the extension path, or None when the
    native path is unavailable."""
    global _built
    with _lock:
        if _built is not None:
            return _built or None
        include = _sqlite_include_dir()
        if include is None:
            log.warning("native crdt extension unavailable: no sqlite headers")
            _built = ""
            return None
        path = _build_so(_SRC, _SO, include=include)
        _built = path or ""
        return path


_BATCH_SRC = _SRC.parent / "crdt_batch.cpp"
_BATCH_SO = _BUILD_DIR / "crdt_batch.so"

_batch_lock = threading.Lock()
_batch_lib = None  # ctypes.CDLL, or False = unavailable (don't retry)


def _build_so(src: Path, so: Path, include: Optional[Path] = None) -> Optional[str]:
    """Hash-gated g++ shared-library build (shared by the SQLite extension
    and the batch-merge library): reuse the cached .so only when the
    recorded content hash of the source matches — mtimes are arbitrary
    after a fresh clone, and a stale or tampered binary must never be
    silently loaded."""
    src_hash = hashlib.sha256(src.read_bytes()).hexdigest() if src.exists() else ""
    hash_file = so.with_suffix(".so.srchash")
    if so.exists() and hash_file.exists() and src_hash:
        if hash_file.read_text().strip() == src_hash:
            return str(so)
    if not src.exists():
        return None
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = so.with_suffix(".so.tmp")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
    if include is not None:
        cmd.append(f"-I{include}")
    cmd += [str(src), "-o", str(tmp)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True, timeout=120)
        os.replace(tmp, so)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        log.warning("native build of %s failed: %s", src.name, detail[:500])
        return None
    try:
        # Best-effort: a failed hash write must not disable the freshly
        # built library — it only costs a rebuild next process.
        hash_file.write_text(src_hash)
    except OSError as e:
        log.warning("could not record native source hash: %s", e)
    return str(so)


def merge_batch_lib():
    """ctypes handle to the columnar CRDT merge engine
    (`native/crdt_batch.cpp::crdt_merge_batch`), or None when the native
    path is unavailable.  Built once per process, content-hash gated."""
    global _batch_lib
    with _batch_lock:
        if _batch_lib is not None:
            return _batch_lib or None
        import ctypes

        path = _build_so(_BATCH_SRC, _BATCH_SO)
        if path is None:
            _batch_lib = False
            return None
        try:
            lib = ctypes.CDLL(path)
            fn = lib.crdt_merge_batch
        except (OSError, AttributeError) as e:
            log.warning("could not load native batch-merge library: %s", e)
            _batch_lib = False
            return None
        c = ctypes
        fn.restype = c.c_int
        fn.argtypes = [
            # batch
            c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
            c.POINTER(c.c_uint8), c.POINTER(c.c_int64), c.POINTER(c.c_double),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_char_p,
            # snapshot
            c.c_int32, c.POINTER(c.c_int64),
            c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int64),
            # disk values
            c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_uint8), c.POINTER(c.c_int64), c.POINTER(c.c_double),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64), c.c_char_p,
            # outputs
            c.POINTER(c.c_uint8),
            c.POINTER(c.c_int64), c.POINTER(c.c_uint8),
            c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32),
        ]
        _batch_lib = lib
        return lib


_finalize_lock = threading.Lock()
_finalize_lib = None  # ctypes.CDLL, or False = unavailable (don't retry)


def finalize_batch_lib():
    """ctypes handle to the native local-commit finalize engine
    (`native/crdt_batch.cpp::crdt_finalize_batch`, r24 — the
    CORRO_FINALIZE=native phase B), or None when the native path is
    unavailable.  Shares the crdt_batch.so build with the merge engine;
    built once per process, content-hash gated.  The store glue falls
    back to the columnar Python engine (counted by
    `corro.write.finalize.native.unavailable`) when this returns None."""
    global _finalize_lib
    with _finalize_lock:
        if _finalize_lib is not None:
            return _finalize_lib or None
        import ctypes

        path = _build_so(_BATCH_SRC, _BATCH_SO)
        if path is None:
            _finalize_lib = False
            return None
        try:
            lib = ctypes.CDLL(path)
            fn = lib.crdt_finalize_batch
        except (OSError, AttributeError) as e:
            log.warning("could not load native finalize library: %s", e)
            _finalize_lib = False
            return None
        c = ctypes
        fn.restype = c.c_int
        fn.argtypes = [
            # group geometry
            c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32),
            # row snapshot
            c.c_int32, c.POINTER(c.c_int64), c.POINTER(c.c_uint8),
            # cv snapshot
            c.c_int32, c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int64),
            # spec outputs
            c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32),
            c.POINTER(c.c_int64), c.POINTER(c.c_int64),
            # rows_up / clock_clear / clock_put plans
            c.POINTER(c.c_int32), c.POINTER(c.c_int64),
            c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
            c.POINTER(c.c_int64), c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        ]
        _finalize_lib = lib
        return lib


def load_into(conn) -> bool:
    """Load the extension into a sqlite3 connection; False → caller must
    register the Python fallbacks."""
    path = extension_path()
    if path is None:
        return False
    try:
        conn.enable_load_extension(True)
        try:
            try:
                conn.load_extension(path, entrypoint=ENTRYPOINT)
            except TypeError:
                # py3.10/3.11: load_extension() takes no entrypoint
                # (added in 3.12).  SQLite then derives the entrypoint
                # from the filename — crdtext.so → sqlite3_crdtext_init,
                # which IS our ENTRYPOINT, so the bare call loads the
                # same symbol (same shim spirit as the tomllib→tomli
                # fallback in runtime/config.py).
                conn.load_extension(path)
        finally:
            conn.enable_load_extension(False)
        return True
    except Exception as e:  # pragma: no cover - depends on sqlite build
        log.warning("could not load native crdt extension: %s", e)
        return False

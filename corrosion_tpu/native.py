"""Build + load the native CRDT SQLite extension.

The reference embeds prebuilt cr-sqlite binaries and loads them via
SQLite's extension loader (`klukai-types/src/sqlite.rs:27-31,125-143`).
We compile our own C++ extension (`native/crdt_ext.cpp`) on first use
with the system toolchain and cache the .so next to the source; every
`CrdtStore` connection then loads it so the write-capture triggers call
native `crdt_pack` instead of a Python callback.

If compilation is impossible (no g++, no SQLite headers), the store
falls back to the pure-Python functions — same semantics, slower
trigger path.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

_SRC = Path(__file__).resolve().parent.parent / "native" / "crdt_ext.cpp"
_BUILD_DIR = _SRC.parent / "build"
_SO = _BUILD_DIR / "crdtext.so"
ENTRYPOINT = "sqlite3_crdtext_init"

_lock = threading.Lock()
_built: Optional[str] = None  # path, or "" = build failed (don't retry)


def _sqlite_include_dir() -> Optional[Path]:
    """SQLite headers aren't installed system-wide in this image, but
    tensorflow vendors them; resolve without importing tensorflow."""
    for name in ("sqlite3ext.h",):
        # 1. standard include dirs
        for d in (
            Path(sysconfig.get_paths()["include"]),
            Path("/usr/include"),
            Path("/usr/local/include"),
        ):
            if (d / name).exists():
                return d
        # 2. tensorflow's bundled copy
        spec = importlib.util.find_spec("tensorflow")
        if spec is not None and spec.origin:
            cand = (
                Path(spec.origin).parent
                / "include"
                / "external"
                / "org_sqlite"
            )
            if (cand / name).exists():
                return cand
    return None


def extension_path() -> Optional[str]:
    """Compile (once) and return the extension path, or None when the
    native path is unavailable."""
    global _built
    with _lock:
        if _built is not None:
            return _built or None
        # Reuse the cached .so only when a recorded content hash of the
        # source matches — mtimes are arbitrary after a fresh clone, and a
        # stale or tampered binary must never be silently loaded.
        src_hash = (
            hashlib.sha256(_SRC.read_bytes()).hexdigest()
            if _SRC.exists()
            else ""
        )
        hash_file = _SO.with_suffix(".so.srchash")
        if _SO.exists() and hash_file.exists() and src_hash:
            if hash_file.read_text().strip() == src_hash:
                _built = str(_SO)
                return _built
        include = _sqlite_include_dir()
        if include is None or not _SRC.exists():
            log.warning("native crdt extension unavailable: no sqlite headers")
            _built = ""
            return None
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        tmp = _SO.with_suffix(".so.tmp")
        cmd = [
            "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
            f"-I{include}",
            str(_SRC), "-o", str(tmp),
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=120
            )
            os.replace(tmp, _SO)
            _built = str(_SO)
            log.info("built native crdt extension at %s", _SO)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            log.warning("native crdt extension build failed: %s", detail[:500])
            _built = ""
            return None
        try:
            # Best-effort: a failed hash write must not disable the freshly
            # built extension — it only costs a rebuild next process.
            hash_file.write_text(src_hash)
        except OSError as e:
            log.warning("could not record native ext source hash: %s", e)
        return _built


def load_into(conn) -> bool:
    """Load the extension into a sqlite3 connection; False → caller must
    register the Python fallbacks."""
    path = extension_path()
    if path is None:
        return False
    try:
        conn.enable_load_extension(True)
        try:
            conn.load_extension(path, entrypoint=ENTRYPOINT)
        finally:
            conn.enable_load_extension(False)
        return True
    except Exception as e:  # pragma: no cover - depends on sqlite build
        log.warning("could not load native crdt extension: %s", e)
        return False

"""Consul → store sync: replicate the local Consul agent's services and
checks into the cluster.

Counterpart of `klukai/src/command/consul/sync.rs` (~980 LoC) and the
Consul client types in `klukai-types/src/consul/mod.rs`:

  - poll `/v1/agent/services` + `/v1/agent/checks` every 1 s (5 s timeout)
  - hash-based change detection: per-entity hashes persisted in
    `__corro_consul_services` / `__corro_consul_checks` so restarts don't
    re-upsert everything; check hashes cover (service_name, service_id,
    status) by default, or the fields named by a JSON
    `{"hash_include": ["status","output"]}` directive in the check's notes
  - diff vs cached hashes → upsert/delete statements executed through the
    corrosion HTTP API in one transaction (hash bookkeeping rides along)
  - rows written with `node = <hostname>`; deletes/upserts are scoped to
    this node's rows
  - reverse TTL sync: configured `[[consul.ttl_checks]]` entries map a
    store SQL query onto a Consul TTL check; statuses are PUT back to
    `/v1/agent/check/update/<id>`, hash-gated on (status, output) with a
    forced refresh inside the TTL window (this reference snapshot's
    consul client is poll-only — consul/mod.rs:111-116 — so the write
    direction follows Consul's own TTL check-update API contract)
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import socket
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from corrosion_tpu.runtime.config import ConsulConfig
from corrosion_tpu.runtime.metrics import METRICS

log = logging.getLogger(__name__)

PULL_INTERVAL = 1.0
CONSUL_TIMEOUT = 5.0


@dataclass(frozen=True)
class AgentService:
    """A service registered with the local Consul agent
    (consul/mod.rs:166-177)."""

    id: str
    name: str
    tags: Tuple[str, ...] = ()
    meta: Tuple[Tuple[str, str], ...] = ()
    port: int = 0
    address: str = ""

    @classmethod
    def from_json(cls, d: dict) -> "AgentService":
        return cls(
            id=d.get("ID", ""),
            name=d.get("Service", ""),
            tags=tuple(d.get("Tags") or ()),
            meta=tuple(sorted((d.get("Meta") or {}).items())),
            port=int(d.get("Port") or 0),
            address=d.get("Address", ""),
        )


@dataclass(frozen=True)
class AgentCheck:
    """A health check from the local Consul agent
    (consul/mod.rs:182-193)."""

    id: str
    name: str
    status: str  # passing | warning | critical
    output: str
    service_id: str
    service_name: str
    notes: Optional[str] = None

    @classmethod
    def from_json(cls, d: dict) -> "AgentCheck":
        return cls(
            id=d.get("CheckID", ""),
            name=d.get("Name", ""),
            status=d.get("Status", "critical"),
            output=d.get("Output", ""),
            service_id=d.get("ServiceID", ""),
            service_name=d.get("ServiceName", ""),
            notes=d.get("Notes") or None,
        )


class ConsulClient:
    """Minimal Consul agent HTTP client (klukai-types/src/consul/mod.rs:
    hyper client exposing agent_services/agent_checks)."""

    def __init__(self, address: str):
        self.base = f"http://{address}"
        self._session = None

    async def _ensure(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def agent_services(self) -> Dict[str, AgentService]:
        s = await self._ensure()
        async with s.get(f"{self.base}/v1/agent/services") as resp:
            resp.raise_for_status()
            data = await resp.json()
        return {k: AgentService.from_json(v) for k, v in data.items()}

    async def agent_checks(self) -> Dict[str, AgentCheck]:
        s = await self._ensure()
        async with s.get(f"{self.base}/v1/agent/checks") as resp:
            resp.raise_for_status()
            data = await resp.json()
        return {k: AgentCheck.from_json(v) for k, v in data.items()}

    async def update_ttl_check(
        self, check_id: str, status: str, output: str = ""
    ) -> None:
        """PUT /v1/agent/check/update/<id> — refresh a TTL check.

        The reverse half of the sync: this reference snapshot's client
        only polls (consul/mod.rs:111-116 — GETs, no writer), so the
        write-back follows Consul's own TTL check-update API contract
        (status must be passing|warning|critical)."""
        s = await self._ensure()
        async with s.put(
            f"{self.base}/v1/agent/check/update/{check_id}",
            json={"Status": status, "Output": output},
        ) as resp:
            resp.raise_for_status()


# -- hashing ---------------------------------------------------------------


def _h64(*parts: str) -> int:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x1f")
    return int.from_bytes(h.digest()[:8], "big")


def hash_service(svc: AgentService) -> int:
    return _h64(
        svc.id,
        svc.name,
        json.dumps(list(svc.tags)),
        json.dumps(dict(svc.meta), sort_keys=True),
        str(svc.port),
        svc.address,
    )


def hash_check(check: AgentCheck) -> int:
    """Checks hash (service_name, service_id, status) by default; a JSON
    notes directive {"hash_include": [...]} overrides which volatile
    fields count (sync.rs:354-386) — so flapping output text doesn't
    rewrite cluster state unless asked to."""
    parts = [check.service_name, check.service_id]
    directive = None
    if check.notes:
        try:
            directive = json.loads(check.notes).get("hash_include")
        except (json.JSONDecodeError, AttributeError):
            directive = None
    if directive:
        for fld in directive:
            if fld == "status":
                parts.append(check.status)
            elif fld == "output":
                parts.append(check.output)
    else:
        parts.append(check.status)
    return _h64(*parts)


# -- schema ----------------------------------------------------------------

INTERNAL_TABLES = """
CREATE TABLE IF NOT EXISTS __corro_consul_services (
    id TEXT NOT NULL PRIMARY KEY, hash BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS __corro_consul_checks (
    id TEXT NOT NULL PRIMARY KEY, hash BLOB NOT NULL
);
"""

_EXPECTED_SERVICE_COLS = {
    "node", "id", "name", "tags", "meta", "port", "address", "updated_at",
}
_EXPECTED_CHECK_COLS = {
    "node", "id", "service_id", "service_name", "name", "status", "output",
    "updated_at",
}


class ConsulSetupError(Exception):
    pass


async def setup(api) -> None:
    """Create hash tables, verify the user schema has the consul tables
    (sync.rs:130-221). `api` is a CorrosionApiClient."""
    for t, cols in (
        ("consul_services", _EXPECTED_SERVICE_COLS),
        ("consul_checks", _EXPECTED_CHECK_COLS),
    ):
        have = {
            r[0]
            for r in await api.query_rows(
                ["SELECT name FROM pragma_table_info(?)", [t]]
            )
        }
        if not have:
            raise ConsulSetupError(
                f"schema must define a CRR table {t!r} (see reference"
                " sync.rs:158-221 for the expected columns)"
            )
        missing = cols - have
        if missing:
            raise ConsulSetupError(f"{t} is missing columns {sorted(missing)}")
    # hash tables are internal (non-CRR) — plain statements
    for stmt in INTERNAL_TABLES.strip().split(";"):
        if stmt.strip():
            await api.execute([stmt.strip()])


# -- diffing ---------------------------------------------------------------


@dataclass
class ApplyStats:
    upserted: int = 0
    deleted: int = 0

    @property
    def is_zero(self) -> bool:
        return self.upserted == 0 and self.deleted == 0


def diff_services(
    services: Dict[str, AgentService], hashes: Dict[str, int]
) -> Tuple[List[Tuple[AgentService, int]], List[str]]:
    """(upserts, deletes) vs the cached hashes (sync.rs:update_services)."""
    upserts: List[Tuple[AgentService, int]] = []
    deletes: List[str] = []
    remaining = dict(services)
    for sid, old_hash in hashes.items():
        svc = remaining.pop(sid, None)
        if svc is None:
            deletes.append(sid)
            continue
        h = hash_service(svc)
        if h != old_hash:
            upserts.append((svc, h))
    for svc in remaining.values():
        upserts.append((svc, hash_service(svc)))
    return upserts, deletes


def diff_checks(
    checks: Dict[str, AgentCheck], hashes: Dict[str, int]
) -> Tuple[List[Tuple[AgentCheck, int]], List[str]]:
    upserts: List[Tuple[AgentCheck, int]] = []
    deletes: List[str] = []
    remaining = dict(checks)
    for cid, old_hash in hashes.items():
        check = remaining.pop(cid, None)
        if check is None:
            deletes.append(cid)
            continue
        h = hash_check(check)
        if h != old_hash:
            upserts.append((check, h))
    for check in remaining.values():
        upserts.append((check, hash_check(check)))
    return upserts, deletes


# -- statement assembly ----------------------------------------------------


def _svc_statements(node, svc: AgentService, h: int, updated_at: int):
    return [
        [
            "INSERT INTO __corro_consul_services (id, hash) VALUES (?, ?)"
            " ON CONFLICT (id) DO UPDATE SET hash = excluded.hash",
            [svc.id, list(h.to_bytes(8, "big"))],
        ],
        [
            "INSERT INTO consul_services"
            " (node, id, name, tags, meta, port, address, updated_at)"
            " VALUES (?,?,?,?,?,?,?,?)"
            " ON CONFLICT (node, id) DO UPDATE SET"
            " name = excluded.name, tags = excluded.tags,"
            " meta = excluded.meta, port = excluded.port,"
            " address = excluded.address, updated_at = excluded.updated_at",
            [
                node,
                svc.id,
                svc.name,
                json.dumps(list(svc.tags)),
                json.dumps(dict(svc.meta), sort_keys=True),
                svc.port,
                svc.address,
                updated_at,
            ],
        ],
    ]


def _check_statements(node, check: AgentCheck, h: int, updated_at: int):
    return [
        [
            "INSERT INTO __corro_consul_checks (id, hash) VALUES (?, ?)"
            " ON CONFLICT (id) DO UPDATE SET hash = excluded.hash",
            [check.id, list(h.to_bytes(8, "big"))],
        ],
        [
            "INSERT INTO consul_checks"
            " (node, id, service_id, service_name, name, status, output,"
            " updated_at) VALUES (?,?,?,?,?,?,?,?)"
            " ON CONFLICT (node, id) DO UPDATE SET"
            " service_id = excluded.service_id,"
            " service_name = excluded.service_name, name = excluded.name,"
            " status = excluded.status, output = excluded.output,"
            " updated_at = excluded.updated_at",
            [
                node,
                check.id,
                check.service_id,
                check.service_name,
                check.name,
                check.status,
                check.output,
                updated_at,
            ],
        ],
    ]


# -- reverse TTL status derivation ----------------------------------------

_TTL_STATUSES = ("passing", "warning", "critical")


def derive_ttl_status(rows: List[Any]) -> Tuple[str, str]:
    """Map a store query result onto a Consul TTL status.

    Contract: no rows → critical; if the first cell is a literal status
    string it is used verbatim (second cell, if any, becomes the output);
    otherwise the first cell's truthiness decides passing/critical. This
    lets one `SELECT 'passing', 'detail'`-style query drive the check
    directly, while `SELECT count(*) > 0 FROM ...` works unadorned."""
    if not rows:
        return "critical", "query returned no rows"
    row = rows[0]
    cell = row[0] if isinstance(row, (list, tuple)) else row
    if isinstance(cell, str) and cell in _TTL_STATUSES:
        out = ""
        if isinstance(row, (list, tuple)) and len(row) > 1 and row[1] is not None:
            out = str(row[1])
        return cell, out
    return ("passing", "") if cell else ("critical", f"query returned {cell!r}")


# -- sync engine -----------------------------------------------------------


class ConsulSync:
    """The 1 s pull loop, factored for testing (sync.rs:90-128)."""

    def __init__(
        self,
        consul: ConsulClient,
        api,
        node: Optional[str] = None,
        ttl_checks: Optional[List[dict]] = None,
        ttl_refresh: float = 30.0,
    ):
        self.consul = consul
        self.api = api
        self.node = node or socket.gethostname()
        self.service_hashes: Dict[str, int] = {}
        self.check_hashes: Dict[str, int] = {}
        self.ttl_checks = list(ttl_checks or ())
        self.ttl_refresh = ttl_refresh
        # check id -> (hash of last PUT (status, output), monotonic time)
        self._ttl_state: Dict[str, Tuple[int, float]] = {}

    async def load_hashes(self) -> None:
        """Warm the in-memory hash caches from the persisted tables."""
        for table, cache in (
            ("__corro_consul_services", self.service_hashes),
            ("__corro_consul_checks", self.check_hashes),
        ):
            for rid, h in await self.api.query_rows(
                f"SELECT id, hash FROM {table}"
            ):
                # blobs ride JSON as byte arrays (api/types.py dump_value)
                cache[rid] = int.from_bytes(bytes(h), "big")

    async def tick(self) -> Tuple[ApplyStats, ApplyStats]:
        """One pull + diff + apply round (sync.rs update_consul)."""
        t_poll = time.monotonic()
        services, checks = await asyncio.gather(
            asyncio.wait_for(self.consul.agent_services(), CONSUL_TIMEOUT),
            asyncio.wait_for(self.consul.agent_checks(), CONSUL_TIMEOUT),
        )
        METRICS.histogram("corro_consul.consul.response.time.seconds").observe(
            time.monotonic() - t_poll
        )
        svc_up, svc_del = diff_services(services, self.service_hashes)
        chk_up, chk_del = diff_checks(checks, self.check_hashes)

        updated_at = int(time.time() * 1000)
        statements: List[Any] = []
        for svc, h in svc_up:
            statements.extend(_svc_statements(self.node, svc, h, updated_at))
        for sid in svc_del:
            statements.append(
                ["DELETE FROM __corro_consul_services WHERE id = ?", [sid]]
            )
            statements.append(
                [
                    "DELETE FROM consul_services WHERE node = ? AND id = ?",
                    [self.node, sid],
                ]
            )
        for check, h in chk_up:
            statements.extend(
                _check_statements(self.node, check, h, updated_at)
            )
        for cid in chk_del:
            statements.append(
                ["DELETE FROM __corro_consul_checks WHERE id = ?", [cid]]
            )
            statements.append(
                [
                    "DELETE FROM consul_checks WHERE node = ? AND id = ?",
                    [self.node, cid],
                ]
            )

        if statements:
            resp = await self.api.execute(statements)
            for res in resp.get("results", []):
                if "error" in res:
                    raise RuntimeError(f"consul sync tx failed: {res}")

        # commit caches only after the tx landed
        for svc, h in svc_up:
            self.service_hashes[svc.id] = h
        for sid in svc_del:
            self.service_hashes.pop(sid, None)
        for check, h in chk_up:
            self.check_hashes[check.id] = h
        for cid in chk_del:
            self.check_hashes.pop(cid, None)

        if self.ttl_checks:
            await self.update_ttl_checks()

        svc_stats = ApplyStats(len(svc_up), len(svc_del))
        chk_stats = ApplyStats(len(chk_up), len(chk_del))
        METRICS.counter("corro_consul.services.upserted").inc(svc_stats.upserted)
        METRICS.counter("corro_consul.services.deleted").inc(svc_stats.deleted)
        METRICS.counter("corro_consul.checks.upserted").inc(chk_stats.upserted)
        METRICS.counter("corro_consul.checks.deleted").inc(chk_stats.deleted)
        return svc_stats, chk_stats

    async def update_ttl_checks(self) -> int:
        """Reverse sync: evaluate each configured TTL check's query against
        the store and PUT the derived status back to the local Consul
        agent. Hash-gated like the forward path — an unchanged
        (status, output) pair is NOT re-sent unless `ttl_refresh` seconds
        have elapsed since the last PUT (TTL checks lapse to critical on
        the Consul side if never refreshed, so gating can't be absolute).
        Returns the number of PUTs issued."""
        sent = 0
        for spec in self.ttl_checks:
            cid = spec.get("id")
            query = spec.get("query")
            if not cid or not query:
                continue
            try:
                rows = await self.api.query_rows(query)
                status, output = derive_ttl_status(rows)
            except Exception as e:  # store unreachable → check fails
                status, output = "critical", f"query failed: {e}"
            h = _h64(status, output)
            prev = self._ttl_state.get(cid)
            now = time.monotonic()
            if (
                prev is not None
                and prev[0] == h
                and now - prev[1] < self.ttl_refresh
            ):
                continue
            # one failing PUT (e.g. check not yet registered → 404) must
            # not starve the remaining checks or abort the tick
            try:
                await self.consul.update_ttl_check(cid, status, output)
            except Exception as e:
                METRICS.counter("corro_consul.consul.response.errors").inc()
                log.warning("ttl check %s update failed: %s", cid, e)
                continue
            self._ttl_state[cid] = (h, now)
            sent += 1
            METRICS.counter("corro_consul.ttl_checks.updated").inc()
        return sent

    async def run(self, tripwire=None) -> None:
        await setup(self.api)
        await self.load_hashes()
        while tripwire is None or not tripwire.tripped:
            try:
                svc_stats, chk_stats = await self.tick()
                if not svc_stats.is_zero:
                    log.info("updated consul services: %s", svc_stats)
                if not chk_stats.is_zero:
                    log.info("updated consul checks: %s", chk_stats)
            except (asyncio.TimeoutError, OSError, RuntimeError) as e:
                METRICS.counter("corro_consul.consul.response.errors").inc()
                log.warning("non-fatal consul sync error: %s", e)
            except Exception as e:
                # aiohttp raises ClientResponseError/ContentTypeError (not
                # OSError subclasses) on non-2xx or malformed responses —
                # common during Consul agent restarts. The reference treats
                # these as non-fatal too (consul sync.rs response.errors).
                if type(e).__module__.split(".")[0] not in (
                    "aiohttp",
                    "json",
                ):
                    raise
                METRICS.counter("corro_consul.consul.response.errors").inc()
                log.warning("non-fatal consul sync error: %s", e)
            await asyncio.sleep(PULL_INTERVAL)


async def consul_sync_loop(agent, consul_cfg: ConsulConfig, tripwire) -> None:
    """Side task started by `corrosion agent` when [consul] is enabled."""
    from corrosion_tpu.client import CorrosionApiClient

    api = CorrosionApiClient(
        agent.config.api.bind_addr[0], token=agent.config.api.authz_bearer
    )
    consul = ConsulClient(consul_cfg.address)
    try:
        await ConsulSync(
            consul,
            api,
            ttl_checks=consul_cfg.ttl_checks,
            ttl_refresh=consul_cfg.ttl_refresh_seconds,
        ).run(tripwire)
    finally:
        await consul.close()
        await api.close()


async def run_consul_sync_cli(cfg) -> int:
    """`corrosion consul sync` (command/agent.rs consul side task)."""
    from corrosion_tpu.client import CorrosionApiClient
    from corrosion_tpu.runtime.tripwire import Tripwire

    consul_cfg = getattr(cfg, "consul", None) or ConsulConfig()
    api = CorrosionApiClient(
        cfg.api.bind_addr[0], token=cfg.api.authz_bearer
    )
    consul = ConsulClient(consul_cfg.address)
    tripwire = Tripwire.from_signals()
    try:
        await ConsulSync(
            consul,
            api,
            ttl_checks=consul_cfg.ttl_checks,
            ttl_refresh=consul_cfg.ttl_refresh_seconds,
        ).run(tripwire)
        return 0
    except ConsulSetupError as e:
        print(f"error: {e}")
        return 1
    finally:
        await consul.close()
        await api.close()

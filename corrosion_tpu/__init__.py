"""corrosion-tpu: a TPU-native, gossip-based, multi-writer distributed store.

A brand-new framework with the capabilities of Corrosion (studied via the
klukai fork): SWIM membership, infection-style change broadcast, pull-based
anti-entropy sync, column-level LWW CRDT merge with causal-length deletes,
live-query subscriptions, and an HTTP/CLI surface.

The core is re-architected for JAX/XLA: the per-node SWIM state machine and
broadcast fanout are batched message-passing kernels over node-state arrays
(`corrosion_tpu.ops.swim`), member shards are laid out over a
`jax.sharding.Mesh` (`corrosion_tpu.parallel`), and the CRDT merge is a
vectorized compare-and-select kernel (`corrosion_tpu.ops.merge`). The host
runtime (agents, transports, sync protocol, HTTP API) lives alongside and
speaks wire formats modeled on the reference's (see `corrosion_tpu.types`).
"""

__version__ = "0.1.0"

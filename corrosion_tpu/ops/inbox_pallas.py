"""Pallas TPU kernel for the bounded gossip-inbox build.

The SWIM tick's hottest host-of-FLOPs-free op is compacting ~N*fanout
gossip packets (each carrying m piggybacked updates for ONE destination)
into bounded [N, slots] per-member inboxes (`ops/swim.py:build_inbox`,
the r3 profile's dominant phase). The XLA paths express this as a
lexicographic `lax.sort` — O(M log M) over M = G*m messages ("sort") or
G packet heads ("gsort").

This kernel replaces the sort with what the operation actually is: a
sequential scatter with per-destination fill counters. TPU has no
scatter unit, but Pallas gives us what XLA's HLO can't express — a
single program that walks the G packets in order, keeps the fill
counters `counts[n]` and both inbox planes resident in VMEM, and does a
read-modify-write of ONE [slots]-wide row per packet. Order of work:
O(G * slots) with no log factor and no [M]-wide intermediate arrays.

Semantics are bit-identical to `build_inbox` on the flattened message
list (tests/test_inbox_impls.py): packets are visited in group-major
(= flat stable-sort) order, so each destination receives its first
`slots` valid messages in arrival order.

The per-packet inner step is vectorized: a packet's m messages land in
columns base+prefix, expressed as an [slots, m] match matrix reduced on
the VPU — no scalar inner loop. Only the packet walk itself is serial
(the counts[] carry makes it inherently so).

Selected via `SwimParams.inbox_impl = "pallas"`; `build_inbox_pallas`
falls back to interpret mode off-TPU so the flag is portable (and the
bit-equality tests run on CPU).

Reference lineage: the inbox bound mirrors the reference's bounded
processing queue with drop semantics (broadcast/mod.rs:793-812); the
kernel form is ours (SURVEY §7 "Pallas kernels — not Python stand-ins").
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# VMEM budget gate: inputs (3 planes of [G, m] int32: subj/key/pos) +
# outputs ([n, slots] * 2) must fit comfortably in ~16 MB VMEM.  The
# control data (dst, cnt, fill counters) lives in SMEM and is gated
# separately — SMEM is far smaller than VMEM.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_SMEM_BUDGET_BYTES = 512 * 1024


def _kernel(dst_ref, cnt_ref, subj_ref, key_ref, pos_ref,
            out_subj_ref, out_key_ref, counts_ref, *, n, slots, m):
    g_total = subj_ref.shape[0]

    # init: outputs (allocations arrive uninitialized).  The fill
    # counters live in SMEM — Mosaic forbids scalar stores to VMEM
    # ("Cannot store scalars to VMEM", observed on a real v5e; interpret
    # mode accepts them, which is why CPU tests alone missed this) — and
    # SMEM has no vector store, so they are zeroed by a scalar loop.
    out_subj_ref[:] = jnp.full((n, slots), n, dtype=jnp.int32)
    out_key_ref[:] = jnp.zeros((n, slots), dtype=jnp.int32)

    def zero(i, carry):
        counts_ref[i] = 0
        return carry

    jax.lax.fori_loop(0, n, zero, 0)

    col_iota = jax.lax.broadcasted_iota(jnp.int32, (slots, m), 0)

    def body(g, _):
        d = dst_ref[g]
        base = counts_ref[d]
        subj = subj_ref[g, :]          # [m]
        key = key_ref[g, :]
        pos = pos_ref[g, :]            # exclusive valid-prefix, -1 = masked
        valid = pos >= 0
        col = base + pos               # [m]
        keep = valid & (col < slots)
        # match[c, k]: message k lands in column c — VPU reduce, no loop
        match = keep[None, :] & (col[None, :] == col_iota)  # [slots, m]
        upd_subj = jnp.min(
            jnp.where(match, subj[None, :], n), axis=1
        )                              # [slots]
        upd_key = jnp.max(jnp.where(match, key[None, :], 0), axis=1)
        hit = jnp.any(match, axis=1)   # [slots]
        cur_subj = out_subj_ref[d, :]
        cur_key = out_key_ref[d, :]
        out_subj_ref[d, :] = jnp.where(hit, upd_subj, cur_subj)
        out_key_ref[d, :] = jnp.where(hit, upd_key, cur_key)
        counts_ref[d] = base + cnt_ref[g]
        return _

    jax.lax.fori_loop(0, g_total, body, 0)


@functools.partial(jax.jit, static_argnums=(0, 1))
def build_inbox_pallas(
    n: int,
    slots: int,
    dst_g: jax.Array,
    subj: jax.Array,
    key: jax.Array,
    ok: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Same contract as `swim.build_inbox_grouped`: dst_g [G] in [0, n),
    subj/key/ok [G, m]; returns ([n, slots] subj, [n, slots] key)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    g, m = subj.shape
    # valid-prefix within the packet, computed vectorized outside the
    # kernel; -1 marks masked messages so the kernel needs no ok plane
    oki = ok.astype(jnp.int32)
    pos = jnp.where(ok, jnp.cumsum(oki, axis=1) - oki, -1).astype(jnp.int32)
    cnt = jnp.sum(oki, axis=1, keepdims=True)

    vmem = 4 * (3 * g * m + 2 * n * slots)
    if vmem > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"inbox_pallas: VMEM working set {vmem}B exceeds budget"
            f" (G={g}, m={m}, n={n}); use inbox_impl='gsort'"
        )
    smem = 4 * (2 * g + n)  # dst + cnt inputs, counts scratch
    if smem > _SMEM_BUDGET_BYTES:
        raise ValueError(
            f"inbox_pallas: SMEM working set {smem}B exceeds budget"
            f" (G={g}, n={n}); use inbox_impl='gsort'"
        )

    interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(_kernel, n=n, slots=slots, m=m)
    vm = pltpu.VMEM
    sm = pltpu.SMEM
    # dst/cnt are control data (dynamic row indices + counter bumps):
    # they live in SMEM, the only space Mosaic allows scalar loads/stores
    # on; the message planes stay VMEM and are touched vector-wise only.
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n, slots), jnp.int32),
            jax.ShapeDtypeStruct((n, slots), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=sm),  # dst [G]
            pl.BlockSpec(memory_space=sm),  # cnt [G]
            pl.BlockSpec(memory_space=vm),  # subj [G, m]
            pl.BlockSpec(memory_space=vm),  # key [G, m]
            pl.BlockSpec(memory_space=vm),  # pos [G, m]
        ],
        out_specs=(
            pl.BlockSpec(memory_space=vm),
            pl.BlockSpec(memory_space=vm),
        ),
        scratch_shapes=[sm((n,), jnp.int32)],  # fill counters
        interpret=interpret,
    )(
        dst_g.astype(jnp.int32),
        cnt.reshape(g).astype(jnp.int32),
        subj.astype(jnp.int32),
        key.astype(jnp.int32),
        pos,
    )

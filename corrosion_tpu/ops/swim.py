"""Batched SWIM failure detection + infection-style dissemination, as one
jitted tick over node-state arrays.

The reference runs one foca SWIM state machine per process, event-driven
(`klukai-agent/src/broadcast/mod.rs:121-386`, with foca's WAN config at
`:951-960`). This kernel re-architects that for TPU: ALL members advance one
protocol period per `tick`, and every message-level merge is expressed as a
scatter-max thanks to the key encoding below. This is what lets a devcluster
simulate 10^4–10^6 members on TPU cores instead of one async task per node.

## Key encoding

A member's knowledge about a subject is one small integer (stored int16
in the view — see VIEW_DTYPE — and int32 everywhere else):

    key = 0                     unknown (never heard of the subject)
    key = (inc + 1) * 4 + prec  known, at incarnation `inc`, with
                                prec: 0 = alive, 1 = suspect, 2 = down

SWIM's update-precedence rules (higher incarnation wins; for the same
incarnation `down > suspect > alive`) make `max(key_a, key_b)` exactly the
protocol merge, so delivering any number of gossip messages is
`view.at[dst, subj].max(key)` — a single batched scatter-max, and views are
monotone (a member's knowledge never goes backwards, matching foca).

## Protocol per tick (one SWIM protocol period)

1. probe FSM: idle members pick a random known-alive target and ping it;
   unacked direct pings escalate to `indirect_probes` helpers; unacked
   indirect pings raise a suspicion (suspect update + per-prober timer, the
   SWIM/Lifeguard rule that only the *prober* runs the suspicion timer)
2. suspicion timers that expire un-refuted declare the subject down
3. gossip: every member sends its `piggyback` least-transmitted buffered
   updates to `fanout` random known-alive targets (infection-style with
   per-update send counts and `max_transmissions` decay, mirroring the
   broadcast loop's re-send policy in `broadcast/mod.rs:653-812`)
4. delivery: scatter-max; updates that *improved* a receiver's view enter
   the receiver's own gossip buffer (epidemic relay); a member that hears
   itself suspected/downed at its current incarnation refutes by bumping
   its incarnation and gossiping a fresh alive update
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from corrosion_tpu.runtime.metrics import FLIGHT_CENSUS, KERNEL_EVENTS

INT32_MAX = jnp.iinfo(jnp.int32).max

# ---------------------------------------------------------------------------
# device telemetry lane (r7): every tick accumulates an [N_EVENTS] int32
# vector of protocol events — what happened ON DEVICE — into the state
# carry, so event totals ride the scan/while_loop like any other lane and
# reach the host in the same readback as the stats (zero extra syncs).
# `KERNEL_EVENTS` (runtime/metrics.py) is the single source of the lane
# order; counters are exact int32 sums of the masks the tick already
# materializes, so the lane is free of extra gathers and bit-identical
# under member-axis sharding (integer reduction).  Totals wrap mod 2^32
# by design: drains compute wrap-safe uint32 deltas (models/cluster.py),
# valid while any single drain window stays under 2^32 events (~200
# ticks at the 1M×2048 rung's message rate — every driver drains far
# more often).

N_EVENTS = len(KERNEL_EVENTS)
_EV_IDX = {name: i for i, name in enumerate(KERNEL_EVENTS)}

# ---------------------------------------------------------------------------
# flight recorder (r8): besides the cumulative lane, both SWIM kernels
# carry a [ring_ticks, N_FLIGHT_LANES] int32 ring in the scan state — per
# tick, row t % ring_ticks records THIS tick's event-delta vector (the
# diff of the cumulative lane, no new masks) followed by a compact census
# frame (FLIGHT_CENSUS order).  One dynamic_update_slice per tick, so the
# fused tick still lowers to one scan and stays donation-aliased; the
# ring drains in the same `stats_and_events` readback as everything else
# (zero extra host syncs) and replicates across the mesh like the events
# lane (parallel/mesh.py).  At the default 128×16 the ring is 8 KiB —
# invisible next to any view/table.  Conservation invariant (pinned by
# tests/test_flight_recorder.py): over any window that fits the ring,
# sum(ring event rows) == cumulative-lane delta, bit-exactly.

N_CENSUS = len(FLIGHT_CENSUS)
N_FLIGHT_LANES = N_EVENTS + N_CENSUS


def _census_frame(n: int, alive, susp_subj, inc, in_subj, lhm) -> jax.Array:
    """[N_CENSUS] int32 point-in-time census in FLIGHT_CENSUS order.
    Every term is an [N]-shaped integer reduction over state the tick
    already holds — deliberately NO whole-view/table pass (that would
    put an O(N^2)/O(N·K) reduction in every tick; the blocked stats
    pass stays the readback-time answer for view-derived census)."""
    return jnp.stack(
        [
            _bsum(alive),
            _bsum(susp_subj < n),
            _bsum(~alive),
            jnp.max(jnp.sum(in_subj < n, axis=1, dtype=jnp.int32)),
            jnp.max(inc),
            jnp.max(lhm),  # r9: worst Local Health Multiplier score
        ]
    )


def _susp_shrink_table(params) -> jax.Array:
    """[susp_k + 1] int32 Lifeguard LHA-Suspicion deadline table:
    entry c = the open-timer duration (in ticks) once c confirming
    suspect messages have been observed — starts at the ceiling
    `suspicion_ticks * susp_ceiling`, shrinks logarithmically to the
    `suspicion_ticks` floor at c = susp_k (Lifeguard's
    max - (max-min) * log(c+1)/log(k+1) curve, arXiv:1707.00788 §4.2).
    Static python math: the table compiles in as a constant.  Shared by
    the dense and partial-view kernels."""
    import math

    lo = params.suspicion_ticks
    hi = params.suspicion_ticks * params.susp_ceiling
    k = max(1, params.susp_k)
    return jnp.asarray(
        [
            max(
                lo,
                math.ceil(
                    hi - (hi - lo) * math.log2(c + 1) / math.log2(k + 1)
                ),
            )
            for c in range(k + 1)
        ],
        dtype=jnp.int32,
    )


def _ring_write(ring, t, ring_ticks: int, frame) -> jax.Array:
    """Record one tick's [N_FLIGHT_LANES] frame at row t % ring_ticks
    (one dynamic_update_slice — in-place under donation)."""
    return jax.lax.dynamic_update_slice(
        ring,
        frame[None, :],
        (jnp.mod(t, jnp.int32(ring_ticks)), jnp.int32(0)),
    )


class FlightDrain(NamedTuple):
    """Host-side snapshot of the device ring: the raw [R, L] rows plus
    the absolute tick they were drained at.  Row j holds the frame of
    tick j + k*R for the largest k keeping it < t — i.e. ticks
    [max(0, t - R), t) are live; `runtime.records.frames_from_ring`
    does the stitching arithmetic in ONE place."""

    ring: object  # np.ndarray [ring_ticks, N_FLIGHT_LANES] int32
    t: int


def _bsum(mask) -> jax.Array:
    """Exact int32 count of a bool mask (sharding-stable: integer adds)."""
    return jnp.sum(mask, dtype=jnp.int32)


def _event_vector(**counts) -> jax.Array:
    """Stack per-event scalar counts into the canonical lane order."""
    vals = [
        jnp.asarray(counts.pop(name), dtype=jnp.int32)
        for name in KERNEL_EVENTS
    ]
    if counts:  # a typo'd event name must not vanish silently
        raise ValueError(f"unknown kernel events: {sorted(counts)}")
    return jnp.stack(vals)

PREC_ALIVE = 0
PREC_SUSPECT = 1
PREC_DOWN = 2


class SwimParams(NamedTuple):
    """Static protocol parameters (hashable → usable as jit static arg)."""

    n: int  # member count
    fanout: int = 2  # gossip targets per tick
    piggyback: int = 8  # updates per gossip message
    buffer_slots: int = 16  # per-member update buffer (B)
    incoming_slots: int = 16  # per-member gossip inbox capacity per tick (R)
    susp_slots: int = 4  # concurrent suspicion timers per member (S)
    max_transmissions: int = 10  # foca-style re-send decay
    direct_timeout: int = 1  # ticks to wait for a direct ack
    indirect_timeout: int = 1  # ticks to wait for indirect acks
    indirect_probes: int = 3  # helpers for an indirect probe (foca WAN: 3)
    suspicion_ticks: int = 6  # suspect → down without refutation
    probe_candidates: int = 4  # random candidates tried to find a target
    antientropy: int = 2  # random view entries pushed per gossip message
    feed_entries: int = 25  # entries per announce/feed exchange (≈ one
    # 1178-byte SWIM packet's worth of member records, the foca feed that
    # bulk-transfers member lists on join/announce)
    feeds_per_tick: int = 4  # feed packets exchanged per protocol period;
    # a protocol period is ~1 s, so k feeds/tick ≈ k packets/s of
    # member-list transfer per member — bump for large clusters
    announce_period: int = 8  # every A ticks each member re-injects its
    # own record into the gossip stream (foca's periodic announce).
    # Guarantees every subject a re-offer rate independent of how
    # widely it is currently held — without it, bounded partial views
    # drift rich-get-richer until rare members go extinct
    loss: float = 0.0  # iid per-leg message loss probability
    inbox_impl: str = "gsort"  # gossip-inbox build: "sort" (flat
    # lax.sort, the r3 baseline), "gsort" (grouped sort: only the
    # N*fanout packet heads are sorted — messages in one packet share a
    # destination; ~20% faster tick at n=10k on the CPU fallback, default),
    # or "pallas" (sequential grouped scatter kernel, ops/inbox_pallas.py).
    # All three are bit-equal (tests/test_inbox_impls.py).
    gossip_mode: str = "shift"  # gossip target selection: "shift"
    # (default — r5 decision, COMPONENTS.md): per-(tick, fanout-slot)
    # random GLOBAL offsets: member i sends slot j's packet to
    # (i + off_j) mod n, so delivery is an exact row gather — no sort,
    # no bounded-inbox drop, and no target-pick view scans.  The same
    # rotating-permutation idea as the feed windows; per-tick random
    # offsets keep partner choice decorrelated across ticks.  Targets
    # are not alive-biased: sends to dead members are masked and
    # wasted, a small overhead at realistic churn.  "pick": each member
    # independently picks known-alive targets; delivery needs the
    # sort-based inbox build above.  Decided on the measured CPU A/B
    # (shift 11.70 s / stable_tick 55 vs pick 14.16 s / 70 at n=10k,
    # PROFILE.md) after the chip window never came; revert criterion
    # recorded in COMPONENTS.md.
    ring_ticks: int = 128  # flight-recorder depth (per-tick frames kept
    # on device; see the ring note above). 0 disables the ring (the
    # state carries a [0, L] array — a perf A/B lever, not a default).
    # ---- Lifeguard (r9, arXiv:1707.00788) --------------------------------
    lhm_max: int = 0  # Local Health Multiplier ceiling; 0 DISABLES all
    # three Lifeguard mechanisms (the compat default: with lhm off the
    # tick is bit-equal to the pre-r9 kernel — no extra rng draws, no
    # protocol-lane writes; only the new state lanes exist, zeroed).
    # >0 enables: each member's probe timeouts and protocol period
    # scale by (1 + its saturating health score in [0, lhm_max]).
    lhm_decay_ticks: int = 8  # a successful probe round decrements the
    # score only once per this many ticks — the paper's asymmetric
    # ramp-fast/relax-slow shape, which keeps a persistently sick
    # member's multiplier pinned high instead of oscillating
    susp_ceiling: int = 3  # LHA-Suspicion: a fresh suspicion timer's
    # deadline starts at susp_ceiling * suspicion_ticks and shrinks
    # toward suspicion_ticks as confirmations arrive
    susp_k: int = 3  # confirming suspect messages needed to shrink the
    # deadline all the way to the suspicion_ticks floor (log curve)


VIEW_DTYPE = jnp.int16
INC_CAP = 8189  # incarnations saturate here: (INC_CAP+1)*4 + prec < 2^15
"""The [N, N] view stores keys as int16: it is BY FAR the dominant array
(HBM footprint and feed/update traffic both halve vs int32 — measured
~30% off the CPU fallback's memory-bound tick), and SWIM keys fit with
room to spare — key = (inc+1)*4 + prec needs inc <= INC_CAP = 8189,
while real incarnations stay in the tens (foca bumps only on
refutation). Incarnations are capped where they are GENERATED
(refutation, restart), so in-range keys pass `to_view_key` untouched;
the clamp there is defense in depth and preserves the precedence bits —
a saturated key must not decode as a different member state. Gossip
buffers and inboxes stay int32."""

# Saturated keys clamp to incarnation INC_CAP exactly — the maximum any
# in-repo generator can emit — so an overflowing int32 gossip key ranks
# EQUAL to a capped-generation key, never below it (a lower clamp would
# let a stale capped key beat a saturated refutation).
_KEY_CLAMP_BASE = (INC_CAP + 1) * 4  # multiple of 4: prec bits survive


def finger_offsets(n: int) -> jnp.ndarray:
    """Chord-style bootstrap offsets: powers of two 1, 2, 4, ..., up to
    the largest power of two below n (8192 at n=10000 — NOT exactly n/2
    for non-power-of-2 n). One definition shared by the dense and
    partial-view kernels so their bootstrap graphs cannot diverge."""
    bits = max(1, (n - 1).bit_length())
    return (2 ** jnp.arange(bits)).astype(jnp.int32)


def to_view_key(key):
    """Cast an int32 key for storage in the int16 view; out-of-range keys
    (unreachable once incarnations cap at INC_CAP) saturate WITHOUT
    changing their precedence class."""
    over = key > jnp.int32(INC_CAP + 1) * 4 + 3
    clamped = jnp.where(over, _KEY_CLAMP_BASE + (key & 3), key)
    return clamped.astype(VIEW_DTYPE)


def make_key(inc, prec):
    return (inc + 1) * 4 + prec


def key_inc(key):
    return key // 4 - 1


def key_prec(key):
    return key % 4


def key_known(key):
    return key > 0


class SwimState(NamedTuple):
    t: jax.Array  # () int32 — current tick
    alive: jax.Array  # [N] bool — ground truth process liveness
    inc: jax.Array  # [N] int32 — own incarnation
    view: jax.Array  # [N, N] VIEW_DTYPE (int16) — key matrix, view[obs, subj]
    buf_subj: jax.Array  # [N, B] int32 — gossip buffer subject (N = empty)
    buf_key: jax.Array  # [N, B] int32
    buf_sent: jax.Array  # [N, B] int32 — send count (empty slots hold
    # INT32_MAX at init; merges normalize them to _SENT_CLAMP — detect
    # empties via subj == n, or sent >= max_transmissions for sendability)
    probe_phase: jax.Array  # [N] int32 — 0 idle / 1 direct / 2 indirect
    probe_subj: jax.Array  # [N] int32
    probe_deadline: jax.Array  # [N] int32
    probe_ok: jax.Array  # [N] bool — will the pending ack arrive?
    susp_subj: jax.Array  # [N, S] int32 (N = empty)
    susp_inc: jax.Array  # [N, S] int32
    susp_deadline: jax.Array  # [N, S] int32
    partition: jax.Array  # [N] int32 — network partition group: messages,
    # probe legs and feed exchanges only succeed between members of the
    # same group (0 = default single network). This is what lets the
    # batched kernel simulate split-brain and asymmetric reachability —
    # the r2 verdict's "oracle" criticism: iid loss alone cannot model
    # per-link partitions
    events: jax.Array  # [N_EVENTS] int32 — cumulative on-device event
    # telemetry in KERNEL_EVENTS order (wraps mod 2^32; see lane note
    # above). NOT a per-member array: sharding replicates it
    # (parallel/mesh.py special-cases the field by name)
    ring: jax.Array  # [ring_ticks, N_FLIGHT_LANES] int32 — the flight
    # recorder: per-tick event deltas + census frames (see ring note
    # above). Replicated under sharding like `events` (by name)
    # ---- Lifeguard lanes (r9) — all per-member, member-sharded -----------
    lhm: jax.Array  # [N] int32 — Local Health Multiplier score in
    # [0, lhm_max]: +1 per missed direct ack / failed indirect probe /
    # hearing oneself suspected; -1 per successful probe round (rate-
    # limited to one decrement per lhm_decay_ticks). Effective timeout/
    # period multiplier is 1 + score. All-zero when lhm_max == 0.
    susp_conf: jax.Array  # [N, S] int32 — confirming suspect messages
    # observed per OPEN suspicion timer (capped at susp_k); shrinks
    # that timer's deadline along _susp_shrink_table
    susp_start: jax.Array  # [N, S] int32 — tick the timer opened
    deg_loss: jax.Array  # [N] float32 — FAULT INJECTION: the member's
    # outbound datagram loss (gossip sends + every probe leg it
    # originates). 0 everywhere = today's iid `params.loss` exactly.
    deg_lag: jax.Array  # [N] int32 — FAULT INJECTION: the member's own
    # failure-detector processing lag in ticks (CPU starvation / GC
    # pause: acks land but are observed late). A probe by member i only
    # succeeds when its window `timeout * (1 + lhm_i)` covers
    # `timeout + deg_lag[i]` — the Lifeguard flaky-accuser pathology.
    # Wire-level slowness of a peer is the host net layer's
    # `node_latency` knob (net/mem.py), not this lane.


def init_state(
    params: SwimParams,
    rng: jax.Array,
    seeds_per_member: int = 3,
    seed_mode: str = "ring",
) -> SwimState:
    """Freshly booted cluster: every member knows itself plus a few
    bootstrap seeds (`seed_mode="ring"`: the next k members, like a
    devcluster ring topology; `"hub"`: everyone knows members 0..k-1;
    `"fingers"`: Chord-style power-of-two offsets (`finger_offsets`) — a
    bootstrap list whose graph is a log-diameter expander, so
    feed-partner picks reach long-range peers from tick 0 instead of
    staying ring-local until random picks start landing. All three are
    just devcluster bootstrap-address choices: a real deployment
    configures gossip.bootstrap freely, and log2(n) configured
    addresses is modest (17 entries at 100k)."""
    # ONE jitted program (the pview kernel learned this at r5 chip
    # scale): run eagerly, every `.at[].set` on the [N, N] view is its
    # own dispatch producing a fresh view-sized buffer — at n=80k the
    # tunnel backend's lazy deallocation of that churn starved the next
    # allocation (membership_stats OOMed at runtime with only ~13 GB
    # live).  Jitted, init is a single output buffer and one compile.
    return _init_state_impl(params, rng, seeds_per_member, seed_mode)


@functools.partial(
    jax.jit, static_argnames=("params", "seeds_per_member", "seed_mode")
)
def _init_state_impl(
    params: SwimParams,
    rng: jax.Array,
    seeds_per_member: int,
    seed_mode: str,
) -> SwimState:
    n, b, s = params.n, params.buffer_slots, params.susp_slots
    view = jnp.zeros((n, n), dtype=VIEW_DTYPE)
    idx = jnp.arange(n)
    view = view.at[idx, idx].set(make_key(0, PREC_ALIVE))
    alive_key = make_key(0, PREC_ALIVE)
    if seed_mode == "ring":
        for k in range(1, seeds_per_member + 1):
            view = view.at[idx, (idx + k) % n].set(alive_key)
    elif seed_mode == "hub":
        k = min(seeds_per_member, n)
        view = view.at[:, :k].set(alive_key)
        view = view.at[idx, idx].set(make_key(0, PREC_ALIVE))
    elif seed_mode == "fingers":
        # one batched scatter (a per-stride loop would copy the [N, N]
        # view log2(n) times at init)
        strides = finger_offsets(n)
        view = view.at[
            idx[:, None], (idx[:, None] + strides[None, :]) % n
        ].set(alive_key)
    else:
        raise ValueError(f"unknown seed_mode {seed_mode!r}")

    # each member starts with an announce of itself in its gossip buffer
    buf_subj = jnp.full((n, b), n, dtype=jnp.int32)
    buf_key = jnp.zeros((n, b), dtype=jnp.int32)
    buf_sent = jnp.full((n, b), INT32_MAX, dtype=jnp.int32)
    buf_subj = buf_subj.at[:, 0].set(idx.astype(jnp.int32))
    buf_key = buf_key.at[:, 0].set(alive_key)
    buf_sent = buf_sent.at[:, 0].set(0)

    return SwimState(
        t=jnp.int32(0),
        alive=jnp.ones(n, dtype=bool),
        inc=jnp.zeros(n, dtype=jnp.int32),
        view=view,
        buf_subj=buf_subj,
        buf_key=buf_key,
        buf_sent=buf_sent,
        probe_phase=jnp.zeros(n, dtype=jnp.int32),
        probe_subj=jnp.full(n, n, dtype=jnp.int32),
        probe_deadline=jnp.zeros(n, dtype=jnp.int32),
        probe_ok=jnp.zeros(n, dtype=bool),
        susp_subj=jnp.full((n, s), n, dtype=jnp.int32),
        susp_inc=jnp.zeros((n, s), dtype=jnp.int32),
        susp_deadline=jnp.zeros((n, s), dtype=jnp.int32),
        partition=jnp.zeros(n, dtype=jnp.int32),
        events=jnp.zeros(N_EVENTS, dtype=jnp.int32),
        ring=jnp.zeros(
            (params.ring_ticks, N_FLIGHT_LANES), dtype=jnp.int32
        ),
        lhm=jnp.zeros(n, dtype=jnp.int32),
        susp_conf=jnp.zeros((n, s), dtype=jnp.int32),
        susp_start=jnp.zeros((n, s), dtype=jnp.int32),
        deg_loss=jnp.zeros(n, dtype=jnp.float32),
        deg_lag=jnp.zeros(n, dtype=jnp.int32),
    )


def _pick_known_alive(view_rows, self_idx, rng, params: SwimParams, tries: int):
    """Per member, return a subject its view says is alive (excluding
    self); n if none found. Tries `tries` random offsets first (uniform
    member sampling once views are populated), then falls back to small
    ring offsets — the bootstrap seeds — so freshly-booted members with
    near-empty views can still find their seed peers at any cluster size."""
    n = params.n
    offs = jax.random.randint(rng, (view_rows.shape[0], tries), 1, n)
    ring = jax.random.randint(rng, (view_rows.shape[0], 2), 1, 4)
    offs = jnp.concatenate([offs, ring], axis=1)
    cands = (self_idx[:, None] + offs) % n
    keys = jnp.take_along_axis(view_rows, cands, axis=1)
    ok = key_known(keys) & (key_prec(keys) == PREC_ALIVE) & (cands != self_idx[:, None])
    first = jnp.argmax(ok, axis=1)
    found = jnp.any(ok, axis=1)
    pick = jnp.take_along_axis(cands, first[:, None], axis=1)[:, 0]
    return jnp.where(found, pick, n)


# (key, sent) pack into one non-negative int31: keys are capped at
# make_key(INC_CAP, 3) = 32763 < 2^15 everywhere they are generated (see
# VIEW_DTYPE note), and real send counts stay ≤ max_transmissions+fanout
# ≪ 2^15 — the INT32_MAX empty sentinel clamps to _SENT_CLAMP, which
# still orders after every real count.
#
# CROSS-KERNEL CONTRACT (r6): the 15-bit key domain and _SENT_CLAMP are
# load-bearing for the partial-view kernel too — swim_pview stores its
# buf_key/buf_sent lanes as int16 at rest (LANE_DTYPE) precisely because
# every merged key stays < 2^15 and every merged send count stays
# <= _SENT_CLAMP = 2^15 - 1 (the int16 maximum, exactly), and it
# INITIALIZES empty buf_sent slots at _SENT_CLAMP rather than the dense
# kernel's INT32_MAX sentinel (trajectory-identical: the first merge
# normalizes the sentinel to the clamp, and every consumer only tests
# `sent < max_transmissions` or ordering).  Widening _KEY_BITS would
# silently overflow those lanes — change both together.
_KEY_BITS = 15
_KEY_MAX = (1 << _KEY_BITS) - 1
_SENT_CLAMP = (1 << _KEY_BITS) - 1


def buffer_merge_lex(params, buf_subj, buf_key, buf_sent,
                     in_subj, in_key):
    """Three-operand lexicographic form of the buffer merge — correct
    for FULL int32 keys. The partial-view kernel must use this one: its
    refutation incarnations clip to `swim_pview.inc_cap(n)` (up to ~2^21
    at small n), far above the dense kernel's 15-bit key domain that
    `_buffer_merge`'s packed sort requires."""
    n = params.n
    subj = jnp.concatenate([buf_subj, in_subj], axis=1)
    key = jnp.concatenate([buf_key, in_key], axis=1)
    sent = jnp.concatenate(
        [buf_sent, jnp.where(in_subj < n, 0, INT32_MAX)], axis=1
    )
    # lexicographic sort per row: subject asc, key desc, sent asc
    subj_s, negkey_s, sent_s = jax.lax.sort(
        (subj, -key, sent), dimension=1, num_keys=3
    )
    key_s = -negkey_s
    dup = jnp.concatenate(
        [jnp.zeros((subj.shape[0], 1), bool), subj_s[:, 1:] == subj_s[:, :-1]],
        axis=1,
    )
    subj_s = jnp.where(dup, n, subj_s)
    key_s = jnp.where(dup, 0, key_s)
    sent_s = jnp.where(dup, INT32_MAX, sent_s)
    # keep least-sent first; empties (sent=INT32_MAX) sort last
    sent_f, subj_f, key_f = jax.lax.sort(
        (sent_s, subj_s, key_s), dimension=1, num_keys=1
    )
    b = params.buffer_slots
    return subj_f[:, :b], key_f[:, :b], sent_f[:, :b]


def _buffer_merge(params: SwimParams, buf_subj, buf_key, buf_sent,
                  in_subj, in_key):
    """Merge incoming updates (send_count 0) into each member's buffer:
    dedupe by subject keeping the highest key, then keep the
    `buffer_slots` least-transmitted entries (drop-most-sent overflow,
    like the reference's queue trim at broadcast/mod.rs:793-812).

    DENSE-KERNEL ONLY: requires keys < 2^15, which the dense kernel
    guarantees (incarnations cap at INC_CAP, the int16-view invariant).
    The partial-view kernel's keys can reach inc_cap(n) ≈ 2^21 — it
    must call `buffer_merge_lex` instead.

    Both row sorts co-sort TWO operands instead of three by packing
    (key desc, sent asc) — and then (sent asc, key desc) — into one
    int31 word (~20% off the phase, the tick's hottest after the
    grouped inbox landed). The pack preserves the exact lexicographic
    order of the r3 three-operand sort for the dedupe pass; the trim
    pass additionally becomes DETERMINISTIC on send-count ties (fresher
    keys first), where the old single-key sort left tie order to XLA.
    Empty slots come back with sent = _SENT_CLAMP (not INT32_MAX);
    every consumer only tests `sent < max_transmissions` or ordering."""
    n = params.n
    subj = jnp.concatenate([buf_subj, in_subj], axis=1)
    key = jnp.concatenate([buf_key, in_key], axis=1)
    sent = jnp.concatenate(
        [buf_sent, jnp.where(in_subj < n, 0, INT32_MAX)], axis=1
    )
    sent_c = jnp.minimum(sent, _SENT_CLAMP)
    # sort 1: subject asc, then (key desc, sent asc) as one packed word
    combo = ((_KEY_MAX - key) << _KEY_BITS) | sent_c
    subj_s, combo_s = jax.lax.sort((subj, combo), dimension=1, num_keys=2)
    key_s = _KEY_MAX - (combo_s >> _KEY_BITS)
    sent_s = combo_s & _SENT_CLAMP
    dup = jnp.concatenate(
        [jnp.zeros((subj.shape[0], 1), bool), subj_s[:, 1:] == subj_s[:, :-1]],
        axis=1,
    )
    subj_s = jnp.where(dup, n, subj_s)
    # sort 2: least-sent first (empties/dups sort last), fresher keys
    # first within a send-count tie
    combo2 = jnp.where(
        dup,
        (_SENT_CLAMP << _KEY_BITS) | _KEY_MAX,
        (sent_s << _KEY_BITS) | (_KEY_MAX - key_s),
    )
    combo2_f, subj_f = jax.lax.sort((combo2, subj_s), dimension=1, num_keys=1)
    b = params.buffer_slots
    combo2_f = combo2_f[:, :b]
    return (
        subj_f[:, :b],
        _KEY_MAX - (combo2_f & _KEY_MAX),
        combo2_f >> _KEY_BITS,
    )


def build_inbox(
    n: int, slots: int, dst: jax.Array, subj: jax.Array, key: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Compact flat (dst, subj, key) messages into bounded per-member
    inboxes [n, slots]: one stable sort by destination, in-group ranks by
    associative scan, then a unique-cell scatter. Masked messages carry
    dst = n and sort past every real destination. Shared by the dense
    and partial-view SWIM kernels."""
    dst_s, subj_s, key_s = jax.lax.sort(
        (dst, subj, key), dimension=0, num_keys=1, is_stable=True
    )
    mlen = dst_s.shape[0]
    pos = jnp.arange(mlen, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), dst_s[1:] != dst_s[:-1]]
    )
    first = jax.lax.associative_scan(jnp.maximum, jnp.where(is_start, pos, 0))
    rank = pos - first
    ok = (dst_s < n) & (rank < slots)
    # scatter with min/max so masked duplicate (0, 0) writes are no-ops:
    # each real (row, rank) cell receives at most one message (ranks are
    # unique per destination), so min(subj)/max(key) both pick that message
    rows = jnp.where(ok, dst_s, 0)
    cols = jnp.where(ok, rank, 0)
    in_subj = jnp.full((n, slots), n, dtype=jnp.int32)
    in_key = jnp.zeros((n, slots), dtype=jnp.int32)
    in_subj = in_subj.at[rows, cols].min(jnp.where(ok, subj_s, n))
    in_key = in_key.at[rows, cols].max(jnp.where(ok, key_s, 0))
    return in_subj, in_key


def build_inbox_grouped(
    n: int,
    slots: int,
    dst_g: jax.Array,
    subj: jax.Array,
    key: jax.Array,
    ok: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Grouped inbox build, bit-equal to `build_inbox` over the flattened
    message list. Gossip messages leave in packets: all `m` piggybacked
    updates of one (sender, fanout-slot) pair share a destination, so the
    flat [G*m] list is G runs of m equal-dst messages in group-major
    order. Only the G packet heads need the stable sort-by-destination;
    a message's inbox column is then (exclusive prefix of valid counts
    over earlier same-dst packets) + (valid-prefix within its packet).
    Shrinks the dominant lax.sort from G*m to G elements — the r3 CPU
    profile had the flat sort at ~60% of the tick.

    `dst_g` is [G] (real destinations, already clipped to [0, n));
    `subj`/`key`/`ok` are [G, m]; masked messages are dropped exactly
    like the flat path's dst=n sentinel ones.
    """
    g = dst_g.shape[0]
    cnt = jnp.sum(ok, axis=1).astype(jnp.int32)
    pos = jnp.cumsum(ok, axis=1).astype(jnp.int32) - ok.astype(jnp.int32)
    order = jnp.arange(g, dtype=jnp.int32)
    dst_s, idx_s, cnt_s = jax.lax.sort(
        (dst_g, order, cnt), dimension=0, num_keys=1, is_stable=True
    )
    cum_before = jnp.cumsum(cnt_s) - cnt_s
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), dst_s[1:] != dst_s[:-1]]
    )
    # cum_before is non-decreasing, so a running max of segment-start
    # values yields each packet's own segment base
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, cum_before, 0)
    )
    base_s = cum_before - seg_start
    base = jnp.zeros((g,), jnp.int32).at[idx_s].set(base_s)
    col = base[:, None] + pos
    keep = ok & (col < slots)
    rows = jnp.where(keep, dst_g[:, None], 0)
    cols = jnp.where(keep, col, 0)
    # same unique-cell scatter as build_inbox: each real (row, col) cell
    # receives at most one message, masked writes to (0, 0) are no-ops
    in_subj = jnp.full((n, slots), n, dtype=jnp.int32)
    in_key = jnp.zeros((n, slots), dtype=jnp.int32)
    in_subj = in_subj.at[rows, cols].min(jnp.where(keep, subj, n))
    in_key = in_key.at[rows, cols].max(jnp.where(keep, key, 0))
    return in_subj, in_key


def dispatch_inbox(
    impl: str,
    n: int,
    slots: int,
    dst_g: jax.Array,
    subj_gm: jax.Array,
    key_gm: jax.Array,
    ok_gm: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Build the bounded inbox with the selected implementation. All
    impls consume the grouped [G, m] form and are bit-equal; "sort"
    flattens to the r3 flat-sort path."""
    if impl == "gsort":
        return build_inbox_grouped(n, slots, dst_g, subj_gm, key_gm, ok_gm)
    if impl == "pallas":
        from corrosion_tpu.ops.inbox_pallas import build_inbox_pallas

        return build_inbox_pallas(n, slots, dst_g, subj_gm, key_gm, ok_gm)
    if impl != "sort":
        # a typo must not silently select the slowest path
        raise ValueError(f"unknown inbox_impl {impl!r}")
    dst = jnp.where(ok_gm, dst_g[:, None], n).reshape(-1)
    subj = jnp.where(ok_gm, subj_gm, n).reshape(-1)
    key = jnp.where(ok_gm, key_gm, 0).reshape(-1)
    return build_inbox(n, slots, dst, subj, key)


def tick_impl(state: SwimState, rng: jax.Array, params: SwimParams) -> SwimState:
    """Advance every member one SWIM protocol period (trace-level impl;
    use `tick` for the jitted form, `tick_n` for k periods per dispatch)."""
    n = params.n
    idx = jnp.arange(n, dtype=jnp.int32)
    t = state.t
    r_probe, r_ack, r_helpers, r_gossip, r_loss = jax.random.split(rng, 5)

    view = state.view
    inc = state.inc
    alive = state.alive
    part = state.partition
    buf_subj, buf_key, buf_sent = state.buf_subj, state.buf_key, state.buf_sent
    susp_subj = state.susp_subj
    susp_inc = state.susp_inc
    susp_deadline = state.susp_deadline
    susp_conf = state.susp_conf
    susp_start = state.susp_start
    lhm = state.lhm
    deg_loss = state.deg_loss
    deg_lag = state.deg_lag

    # Lifeguard (r9): one STATIC switch for all three mechanisms.  Off
    # (lhm_max == 0, the default) every branch below compiles to exactly
    # the pre-r9 tick — same rng draws, same protocol-lane writes; the
    # fault-injection lanes stay live in both modes (all-zero lanes
    # reduce to the exact pre-r9 arithmetic, so the vanilla kernel can
    # host the degraded-node A/B).
    lifeguard = params.lhm_max > 0
    # effective per-member timeout/period multiplier (1 = healthy)
    mult = 1 + jnp.clip(lhm, 0, params.lhm_max) if lifeguard else 1
    # timer ceiling at registration: LHA-Suspicion opens at the ceiling
    # and shrinks with confirmations (phase 5c); vanilla opens at the
    # fixed window
    open_ticks = params.suspicion_ticks * (
        params.susp_ceiling if lifeguard else 1
    )

    # announcements generated this tick, merged into own view + buffer
    # later: suspect / down / refute / periodic self-announce
    own_upd_subj = jnp.full((n, 4), n, dtype=jnp.int32)
    own_upd_key = jnp.zeros((n, 4), dtype=jnp.int32)

    # ---- 1. probe FSM ----------------------------------------------------
    phase, psubj, pdl, pok = (
        state.probe_phase,
        state.probe_subj,
        state.probe_deadline,
        state.probe_ok,
    )

    # 1a. escalate expired indirect probes to suspicion
    expire2 = (phase == 2) & (t >= pdl) & alive
    fail2 = expire2 & ~pok
    # believed incarnation of the target
    tgt_key = view[idx, jnp.clip(psubj, 0, n - 1)]
    binc = jnp.maximum(key_inc(tgt_key), 0)
    susp_key = make_key(binc, PREC_SUSPECT)
    own_upd_subj = own_upd_subj.at[:, 0].set(jnp.where(fail2, psubj, n))
    own_upd_key = own_upd_key.at[:, 0].set(jnp.where(fail2, susp_key, 0))
    # register suspicion timer in a free slot (or steal the oldest);
    # every row writes exactly its own (row, slot) cell so masked rows
    # cannot clobber real writes via duplicate scatter indices
    slot_score = jnp.where(susp_subj == n, INT32_MAX, -susp_deadline)
    free_slot = jnp.argmax(slot_score, axis=1)
    old_subj = susp_subj[idx, free_slot]
    old_inc = susp_inc[idx, free_slot]
    old_dl = susp_deadline[idx, free_slot]
    susp_subj = susp_subj.at[idx, free_slot].set(jnp.where(fail2, psubj, old_subj))
    susp_inc = susp_inc.at[idx, free_slot].set(jnp.where(fail2, binc, old_inc))
    susp_deadline = susp_deadline.at[idx, free_slot].set(
        jnp.where(fail2, t + open_ticks, old_dl)
    )
    # fresh timers start with zero confirmations at this tick (the
    # lanes are maintained in both modes; only the deadline shrink is
    # lifeguard-gated — phase 5c)
    old_conf = susp_conf[idx, free_slot]
    old_start = susp_start[idx, free_slot]
    susp_conf = susp_conf.at[idx, free_slot].set(
        jnp.where(fail2, 0, old_conf)
    )
    susp_start = susp_start.at[idx, free_slot].set(
        jnp.where(fail2, t, old_start)
    )
    phase = jnp.where(expire2, 0, phase)
    if lifeguard:
        # LHA-Probe period stretch: a completed probe cycle (success or
        # suspicion) cools down for mult-1 extra ticks before the next
        # probe starts (phase-0 rows repurpose probe_deadline as the
        # cooldown; mult == 1 reproduces the vanilla same-tick restart)
        pdl = jnp.where(expire2, t + mult - 1, pdl)

    # 1b. escalate expired direct probes to indirect probes
    expire1 = (phase == 1) & (t >= pdl) & alive
    fail1 = expire1 & ~pok
    helpers = jax.random.randint(
        r_helpers, (n, params.indirect_probes), 0, n
    )
    psafe_t = jnp.clip(psubj, 0, n - 1)
    tgt_alive = alive[psafe_t] & (psubj < n)
    # raw leg draws ([:, 0] = direct round-trip, rest = per-helper
    # path); the loss threshold is per-pair — every participant's
    # injected outbound loss raises it (max with the iid base; all-zero
    # deg_loss reduces to `>= params.loss` bit-exactly)
    leg_u = jax.random.uniform(r_ack, (n, params.indirect_probes + 1))
    path_loss = jnp.maximum(
        params.loss,
        jnp.maximum(
            jnp.maximum(deg_loss[:, None], deg_loss[helpers]),
            deg_loss[psafe_t][:, None],
        ),
    )
    # a probe only succeeds when the prober's window covers the base
    # RTT plus ITS OWN processing lag (deg_lag: the Lifeguard flaky-
    # accuser injection; lag 0 is vacuously true)
    ind_win = params.indirect_timeout * mult
    ind_window_ok = ind_win >= params.indirect_timeout + deg_lag
    # an indirect path works only if prober→helper AND helper→target
    # are both within-partition
    helper_reach = (part[helpers] == part[:, None]) & (
        part[helpers] == part[psafe_t][:, None]
    )
    helper_ok = (
        alive[helpers] & (leg_u[:, 1:] >= path_loss)
        & tgt_alive[:, None] & helper_reach
    )
    ind_ok = jnp.any(helper_ok, axis=1) & ind_window_ok
    phase = jnp.where(fail1, 2, jnp.where(expire1, 0, phase))
    pok = jnp.where(fail1, ind_ok, pok)
    pdl = jnp.where(fail1, t + ind_win, pdl)
    if lifeguard:
        # completed-successfully rows cool down (see 1a); ~fail1, not
        # pok — pok was just reassigned to the escalated rows' outcome
        pdl = jnp.where(expire1 & ~fail1, t + mult - 1, pdl)

    # 1c. idle members start a new probe
    start = (phase == 0) & alive
    if lifeguard:
        # LHA-Probe: the protocol period stretches with the member's own
        # health score — phase-0 rows wait out their cooldown deadline
        start = start & (t >= pdl)
    target = _pick_known_alive(view, idx, r_probe, params, params.probe_candidates)
    will = start & (target < n)
    tsafe = jnp.clip(target, 0, n - 1)
    d_loss = jnp.maximum(
        params.loss, jnp.maximum(deg_loss, deg_loss[tsafe])
    )
    d_win = params.direct_timeout * mult
    direct_ok = (
        alive[tsafe] & (target < n) & (leg_u[:, 0] >= d_loss)
        & (part[tsafe] == part)
        & (d_win >= params.direct_timeout + deg_lag)
    )
    phase = jnp.where(will, 1, phase)
    psubj = jnp.where(will, target, psubj)
    pdl = jnp.where(will, t + d_win, pdl)
    pok = jnp.where(will, direct_ok, pok)

    # ---- 2. suspicion timers ---------------------------------------------
    sdl_hit = (susp_subj < n) & (t >= susp_deadline) & alive[:, None]
    ssub = jnp.clip(susp_subj, 0, n - 1)
    cur = view[idx[:, None], ssub]
    still = sdl_hit & (key_prec(cur) == PREC_SUSPECT) & (key_inc(cur) == susp_inc)
    down_key = make_key(susp_inc, PREC_DOWN)
    # at most one down-declaration per member per tick (rest fire next tick)
    fire_col = jnp.argmax(still, axis=1)
    fire = jnp.any(still, axis=1)
    fired_subj = jnp.take_along_axis(susp_subj, fire_col[:, None], axis=1)[:, 0]
    fired_key = jnp.take_along_axis(down_key, fire_col[:, None], axis=1)[:, 0]
    own_upd_subj = own_upd_subj.at[:, 1].set(jnp.where(fire, fired_subj, n))
    own_upd_key = own_upd_key.at[:, 1].set(jnp.where(fire, fired_key, 0))
    clear = (jnp.arange(params.susp_slots)[None, :] == fire_col[:, None]) & fire[:, None]
    clear = clear | (sdl_hit & ~still)  # refuted timers just clear
    susp_subj = jnp.where(clear, n, susp_subj)
    susp_conf = jnp.where(clear, 0, susp_conf)

    # ---- 3. gossip send --------------------------------------------------
    m, f = params.piggyback, params.fanout
    if params.gossip_mode == "shift":
        # per-(tick, slot) random global offsets; delivery in step 4 is
        # then an exact row gather (no sort).  1..n-1 excludes self-send.
        shift_off = jax.random.randint(
            jax.random.fold_in(r_gossip, 65537), (f,), 1, n,
            dtype=jnp.int32,
        )
        tg = (idx[:, None] + shift_off[None, :]) % n  # [N, f]
    else:
        # targets: known-alive picks per fanout slot
        tg = jnp.stack(
            [
                _pick_known_alive(
                    view, idx, jax.random.fold_in(r_gossip, j), params, 2
                )
                for j in range(f)
            ],
            axis=1,
        )  # [N, f]
    # least-sent m buffer entries are already sorted to the front by merge
    send_subj = buf_subj[:, :m]  # [N, m]
    send_key = buf_key[:, :m]
    sendable = (send_subj < n) & (buf_sent[:, :m] < params.max_transmissions)
    valid_tgt = tg < n  # [N, f]
    # bump send counts by the number of targets each entry was sent to
    nt = jnp.sum(valid_tgt & alive[:, None], axis=1)  # [N]
    buf_sent = buf_sent.at[:, :m].set(
        jnp.where(
            sendable & (nt[:, None] > 0),
            buf_sent[:, :m] + nt[:, None],
            buf_sent[:, :m],
        )
    )

    # anti-entropy tail correction: besides fresh updates, push a few
    # random entries from the sender's own view so dissemination cannot
    # die out short of full coverage once send counts decay (foca's
    # periodic announce/feed exchange plays this role)
    ae = params.antientropy
    if ae > 0:
        r_ae = jax.random.fold_in(r_gossip, 7919)
        ae_subj = jax.random.randint(r_ae, (n, ae), 0, n).astype(jnp.int32)
        ae_key = view[idx[:, None], ae_subj]
        send_subj = jnp.concatenate([send_subj, ae_subj], axis=1)
        send_key = jnp.concatenate([send_key, ae_key], axis=1)
        sendable = jnp.concatenate(
            [sendable, ae_key > 0], axis=1
        )
        m = m + ae

    # message triples [N, f, m] → flat [M], then a bounded per-member
    # inbox. The r2 profile showed the old path — scatter-maxing all M
    # messages into the [N, N] view at random (dst, subj) indices, plus an
    # argsort+searchsorted relay ranking — dominating the tick. Instead,
    # messages are sorted by destination ONCE (co-sorted lax.sort), ranked
    # within their destination group by an associative scan, and compacted
    # into a [N, incoming_slots] inbox; every later step (refutation, view
    # update, relay) is then row-aligned. Messages beyond the inbox cap
    # are dropped — bounded mailboxes, matching the reference's drop-oldest
    # processing queue (broadcast/mod.rs:793-812); anti-entropy tails and
    # the feed exchange repair any loss.
    tg_safe = jnp.clip(tg, 0, n - 1)
    msg_ok = (
        sendable[:, None, :]
        & valid_tgt[:, :, None]
        & alive[:, None, None]  # sender must be up
        & alive[tg_safe][:, :, None]  # receiver must be up
        & (part[tg_safe] == part[:, None])[:, :, None]  # same network
    )
    # the sender's injected outbound loss stacks on the iid base (max,
    # not product: one effective per-datagram loss probability); zero
    # deg_loss lanes reduce to `< params.loss` bit-exactly
    drop = (
        jax.random.uniform(r_loss, msg_ok.shape)
        < jnp.maximum(params.loss, deg_loss)[:, None, None]
    )
    # telemetry: emitted counts messages that would reach an up, same-
    # partition receiver; lost is the loss-injection slice of those
    ev_emitted = _bsum(msg_ok)
    ev_lost = _bsum(msg_ok & drop)
    msg_ok = msg_ok & ~drop

    # ---- 4. inbox: compact messages into bounded per-member inboxes ----
    subj_gm = jnp.broadcast_to(send_subj[:, None, :], msg_ok.shape)
    key_gm = jnp.broadcast_to(send_key[:, None, :], msg_ok.shape)
    if params.gossip_mode == "shift":
        # receiver r's slot-j packet comes from sender (r - off_j) mod n:
        # delivery is an exact [N, f] row gather of the masked send
        # planes into an [N, f*m] plane, row-compacted below to the
        # incoming_slots cap when it exceeds it (bounded-mailbox drops,
        # same contract as the pick path)
        src = (idx[:, None] - shift_off[None, :]) % n  # [N, f]
        sub_m = jnp.where(msg_ok, subj_gm, n)
        key_m = jnp.where(msg_ok, key_gm, 0)
        jj = jnp.arange(f, dtype=jnp.int32)[None, :]
        in_subj = sub_m[src, jj].reshape(n, f * m)
        in_key = key_m[src, jj].reshape(n, f * m)
        if f * m > params.incoming_slots:
            # row-local compaction to the inbox cap: valid messages
            # first (arrival order preserved — stable argsort), excess
            # dropped, exactly the pick path's bounded-mailbox contract.
            # A width-(f*m) ROW sort is trivia next to the [G]-element
            # destination sort this mode eliminates; it keeps the
            # downstream viewupd/bufmrg widths at slots+4 (measured on
            # the CPU fallback at n=10k: without compaction the wider
            # planes cost more than the destination sort saved).
            order = jnp.argsort(in_subj == n, axis=1, stable=True)
            take = order[:, : params.incoming_slots]
            in_subj = jnp.take_along_axis(in_subj, take, axis=1)
            in_key = jnp.take_along_axis(in_key, take, axis=1)
    else:
        # grouped [G, m] form (G = N*fanout packets, equal-dst runs); the
        # impl choice (flat sort / grouped sort / pallas) is bit-equal
        in_subj, in_key = dispatch_inbox(
            params.inbox_impl,
            n,
            params.incoming_slots,
            tg_safe.reshape(-1),
            subj_gm.reshape(-1, m),
            key_gm.reshape(-1, m),
            msg_ok.reshape(-1, m),
        )
    # survivors of the bounded-mailbox compaction; the cap's drops are
    # the delivered/overflowed split of (emitted - lost)
    ev_delivered = _bsum(in_subj < n)

    # ---- 4b. announce/feed exchange --------------------------------------
    # Each member pulls one packet's worth of member records from a random
    # known-alive partner: a rotating window over subject space, so every
    # subject is fed within ceil(n / feed_entries) exchanges. This is the
    # batched form of foca's Announce→Feed bulk member-list transfer, and
    # it is what bootstraps large clusters (per-update infection alone
    # cannot push 10^4+ simultaneous joins through bounded buffers).
    # The window start is GLOBAL (shared by all members this feed): that
    # turns the exchange into dynamic_slice + row-take + dynamic_update
    # _slice — contiguous, layout-friendly ops — instead of the r2
    # kernel's fully general two-axis gather, which the profile showed at
    # ~70% of the tick. Members still draw independent random partners, so
    # per-pair coverage decorrelates across sweeps.
    fe = min(params.feed_entries, n)
    nfeeds = params.feeds_per_tick
    steps_per_sweep = -(-n // fe) if fe > 0 else 1
    ev_feed = jnp.int32(0)
    ev_seed = jnp.int32(0)
    if fe > 0 and nfeeds > 0:  # ceil: windows per full subject sweep

        spacing = max(1, steps_per_sweep // nfeeds)

        def one_feed(k, carry):
            v, n_pulls = carry
            r_feed = jax.random.fold_in(r_gossip, 104729 + k)
            partner = _pick_known_alive(v, idx, r_feed, params, 2)
            psafe = jnp.clip(partner, 0, n - 1)
            # both ends must be up AND mutually reachable
            has_partner = (
                (partner < n) & alive & alive[psafe] & (part[psafe] == part)
            )
            # the tick's windows are staggered EVENLY across the sweep
            # (not adjacent): each subject is then fed nfeeds times per
            # sweep at spaced intervals, letting infection spread between
            # visits — spaced visits converge much faster than one
            # consecutive burst per sweep
            j = (t + k * spacing) % steps_per_sweep
            w = jnp.minimum(j * fe, n - fe)  # clamp final window to tail
            vw = jax.lax.dynamic_slice(v, (jnp.int32(0), w), (n, fe))
            pulled = jnp.take(vw, psafe, axis=0)  # [N, fe] partner rows
            pulled = jnp.where(has_partner[:, None], pulled, 0)
            return (
                jax.lax.dynamic_update_slice(
                    v, jnp.maximum(vw, pulled), (jnp.int32(0), w)
                ),
                n_pulls + _bsum(has_partner),
            )

        # unrolled (nfeeds is static, typically 4): a fori_loop here nests
        # an inner while around the [N, N] view inside tick_n's scan, and
        # XLA's copy insertion then double-buffers the view across the
        # loop boundary — a compile-time OOM at n=80k (24.2 G > 15.75 G
        # HBM; PROFILE.md "80k dense OOM" preserves the allocation
        # report). Unrolled, the whole tick
        # updates the view in place under donation. Unrolling is linear
        # in nfeeds (HLO size and compile time), so unusually large
        # values keep the rolled loop: those configs pay the view
        # double-buffer, which only matters where n is also huge.
        if nfeeds <= 8:
            for _k in range(nfeeds):
                view, ev_feed = one_feed(_k, (view, ev_feed))
        else:
            view, ev_feed = jax.lax.fori_loop(
                0, nfeeds, one_feed, (view, ev_feed)
            )

    # ---- 4c. bootstrap-seed exchange -------------------------------------
    # The reference's announcer keeps announcing to its CONFIGURED
    # bootstrap addresses forever, regardless of what gossip believes
    # about them (handlers.rs:197-248: the announce loop never stops).
    # Without this, a healed partition can never re-merge: each side
    # believes the other down, and every gossip/feed target pick
    # requires a believed-alive peer — a permanent split. One window
    # pull per tick from a rotating ring seed (ground-truth
    # reachability only) re-opens the information path; the feed's
    # diagonal refutation check then clears the stale down entries.
    if fe > 0:
        seed_off = 1 + (t // jnp.int32(max(1, params.announce_period))) % 3
        sp = (idx + seed_off) % n
        seed_ok = alive & alive[sp] & (part[sp] == part)
        j = t % steps_per_sweep
        w = jnp.minimum(j * fe, n - fe)
        vw = jax.lax.dynamic_slice(view, (jnp.int32(0), w), (n, fe))
        pulled = jnp.take(vw, sp, axis=0)
        pulled = jnp.where(seed_ok[:, None], pulled, 0)
        view = jax.lax.dynamic_update_slice(
            view, jnp.maximum(vw, pulled), (jnp.int32(0), w)
        )
        ev_seed = _bsum(seed_ok)

    # ---- 5. refutation (row-local over the inbox + own diag) -------------
    # a live member hearing itself suspect/down at ≥ its inc refutes by
    # bumping its incarnation; the diag check also catches suspicions that
    # arrived via a feed window rather than a gossip message
    about_self = (in_subj == idx[:, None]) & (key_prec(in_key) >= PREC_SUSPECT)
    worst_msg = jnp.max(jnp.where(about_self, key_inc(in_key), -1), axis=1)
    selfk = view[idx, idx]
    worst_diag = jnp.where(
        key_prec(selfk) >= PREC_SUSPECT, key_inc(selfk), -1
    )
    worst = jnp.maximum(worst_msg, worst_diag)
    if lifeguard:
        # LHA-Refute buddy system: a prober that STARTED a probe this
        # tick while holding a suspect entry about its target tells the
        # target in the ping payload — the target refutes immediately
        # instead of waiting for the suspicion to reach it by gossip.
        # Delivery rides the direct-probe leg draw (the ping must reach
        # an up, same-partition target); no extra rng is consumed.
        tkey = view[idx, tsafe]
        tell = (
            will & alive & alive[tsafe] & (part[tsafe] == part)
            & (leg_u[:, 0] >= d_loss)
            & (key_prec(tkey) == PREC_SUSPECT)
        )
        buddy = (
            jnp.full((n,), -1, dtype=jnp.int32)
            .at[jnp.where(tell, tsafe, n)]
            .max(
                jnp.where(tell, jnp.maximum(key_inc(tkey), 0), -1),
                mode="drop",
            )
        )
        worst = jnp.maximum(worst, buddy)
    refute = alive & (worst >= 0) & (worst >= inc)
    inc = jnp.where(refute, jnp.minimum(worst + 1, INC_CAP), inc)
    own_upd_subj = own_upd_subj.at[:, 2].set(jnp.where(refute, idx, n))
    own_upd_key = own_upd_key.at[:, 2].set(
        jnp.where(refute, make_key(inc, PREC_ALIVE), 0)
    )

    # ---- 5b. periodic self-announce (staggered by member id) -------------
    ev_announce = jnp.int32(0)
    if params.announce_period > 0:
        due = ((t + idx) % params.announce_period == 0) & alive
        own_upd_subj = own_upd_subj.at[:, 3].set(jnp.where(due, idx, n))
        own_upd_key = own_upd_key.at[:, 3].set(
            jnp.where(due, make_key(inc, PREC_ALIVE), 0)
        )
        ev_announce = _bsum(due)

    # ---- 5c. Lifeguard bookkeeping (LHA-Suspicion + LHM update) ----------
    ev_conf = jnp.int32(0)
    if lifeguard:
        # confirmations: suspect messages in THIS tick's gossip inbox
        # about a subject with an open timer, at the timer's believed
        # incarnation or newer (gossip the tick already delivers — no
        # extra traffic; message count approximates independent
        # suspectors, since SWIM suspect updates carry no origin)
        open_t = susp_subj < n  # [N, S] post-registration, post-clear
        msg_inc = key_inc(in_key)
        conf_msg = (
            (in_subj[:, None, :] == susp_subj[:, :, None])
            & (key_prec(in_key) == PREC_SUSPECT)[:, None, :]
            & (msg_inc[:, None, :] >= susp_inc[:, :, None])
        )  # [N, S, R] — S and R are small (4, ~16)
        conf_add = jnp.sum(conf_msg, axis=2, dtype=jnp.int32) * open_t
        ev_conf = jnp.sum(conf_add, dtype=jnp.int32)
        susp_conf = jnp.minimum(susp_conf + conf_add, params.susp_k)
        # deadline = start + shrink(confirmations): opens at the
        # ceiling, collapses toward the suspicion_ticks floor as
        # independent confirmations accumulate — a lone (possibly
        # wrong) suspector leaves the target the whole ceiling to
        # refute, while a cluster-wide true suspicion fires fast
        shrink = _susp_shrink_table(params)
        susp_deadline = jnp.where(
            open_t,
            susp_start + shrink[jnp.clip(susp_conf, 0, params.susp_k)],
            susp_deadline,
        )
        # LHM saturating counter: ramp on every local-health miss
        # (missed direct ack, failed indirect probe, hearing oneself
        # suspected), relax one step per successful probe round at most
        # once per lhm_decay_ticks (success = expired un-failed, judged
        # on the masks captured BEFORE pok was reassigned)
        succ = (expire1 & ~fail1) | (expire2 & ~fail2)
        dec = succ & (jnp.mod(t, jnp.int32(params.lhm_decay_ticks)) == 0)
        lhm = jnp.clip(
            lhm
            + fail1.astype(jnp.int32)
            + fail2.astype(jnp.int32)
            + refute.astype(jnp.int32)
            - dec.astype(jnp.int32),
            0,
            params.lhm_max,
        )

    # ---- 6. row-aligned view update + relay ------------------------------
    all_subj = jnp.concatenate([in_subj, own_upd_subj], axis=1)  # [N, R+3]
    all_key = jnp.concatenate([in_key, own_upd_key], axis=1)
    safe = jnp.clip(all_subj, 0, n - 1)
    eff_key = jnp.where(all_subj < n, all_key, 0)
    prev = view[idx[:, None], safe]
    eff_key16 = to_view_key(eff_key)
    # improvement judged on the STORED (clamped) key: a saturated key
    # must not re-register as improved on every tick
    improved = eff_key16 > prev
    view = view.at[idx[:, None], safe].max(eff_key16)
    # self-entries stay fresh (and reflect refutations immediately)
    self_key = make_key(inc, PREC_ALIVE)
    view = view.at[idx, idx].max(
        to_view_key(jnp.where(alive, self_key, 0))
    )

    # relay: improved updates about third parties enter the receiver's own
    # gossip buffer (epidemic relay); own announcements enter unconditionally
    relay_ok = improved & (all_subj != idx[:, None]) & (all_subj < n)
    bin_subj = jnp.concatenate(
        [jnp.where(relay_ok, all_subj, n), own_upd_subj], axis=1
    )
    bin_key = jnp.concatenate(
        [jnp.where(relay_ok, all_key, 0), own_upd_key], axis=1
    )

    buf_subj, buf_key, buf_sent = _buffer_merge(
        params, buf_subj, buf_key, buf_sent, bin_subj, bin_key
    )

    # telemetry lane: exact counts of the masks this tick materialized
    # anyway — no extra gathers, no host sync (drained with the stats)
    # ground-truth false-positive splits of the suspicion lanes: the
    # kernel owns `alive`, so "suspected/downed a subject that is in
    # fact up" is exact — the lane the Lifeguard A/B is judged on
    ev_suspect_fp = _bsum(fail2 & (psubj < n) & alive[psafe_t])
    fired_safe = jnp.clip(fired_subj, 0, n - 1)
    ev_down_fp = _bsum(fire & (fired_subj < n) & alive[fired_safe])
    ev_delta = _event_vector(
        gossip_emitted=ev_emitted,
        gossip_lost=ev_lost,
        inbox_delivered=ev_delivered,
        inbox_overflowed=ev_emitted - ev_lost - ev_delivered,
        merge_won=_bsum(improved),
        feed_pulls=ev_feed,
        seed_pulls=ev_seed,
        suspect_raised=_bsum(fail2),
        down_declared=_bsum(fire),
        refuted=_bsum(refute),
        self_announced=ev_announce,
        suspicion_confirmations=ev_conf,
        suspect_fp=ev_suspect_fp,
        down_fp=ev_down_fp,
    )
    events = state.events + ev_delta

    # flight ring: this tick's delta vector + census, one
    # dynamic_update_slice at row t % ring_ticks (see ring note above)
    ring = state.ring
    if params.ring_ticks > 0:
        ring = _ring_write(
            ring, t, params.ring_ticks,
            jnp.concatenate(
                [
                    ev_delta,
                    _census_frame(n, alive, susp_subj, inc, in_subj, lhm),
                ]
            ),
        )

    return SwimState(
        t=t + 1,
        alive=alive,
        inc=inc,
        view=view,
        buf_subj=buf_subj,
        buf_key=buf_key,
        buf_sent=buf_sent,
        probe_phase=phase,
        probe_subj=psubj,
        probe_deadline=pdl,
        probe_ok=pok,
        susp_subj=susp_subj,
        susp_inc=susp_inc,
        susp_deadline=susp_deadline,
        partition=part,
        events=events,
        ring=ring,
        lhm=lhm,
        susp_conf=susp_conf,
        susp_start=susp_start,
        deg_loss=deg_loss,
        deg_lag=deg_lag,
    )


tick = functools.partial(jax.jit, static_argnames=("params",))(tick_impl)


def _tick_n_impl(
    state: SwimState, rng: jax.Array, params: SwimParams, k: int
) -> SwimState:
    def body(s, key):
        return tick_impl(s, key, params), None

    keys = jax.random.split(rng, k)
    out, _ = jax.lax.scan(body, state, keys)
    return out


tick_n = functools.partial(jax.jit, static_argnames=("params", "k"))(
    _tick_n_impl
)
"""Advance `k` protocol periods in ONE dispatch (lax.scan over tick).
Amortizes host→device round-trips — essential when the chip sits behind a
high-latency tunnel, and the pattern the sharded multi-chip path uses to
keep ICI busy between host syncs."""

tick_n_donated = functools.partial(
    jax.jit, static_argnames=("params", "k"), donate_argnums=(0,)
)(_tick_n_impl)
"""`tick_n` with the input state's buffers donated: the [N, N] view is
updated in place, halving peak HBM for the dominant array and raising the
largest single-chip member count (~40–60k on a 16 GB v5e chip). Callers
must not touch the input state afterwards — the simulation drivers
(ClusterSim, bench) always replace their reference."""


def set_alive(state: SwimState, member: int, value: bool) -> SwimState:
    """Churn injection: crash or (re)start a member process."""
    alive = state.alive.at[member].set(value)
    inc = jnp.where(
        value,
        jnp.minimum(state.inc.at[member].add(1), INC_CAP),
        state.inc,
    )  # restart = renewed identity (actor.rs:199 renew())
    return state._replace(alive=alive, inc=inc)


def set_partition(state: SwimState, groups) -> SwimState:
    """Partition injection: `groups` is a length-N int array; members in
    different groups cannot exchange ANY traffic (datagrams, gossip,
    feeds). Pass zeros to heal."""
    return state._replace(
        partition=jnp.asarray(groups, dtype=jnp.int32)
    )


def set_degraded(state, members, loss: float = 0.0, lag: int = 0):
    """Degraded-node fault injection (r9): mark `members` as flaky
    WITHOUT killing them — `loss` is their outbound datagram loss
    (gossip sends + every probe leg they originate), `lag` their local
    failure-detector processing lag in ticks (the Lifeguard CPU-
    starvation pathology: a lagged member's probes miss their window
    and it falsely accuses healthy peers — unless LHA-Probe stretches
    its window).  Pass loss=0, lag=0 to restore.  Works on both
    SwimState and PViewState (same lane names)."""
    idx = jnp.asarray(members, dtype=jnp.int32)
    return state._replace(
        deg_loss=state.deg_loss.at[idx].set(jnp.float32(loss)),
        deg_lag=state.deg_lag.at[idx].set(jnp.int32(lag)),
    )


@jax.jit
def _stats_impl(view, alive):
    """All three metrics from ONE block-row streaming pass (see
    _stats_sums) plus O(N) combination.  Diagonal (self) terms are
    subtracted in closed form: a live member's self entry is always an
    alive-precedence key."""
    n = view.shape[0]
    cov_num, det_num, fp_num, n_alive = _stats_sums(view, alive)
    n_alive_pairs = jnp.maximum(n_alive * (n_alive - 1.0), 1.0)
    n_dead_pairs = jnp.maximum(n_alive * (n - n_alive), 1.0)
    return jnp.stack(
        [cov_num / n_alive_pairs, det_num / n_dead_pairs, fp_num / n_alive_pairs]
    )


# [B, N] row blocks for the stats reductions.  The whole-view
# formulation materialized shared prec/known temporaries next to the
# int16 view — at n=80k that is multi-GB of HLO temps beside a 12.8 GB
# view, which OOMed a 16 GB v5e chip (PROFILE.md "80k dense OOM", r5).
# Blocking caps the temps at [B, N] regardless of n.  B=512: at n=80k
# the resident view leaves under 4 GB of headroom, and the B=2048
# blocks' f32 temps still exhausted it at runtime; 512 keeps the
# streamed temps a few hundred MB for no measurable CPU/TPU cost.
_STATS_BLOCK = 512


def _stats_sums(view, alive):
    """(cov_num, det_num, fp_num, n_alive): the three masked row-sums
    of the stats/coverage reductions, streamed over [B, N] row blocks
    in a single pass (lax.fori_loop + dynamic_slice).  The last block's
    start is clamped, so rows an earlier block already counted are
    masked out of its observer weights."""
    n = view.shape[0]
    b = min(n, _STATS_BLOCK)
    nblocks = (n + b - 1) // b
    af = alive.astype(jnp.float32)  # [N]

    def body(i, acc):
        cov, det, fp = acc
        start = jnp.minimum(i * b, n - b)
        rows = jax.lax.dynamic_slice(view, (start, 0), (b, n))
        prec = key_prec(rows)
        known = key_known(rows)
        row_ka = jnp.sum(
            jnp.where(known & (prec == PREC_ALIVE), af[None, :], 0.0), axis=1
        )
        row_td = jnp.sum(  # down-marked subjects that ARE dead
            jnp.where(known & (prec == PREC_DOWN), 1.0 - af[None, :], 0.0),
            axis=1,
        )
        row_fp = jnp.sum(  # suspected/downed subjects that ARE alive
            jnp.where(known & (prec >= PREC_SUSPECT), af[None, :], 0.0),
            axis=1,
        )
        rg = start + jnp.arange(b)
        w = af[rg] * (rg >= i * b)  # fresh live observers only
        return (
            cov + jnp.sum(row_ka * w),
            det + jnp.sum(row_td * w),
            fp + jnp.sum(row_fp * w),
        )

    z = jnp.float32(0.0)
    cov, det, fp = jax.lax.fori_loop(0, nblocks, body, (z, z, z))
    n_alive = jnp.sum(af)
    # minus the alive diagonal (self entries are alive-precedence);
    # dead/suspect diagonals contribute zero by the same argument
    return cov - n_alive, det, fp, n_alive


def _coverage_impl(view, alive):
    num, _, _, n_alive = _stats_sums(view, alive)
    return num / jnp.maximum(n_alive * (n_alive - 1.0), 1.0)


def _run_to_coverage_impl(state, rng, params, target, check_every, max_ticks):
    """Tick until live-member coverage reaches ``target``, ENTIRELY on
    device: a lax.while_loop of check_every-tick scans with the coverage
    reduction as its predicate.  No host round-trip happens between
    dispatch and convergence — on a tunneled chip every host-side stats
    check costs a full RTT (~85 ms measured), which at single-digit-ms
    ticks is the dominant cost of the host-driven loop.

    Returns (state, coverage); state.t carries the absolute tick at
    exit.  ``max_ticks`` is a hard budget: only whole check_every-tick
    chunks that FIT the budget run (the host loop clamps its final
    partial batch instead; the device loop cannot vary chunk size).
    cond is evaluated before body, so a caller passing a state with
    t + check_every > max_ticks compiles the whole program without
    running a tick — the bench warm-up uses this.
    """

    def cond(carry):
        st, _, cov = carry
        return (cov < target) & (st.t + check_every <= max_ticks)

    def body(carry):
        st, rng, _ = carry
        rng, key = jax.random.split(rng)
        st = _tick_n_impl(st, key, params, check_every)
        return st, rng, _coverage_impl(st.view, st.alive)

    state, _, cov = jax.lax.while_loop(
        cond, body, (state, rng, jnp.float32(-1.0))
    )
    return state, cov


run_to_coverage = functools.partial(
    jax.jit,
    static_argnames=("params", "target", "check_every", "max_ticks"),
    donate_argnums=(0,),
)(_run_to_coverage_impl)


def stats_and_events(state: SwimState):
    """(stats dict, [N_EVENTS] uint32 event totals, FlightDrain) in ONE
    device→host readback — the telemetry lane AND the flight ring drain
    beside the stats they already pay for, never as their own sync."""
    import numpy as np

    vals, ev, ring, t = jax.device_get(
        (
            _stats_impl(state.view, state.alive),
            state.events,
            state.ring,
            state.t,
        )
    )
    vals = np.asarray(vals)
    stats = {
        "coverage": float(vals[0]),  # live members known-alive by live peers
        "detected": float(vals[1]),  # dead members marked down
        "false_positive": float(vals[2]),  # live members suspected/downed
    }
    # uint32 view: totals wrap mod 2^32, drains subtract in uint32
    return (
        stats,
        np.asarray(ev).astype(np.uint32),
        FlightDrain(ring=np.asarray(ring), t=int(t)),
    )


def membership_stats(state: SwimState) -> dict:
    """Convergence metrics over live observers. Fetched as ONE stacked
    device→host transfer: per-scalar readbacks cost a full round-trip
    each, which dominates on tunneled TPU links."""
    return stats_and_events(state)[0]

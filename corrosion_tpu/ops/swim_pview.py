"""Bounded partial-view SWIM: the beyond-100k member representation.

The dense kernel (`ops/swim.py`) keeps a full [N, N] view — 37 GiB at
100k members and 4 TB at 1M: it hard-caps the simulation at ~100k on a
v5e-8 (VERDICT r2 missing #5). This kernel replaces the view with a
**bounded per-member hash-slot table** of `slots` entries, making state
O(N·K):

    slot_packed [N, K] int32   packed = key * P + (subj ^ mask),
                               P = next_pow2(n), 0 = empty (see _mask)

A subject `s` lives in slot `h(s) = (s * 2654435761 mod 2^32) mod K`
of each member's row. Because the packed word orders by (key, subj) and
SWIM keys are monotone in information (higher incarnation wins, then
down > suspect > alive — `ops/swim.py` key encoding), every merge — a
gossip delivery, a feed pull, an anti-entropy push — is ONE row-aligned
`max` scatter: hash collisions resolve as freshest-info-wins eviction,
which doubles as the partial view's retention policy. No per-row sorts,
no dedupe passes.

Own-entry pinning: a member's own record is force-written (`set`, not
`max`) into `h(self)` at the end of every tick, so a member can never be
evicted from its own table by a colliding squatter.

Packing bound: key*P + field < 2^31 requires key < 2^31/P (P =
next_pow2(n)), so refutation incarnations are clipped to `inc_cap(n)`
— AND to the dense kernel's INC_CAP = 8189, whichever is smaller, so
every key also fits the shared packed buffer merge's 15-bit domain
(inc_cap alone: 524 286 at n=1000, 2046 at n=262144, 510 at n=1M).
Either bound is far beyond realistic churn (SWIM incarnations in
practice stay < 100).

With `identity_hash=True` and `slots == n`, h is the identity, slot `s`
holds subject `s`, and this kernel is **bit-equivalent to the dense
kernel** — every random draw has the same shape and order, every merge
lands in the same cell, and the packed word's max coincides with the
dense key max (`tests/test_swim_pview.py` pins this). That makes the
partial view a strict generalization: the dense kernel is its K = n
special case.

Memory math for the scale ladder (int32, per chip on a v5e-8):
    n = 262 144, K = 1024:  slot table 1.07 GB → 134 MB/chip
    n = 1 048 576, K = 1024: slot table 4.3 GB → 537 MB/chip
    (+ gossip buffers [N, 3B], FSM arrays [N, ~10] — tens of MB)

Stability metric: with partial views, "everyone knows everyone" is
replaced by in-degree coverage — every live member should be known-alive
by ≈ (total live entries / n) observers. `membership_stats` reports
occupancy, mean/min in-degree, the fraction of live members at ≥ half
the expected in-degree ("pv_coverage"), and false positives.

Reference behavior being modeled: same as `ops/swim.py` (foca's SWIM
runtime, `klukai-agent/src/broadcast/mod.rs:121-386`), with the member
list bounded — the partial-view generalization follows the same design
space as SWIM-with-partial-views gossip systems (HyParView/Scamp
lineage), which is how membership scales past the full-view regime.

r6 optimization round (this kernel's first — the dense kernel had
three): `tick_mode="fused"` restructures the tick so every table
reader materializes against the tick-start table ahead of ONE in-place
merge scatter chain (kills the XLA whole-table copy that rejected
1M×2048 on a single chip — see `tick_impl`); `gossip_mode="shift"`
ports the dense kernel's sortless row-gather delivery; the
buf_key/buf_sent/susp_inc lanes store int16 at rest (LANE_DTYPE); and
`run_to_converged` is the device-resident convergence loop (the
four-term bar evaluated on device, zero host round-trips).  The
round-5 formulation stays selectable (`tick_mode="r5"`) as the
bit-parity reference.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops.swim import (
    INT32_MAX,
    N_EVENTS,
    N_FLIGHT_LANES,
    PREC_ALIVE,
    PREC_DOWN,
    PREC_SUSPECT,
    INC_CAP,
    _SENT_CLAMP,
    _EV_IDX,
    _bsum,
    _buffer_merge,
    _census_frame,
    _event_vector,
    _ring_write,
    _susp_shrink_table,
    FlightDrain,
    dispatch_inbox,
    set_degraded,  # noqa: F401 — duck-typed over both state types;
    # re-exported so drivers can call swim_pview.set_degraded
    finger_offsets,
    key_inc,
    key_known,
    key_prec,
    make_key,
)

_HASH_MULT = jnp.uint32(2654435761)  # Knuth multiplicative constant

SLOT_DTYPE = jnp.int32  # packed (key*P + subj^mask) words need 31 bits;
# int16 is NOT an option here (unlike the dense kernel's VIEW_DTYPE) —
# the pack bound key*P < 2^31 already consumes the whole word

LANE_DTYPE = jnp.int16  # at-rest dtype for the state lanes whose ranges
# provably fit 15 bits: buf_key (keys < 2^15 — the INC_CAP invariant every
# generation site enforces), buf_sent (clamped to _SENT_CLAMP = 2^15-1 by
# the shared buffer merge; init writes the clamp directly instead of the
# dense kernel's INT32_MAX sentinel — trajectory-identical, every consumer
# only tests `sent < max_transmissions` or ordering), and susp_inc
# (incarnations cap at INC_CAP = 8189).  The tick widens them to int32 on
# entry and narrows on exit; subjects (up to n = 2M) and the packed slot
# words (31 bits) stay int32.  The dense kernel applied the same lever to
# its dominant array (the int16 view); here the table cannot narrow, so
# the win is the carried gossip/suspicion lanes.


class PViewParams(NamedTuple):
    """Static parameters. FSM fields mirror `swim.SwimParams`; `slots`
    bounds the per-member table; `identity_hash` (requires slots == n)
    selects the dense-equivalent mode used by the parity tests."""

    n: int
    slots: int = 1024  # K — bounded view size per member
    fanout: int = 2
    piggyback: int = 8
    buffer_slots: int = 16
    incoming_slots: int = 16
    susp_slots: int = 4
    max_transmissions: int = 10
    direct_timeout: int = 1
    indirect_timeout: int = 1
    indirect_probes: int = 3
    suspicion_ticks: int = 6
    probe_candidates: int = 4
    # bounded-mode defaults tuned on the load-16 fairness sweep (see
    # tests/test_swim_pview.py::test_retention_fairness_under_load):
    # more anti-entropy + faster announce + longer tie epochs give the
    # designated winners time to install, lifting the in-degree floor
    antientropy: int = 4
    feed_entries: int = 25
    feeds_per_tick: int = 4
    announce_period: int = 4
    tie_epoch: int = 48  # ticks between tie-break re-maskings (see _mask)
    loss: float = 0.0
    identity_hash: bool = False
    inbox_impl: str = "gsort"  # see swim.SwimParams.inbox_impl
    # feed merge scheduling: "seq" (each feed's partner pick reads the
    # already-merged table — the dense kernel's semantics, required for
    # the identity-hash parity pin) or "batched" (all feeds pick from
    # the pre-feed table and merge in ONE scatter-max — 1/nfeeds the
    # scatter launches; the CPU tick is feed-scatter bound, PROFILE.md
    # r4 pview phase table)
    feed_mode: str = "seq"
    # tick structure: "fused" (default — the r6 restructure: every
    # pre-merge reader of the slot table materializes against the
    # TICK-START table behind an optimization barrier, then ONE merge
    # scatter chain updates it in place; this is what eliminates the
    # XLA-inserted whole-table copy that rejected the 1M×2048 rung at
    # compile time, PROFILE.md "Round 5: 1M on chip") or "r5" (the
    # round-5 formulation: feeds merge sequentially and later phases
    # read the already-merged table — required for the identity-hash
    # bit-parity pin against the dense kernel, and the reference the
    # fused tick's convergence is pinned against).  In "fused" mode
    # feed partner picks read the pre-feed table (the "batched" feed
    # semantics — one merge staler, convergence-equivalent); feed_mode
    # is ignored.
    tick_mode: str = "fused"
    # gossip target selection, mirroring swim.SwimParams.gossip_mode:
    # "shift" (default — the dense kernel's r5-decided lever): per-
    # (tick, fanout-slot) random GLOBAL offsets make delivery an exact
    # row gather of the send planes — no destination sort at all.
    # "pick": per-member known-alive picks + the grouped-sort inbox
    # build (the r5 path; the identity-hash parity pin uses it because
    # the dense parity contract is pick-shaped).
    gossip_mode: str = "shift"
    ring_ticks: int = 128  # flight-recorder depth (see ops/swim.py ring
    # note — per-tick event-delta + census frames in the scan carry;
    # 0 disables)
    # ---- Lifeguard (r9) — same contract as swim.SwimParams ---------------
    lhm_max: int = 0  # 0 disables all three mechanisms (compat default:
    # bit-equal to the pre-r9 tick); >0 = LHM score ceiling
    lhm_decay_ticks: int = 8
    susp_ceiling: int = 3
    susp_k: int = 3


def _keycap(n: int) -> int:
    """Key-field capacity of the packed word: keys occupy the LOW part."""
    return 2**31 // _pow2(n)


def inc_cap(n: int) -> int:
    """Largest incarnation representable in the packed word for n."""
    return (_keycap(n) - 7) // 4


def _hash(params: PViewParams, subj: jax.Array) -> jax.Array:
    if params.identity_hash:
        if params.slots < params.n:
            raise ValueError("identity_hash requires slots >= n")
        return subj
    mixed = subj.astype(jnp.uint32) * _HASH_MULT
    return (mixed % jnp.uint32(params.slots)).astype(jnp.int32)


def _pow2(n: int) -> int:
    """Smallest power of two >= n: the packed tie-field domain."""
    return 1 << (n - 1).bit_length()


def _fmix32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: avalanche a uint32 (bijective)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _mask(params: PViewParams, rows, t) -> jax.Array:
    """Per-(observer, epoch) XOR mask for the packed word's tie-break.

    Within one key level, `max` on the packed word breaks ties by the
    STORED subject field; that field is `subj ^ mask(row, epoch)` over
    the power-of-two domain `_pow2(n)`. Why this exact construction:

    - raw subject ids: eviction deterministic by id — high ids squat
      every saturated slot, low ids go globally extinct.
    - a global time-varying shift: every observer retains the SAME
      winner subset — only ~K subjects well-known at any instant.
    - a per-row ADDITIVE rotation `(subj + r(i,e)) % n`: decorrelates
      observers, but addition only moves the wrap point — the circular
      ORDER of `{subj}` never changes, so a subject's win share stays
      pinned to its fixed gap in the bucket ordering and in-degree
      plateaus unevenly (measured: pv_coverage stuck ~0.97).
    - XOR by an avalanched per-(row, epoch) mask is a self-inverse
      bijection on [0, 2^k) that genuinely RE-ORDERS the domain every
      epoch: win shares re-roll per epoch, so time-averaged retention
      is uniform across subjects, while within an epoch every (row,
      bucket) still has one stable designated winner that feed/announce
      traffic has time to install (in-degree concentrates near
      n/bucket-load).

    The mask advances once per `tie_epoch` ticks. Rows' tables at rest
    are encoded at mask(row, state.t); `tick_impl` re-encodes to t+1 in
    one elementwise pass, and feed pulls re-encode partner rows into the
    receiver's mask."""
    rows = jnp.asarray(rows, dtype=jnp.int32)
    epoch = (jnp.int32(t) // jnp.int32(max(1, params.tie_epoch))).astype(
        jnp.uint32
    )
    mixed = _fmix32(
        rows.astype(jnp.uint32) * jnp.uint32(2246822519)
        ^ epoch * jnp.uint32(2654435761)
    )
    return (mixed & jnp.uint32(_pow2(params.n) - 1)).astype(jnp.int32)


def _pack(params: PViewParams, subj: jax.Array, key: jax.Array, rows, t) -> jax.Array:
    """packed = (subj ^ mask) * KEYCAP + key.

    Field order matters: the masked SUBJECT field is the HIGH part.
    Same-subject entries always share a cell (same hash), so within a
    cell the max still orders by key — the protocol merge. But when two
    DIFFERENT subjects contend for a slot, the comparison is decided by
    the masked fields alone, never by key: eviction fairness is
    incarnation-independent. (With key as the high part, a member that
    refuted to a high incarnation would permanently evict low-inc
    bucket-mates everywhere — measured post-heal: one member pinned at
    in-degree 0.)"""
    kc = _keycap(params.n)
    return (subj ^ _mask(params, rows, t)) * kc + key


def _unpack(params: PViewParams, packed: jax.Array, rows, t):
    kc = _keycap(params.n)
    subj = (packed // kc) ^ _mask(params, rows, t)
    return subj, packed % kc  # (subj, key)


class PViewState(NamedTuple):
    t: jax.Array  # () int32
    alive: jax.Array  # [N] bool — ground truth process liveness
    inc: jax.Array  # [N] int32 — own incarnation
    slot_packed: jax.Array  # [N, K] int32 — key*P + (subj^mask), 0 = empty
    buf_subj: jax.Array  # [N, B] int32 — gossip buffer (N = empty)
    buf_key: jax.Array  # [N, B] LANE_DTYPE (int16) — keys < 2^15
    buf_sent: jax.Array  # [N, B] LANE_DTYPE (int16) — empty slots hold
    # _SENT_CLAMP (the post-merge normalization of the dense kernel's
    # INT32_MAX sentinel; subj==n is the real empty marker)
    probe_phase: jax.Array  # [N] int32
    probe_subj: jax.Array  # [N] int32
    probe_deadline: jax.Array  # [N] int32
    probe_ok: jax.Array  # [N] bool
    susp_subj: jax.Array  # [N, S] int32 (N = empty)
    susp_inc: jax.Array  # [N, S] LANE_DTYPE (int16) — capped at INC_CAP
    susp_deadline: jax.Array  # [N, S] int32
    partition: jax.Array  # [N] int32 — network partition group (see
    # swim.SwimState.partition; same split-brain semantics)
    events: jax.Array  # [N_EVENTS] int32 — cumulative on-device event
    # telemetry, KERNEL_EVENTS order (see swim.py lane note; replicated
    # under sharding, wrap-mod-2^32 totals drained as uint32 deltas)
    ring: jax.Array  # [ring_ticks, N_FLIGHT_LANES] int32 — the flight
    # recorder ring (see swim.py ring note; replicated like `events`)
    # ---- Lifeguard lanes (r9) — see swim.SwimState for semantics ---------
    lhm: jax.Array  # [N] int32 — Local Health Multiplier score
    susp_conf: jax.Array  # [N, S] int32 — per-open-timer confirmations
    susp_start: jax.Array  # [N, S] int32 — timer registration tick
    deg_loss: jax.Array  # [N] float32 — injected outbound datagram loss
    deg_lag: jax.Array  # [N] int32 — injected local processing lag


def init_state(
    params: PViewParams,
    rng: jax.Array,
    seeds_per_member: int = 3,
    seed_mode: str = "ring",
) -> PViewState:
    """Freshly booted cluster: every member knows itself plus bootstrap
    seeds — `seed_mode="ring"`: the next `seeds_per_member` neighbours;
    `"fingers"`: Chord-style power-of-two offsets (`swim.finger_offsets`,
    same expander bootstrap rationale as `swim.init_state`: long-range
    feed partners from tick 0).

    Construction is scatter-free and jitted as ONE program.  History of
    why (r5 chip window): the original eager scatter-max form compiled
    each op separately — ~99 s of init at n=100k through the tunnel and
    an UNAVAILABLE device/compile fault at n ≥ 262k; jitting that same
    scatter chain whole then HUNG outright at n=100k (5400 s, zero
    output).  The blocked one-hot construction below has fixed [B,F,K]
    shapes, no scatter, and is bit-equal to the scatter-max semantics
    (same-slot contenders resolved by max over packed values)."""
    return _init_impl(params, seeds_per_member, seed_mode)


@functools.partial(
    jax.jit, static_argnames=("params", "seeds_per_member", "seed_mode")
)
def _init_impl(
    params: PViewParams, seeds_per_member: int, seed_mode: str
) -> PViewState:
    n, k, b, s = params.n, params.slots, params.buffer_slots, params.susp_slots
    idx = jnp.arange(n, dtype=jnp.int32)
    alive_key = make_key(0, PREC_ALIVE)
    if seed_mode == "ring":
        offs = jnp.arange(1, seeds_per_member + 1, dtype=jnp.int32)
    elif seed_mode == "fingers":
        offs = finger_offsets(n)
    else:
        raise ValueError(f"unknown seed_mode {seed_mode!r}")
    # self + seeds, one [B, F, K] one-hot max per row block: for each
    # observer row the F+1 seed entries land in their hashed slots via
    # comparison against the slot index, max-reduced over seeds —
    # identical cell contents to a scatter-max, with bounded temps
    offs_all = jnp.concatenate([jnp.zeros(1, jnp.int32), offs])
    bb = min(n, 1024)
    nblocks = (n + bb - 1) // bb
    slot_ids = jnp.arange(k, dtype=jnp.int32)

    def init_block(i, packed):
        start = jnp.minimum(i * bb, n - bb)
        rows = start + jnp.arange(bb, dtype=jnp.int32)  # [B]
        peers = (rows[:, None] + offs_all[None, :]) % n  # [B, F+1]
        slot = _hash(params, peers)  # [B, F+1]
        val = _pack(params, peers, alive_key, rows[:, None], 0)
        block = jnp.max(
            jnp.where(
                slot[:, :, None] == slot_ids[None, None, :],
                val[:, :, None],
                0,
            ),
            axis=1,
        ).astype(SLOT_DTYPE)  # [B, K]
        # clamped last block recomputes identical rows — no mask needed
        return jax.lax.dynamic_update_slice(packed, block, (start, 0))

    packed = jax.lax.fori_loop(
        0, nblocks, init_block, jnp.zeros((n, k), dtype=SLOT_DTYPE)
    )

    buf_subj = jnp.full((n, b), n, dtype=jnp.int32)
    buf_key = jnp.zeros((n, b), dtype=LANE_DTYPE)
    # _SENT_CLAMP, not INT32_MAX: the value every merge normalizes the
    # dense sentinel to anyway (trajectory-identical, fits LANE_DTYPE)
    buf_sent = jnp.full((n, b), _SENT_CLAMP, dtype=LANE_DTYPE)
    buf_subj = buf_subj.at[:, 0].set(idx)
    buf_key = buf_key.at[:, 0].set(alive_key)
    buf_sent = buf_sent.at[:, 0].set(0)

    return PViewState(
        t=jnp.int32(0),
        alive=jnp.ones(n, dtype=bool),
        inc=jnp.zeros(n, dtype=jnp.int32),
        slot_packed=packed,
        buf_subj=buf_subj,
        buf_key=buf_key,
        buf_sent=buf_sent,
        probe_phase=jnp.zeros(n, dtype=jnp.int32),
        probe_subj=jnp.full(n, n, dtype=jnp.int32),
        probe_deadline=jnp.zeros(n, dtype=jnp.int32),
        probe_ok=jnp.zeros(n, dtype=bool),
        susp_subj=jnp.full((n, s), n, dtype=jnp.int32),
        susp_inc=jnp.zeros((n, s), dtype=LANE_DTYPE),
        susp_deadline=jnp.zeros((n, s), dtype=jnp.int32),
        partition=jnp.zeros(n, dtype=jnp.int32),
        events=jnp.zeros(N_EVENTS, dtype=jnp.int32),
        ring=jnp.zeros(
            (params.ring_ticks, N_FLIGHT_LANES), dtype=jnp.int32
        ),
        lhm=jnp.zeros(n, dtype=jnp.int32),
        susp_conf=jnp.zeros((n, s), dtype=jnp.int32),
        susp_start=jnp.zeros((n, s), dtype=jnp.int32),
        deg_loss=jnp.zeros(n, dtype=jnp.float32),
        deg_lag=jnp.zeros(n, dtype=jnp.int32),
    )


def _pick_known_alive(
    params: PViewParams, packed, self_idx, rng, tries: int, t=0
):
    """Per member, an alive subject sampled from its own slot table
    (n if none found). Random slot columns relative to self (identical
    draw shapes to the dense kernel's `_pick_known_alive`, so the
    identity-hash mode consumes the same rng stream), plus two small
    ring-offset fallback columns for freshly-booted tables."""
    n, k = params.n, params.slots
    offs = jax.random.randint(rng, (packed.shape[0], tries), 1, k)
    ring = jax.random.randint(rng, (packed.shape[0], 2), 1, 4)
    cols = (self_idx[:, None] + jnp.concatenate([offs, ring], axis=1)) % k
    cand = jnp.take_along_axis(packed, cols, axis=1)
    subj, key = _unpack(params, cand, self_idx[:, None], t)
    ok = key_known(key) & (key_prec(key) == PREC_ALIVE) & (subj != self_idx[:, None])
    first = jnp.argmax(ok, axis=1)
    found = jnp.any(ok, axis=1)
    pick = jnp.take_along_axis(subj, first[:, None], axis=1)[:, 0]
    return jnp.where(found, pick, n)


def _lookup(params: PViewParams, packed, subjs, t=0):
    """Believed key for `subjs` ([N] or [N, S]) per row; 0 if absent."""
    squeeze = subjs.ndim == 1
    if squeeze:
        subjs = subjs[:, None]
    safe = jnp.clip(subjs, 0, params.n - 1)
    cols = _hash(params, safe)
    rows = jnp.arange(packed.shape[0], dtype=jnp.int32)[:, None]
    cur = jnp.take_along_axis(packed, cols, axis=1)
    cs, ck = _unpack(params, cur, rows, t)
    out = jnp.where((cs == safe) & (subjs < params.n), ck, 0)
    return out[:, 0] if squeeze else out


def tick_impl(
    state: PViewState, rng: jax.Array, params: PViewParams
) -> PViewState:
    """One SWIM protocol period for every member, phase-for-phase the
    dense kernel (`swim.tick_impl`) with the view ops swapped for
    hash-slot equivalents.

    Two tick structures (``params.tick_mode``):

    - ``"r5"``: the round-5 formulation — feeds merge into the table
      sequentially, and every later phase (refutation diag, relay prev
      gather) reads the already-merged table.  In ``gossip_mode="pick"``
      its random draws match the dense kernel's shapes and order exactly
      (the identity-hash parity contract).
    - ``"fused"`` (default): every reader of the slot table — probe
      lookups, target picks, anti-entropy lanes, ALL feed-window pulls,
      the refutation diag and the relay's prev gather — reads the
      TICK-START table; an optimization barrier then pins those reads
      ahead of ONE merge scatter chain (feeds + inbox + own updates in a
      single scatter-max, then the own-entry pin, then the tie-epoch
      re-encode).  With no reader left that could observe the table
      mid-mutation, XLA's copy insertion keeps the donated table fully
      in place — this removes the whole-table HLO-temp copy that
      rejected the 1M×2048 single-chip rung at compile time (PROFILE.md
      "Round 5: 1M on chip").  Semantics vs "r5": feed partner picks and
      the refutation diag are one merge staler (the "batched" feed
      trade, convergence-pinned by tests/test_swim_pview.py).
    """
    if params.tick_mode not in ("fused", "r5"):
        raise ValueError(f"unknown tick_mode: {params.tick_mode!r}")
    if params.gossip_mode not in ("shift", "pick"):
        raise ValueError(f"unknown gossip_mode: {params.gossip_mode!r}")
    fused = params.tick_mode == "fused"
    n, k = params.n, params.slots
    idx = jnp.arange(n, dtype=jnp.int32)
    t = state.t
    r_probe, r_ack, r_helpers, r_gossip, r_loss = jax.random.split(rng, 5)

    packed = state.slot_packed
    inc = state.inc
    alive = state.alive
    part = state.partition
    # narrowed at-rest lanes widen to int32 for the tick's arithmetic
    buf_subj = state.buf_subj
    buf_key = state.buf_key.astype(jnp.int32)
    buf_sent = state.buf_sent.astype(jnp.int32)
    susp_subj = state.susp_subj
    susp_inc = state.susp_inc.astype(jnp.int32)
    susp_deadline = state.susp_deadline
    susp_conf = state.susp_conf
    susp_start = state.susp_start
    lhm = state.lhm
    deg_loss = state.deg_loss
    deg_lag = state.deg_lag

    # Lifeguard (r9): same static switch + semantics as swim.tick_impl
    # (see the dense kernel's comments; this kernel mirrors it phase for
    # phase so the identity-hash parity holds with lifeguard on too)
    lifeguard = params.lhm_max > 0
    mult = 1 + jnp.clip(lhm, 0, params.lhm_max) if lifeguard else 1
    open_ticks = params.suspicion_ticks * (
        params.susp_ceiling if lifeguard else 1
    )

    # suspect / down / refute / periodic self-announce
    own_upd_subj = jnp.full((n, 4), n, dtype=jnp.int32)
    own_upd_key = jnp.zeros((n, 4), dtype=jnp.int32)

    # ---- 1. probe FSM ----------------------------------------------------
    phase, psubj, pdl, pok = (
        state.probe_phase,
        state.probe_subj,
        state.probe_deadline,
        state.probe_ok,
    )

    expire2 = (phase == 2) & (t >= pdl) & alive
    fail2 = expire2 & ~pok
    tgt_key = _lookup(params, packed, psubj, t)
    binc = jnp.maximum(key_inc(tgt_key), 0)
    susp_key = make_key(binc, PREC_SUSPECT)
    own_upd_subj = own_upd_subj.at[:, 0].set(jnp.where(fail2, psubj, n))
    own_upd_key = own_upd_key.at[:, 0].set(jnp.where(fail2, susp_key, 0))
    slot_score = jnp.where(susp_subj == n, INT32_MAX, -susp_deadline)
    free_slot = jnp.argmax(slot_score, axis=1)
    old_subj = susp_subj[idx, free_slot]
    old_inc = susp_inc[idx, free_slot]
    old_dl = susp_deadline[idx, free_slot]
    susp_subj = susp_subj.at[idx, free_slot].set(jnp.where(fail2, psubj, old_subj))
    susp_inc = susp_inc.at[idx, free_slot].set(jnp.where(fail2, binc, old_inc))
    susp_deadline = susp_deadline.at[idx, free_slot].set(
        jnp.where(fail2, t + open_ticks, old_dl)
    )
    old_conf = susp_conf[idx, free_slot]
    old_start = susp_start[idx, free_slot]
    susp_conf = susp_conf.at[idx, free_slot].set(
        jnp.where(fail2, 0, old_conf)
    )
    susp_start = susp_start.at[idx, free_slot].set(
        jnp.where(fail2, t, old_start)
    )
    phase = jnp.where(expire2, 0, phase)
    if lifeguard:
        # LHA-Probe period stretch (see swim.tick_impl 1a)
        pdl = jnp.where(expire2, t + mult - 1, pdl)

    expire1 = (phase == 1) & (t >= pdl) & alive
    fail1 = expire1 & ~pok
    helpers = jax.random.randint(r_helpers, (n, params.indirect_probes), 0, n)
    psafe_t = jnp.clip(psubj, 0, n - 1)
    tgt_alive = alive[psafe_t] & (psubj < n)
    # raw leg draws + per-pair loss/lag model — see swim.tick_impl 1b
    leg_u = jax.random.uniform(r_ack, (n, params.indirect_probes + 1))
    path_loss = jnp.maximum(
        params.loss,
        jnp.maximum(
            jnp.maximum(deg_loss[:, None], deg_loss[helpers]),
            deg_loss[psafe_t][:, None],
        ),
    )
    ind_win = params.indirect_timeout * mult
    ind_window_ok = ind_win >= params.indirect_timeout + deg_lag
    helper_reach = (part[helpers] == part[:, None]) & (
        part[helpers] == part[psafe_t][:, None]
    )
    helper_ok = (
        alive[helpers] & (leg_u[:, 1:] >= path_loss)
        & tgt_alive[:, None] & helper_reach
    )
    ind_ok = jnp.any(helper_ok, axis=1) & ind_window_ok
    phase = jnp.where(fail1, 2, jnp.where(expire1, 0, phase))
    pok = jnp.where(fail1, ind_ok, pok)
    pdl = jnp.where(fail1, t + ind_win, pdl)
    if lifeguard:
        pdl = jnp.where(expire1 & ~fail1, t + mult - 1, pdl)

    start = (phase == 0) & alive
    if lifeguard:
        start = start & (t >= pdl)
    target = _pick_known_alive(
        params, packed, idx, r_probe, params.probe_candidates, t
    )
    will = start & (target < n)
    tsafe = jnp.clip(target, 0, n - 1)
    d_loss = jnp.maximum(
        params.loss, jnp.maximum(deg_loss, deg_loss[tsafe])
    )
    d_win = params.direct_timeout * mult
    direct_ok = (
        alive[tsafe] & (target < n) & (leg_u[:, 0] >= d_loss)
        & (part[tsafe] == part)
        & (d_win >= params.direct_timeout + deg_lag)
    )
    phase = jnp.where(will, 1, phase)
    psubj = jnp.where(will, target, psubj)
    pdl = jnp.where(will, t + d_win, pdl)
    pok = jnp.where(will, direct_ok, pok)

    # ---- 2. suspicion timers ---------------------------------------------
    sdl_hit = (susp_subj < n) & (t >= susp_deadline) & alive[:, None]
    cur = _lookup(params, packed, susp_subj, t)
    still = sdl_hit & (key_prec(cur) == PREC_SUSPECT) & (key_inc(cur) == susp_inc)
    down_key = make_key(susp_inc, PREC_DOWN)
    fire_col = jnp.argmax(still, axis=1)
    fire = jnp.any(still, axis=1)
    fired_subj = jnp.take_along_axis(susp_subj, fire_col[:, None], axis=1)[:, 0]
    fired_key = jnp.take_along_axis(down_key, fire_col[:, None], axis=1)[:, 0]
    own_upd_subj = own_upd_subj.at[:, 1].set(jnp.where(fire, fired_subj, n))
    own_upd_key = own_upd_key.at[:, 1].set(jnp.where(fire, fired_key, 0))
    clear = (jnp.arange(params.susp_slots)[None, :] == fire_col[:, None]) & fire[:, None]
    clear = clear | (sdl_hit & ~still)
    susp_subj = jnp.where(clear, n, susp_subj)
    susp_conf = jnp.where(clear, 0, susp_conf)

    # ---- 3. gossip send --------------------------------------------------
    m, f = params.piggyback, params.fanout
    if params.gossip_mode == "shift":
        # per-(tick, slot) random global offsets (the dense kernel's r5
        # default): member i sends slot j's packet to (i + off_j) mod n,
        # so delivery in step 4 is an exact row gather — no target-pick
        # table scans, no destination sort.  Same fold_in constant as
        # the dense kernel so the two shift modes draw identically.
        shift_off = jax.random.randint(
            jax.random.fold_in(r_gossip, 65537), (f,), 1, n,
            dtype=jnp.int32,
        )
        tg = (idx[:, None] + shift_off[None, :]) % n  # [N, f]
    else:
        tg = jnp.stack(
            [
                _pick_known_alive(
                    params, packed, idx, jax.random.fold_in(r_gossip, j), 2, t
                )
                for j in range(f)
            ],
            axis=1,
        )
    send_subj = buf_subj[:, :m]
    send_key = buf_key[:, :m]
    sendable = (send_subj < n) & (buf_sent[:, :m] < params.max_transmissions)
    valid_tgt = tg < n
    nt = jnp.sum(valid_tgt & alive[:, None], axis=1)
    buf_sent = buf_sent.at[:, :m].set(
        jnp.where(
            sendable & (nt[:, None] > 0),
            buf_sent[:, :m] + nt[:, None],
            buf_sent[:, :m],
        )
    )

    ae = params.antientropy
    if ae > 0:
        r_ae = jax.random.fold_in(r_gossip, 7919)
        ae_cols = jax.random.randint(r_ae, (n, ae), 0, k).astype(jnp.int32)
        ae_packed = jnp.take_along_axis(packed, ae_cols, axis=1)
        ae_subj, ae_key = _unpack(params, ae_packed, idx[:, None], t)
        send_subj = jnp.concatenate([send_subj, ae_subj], axis=1)
        send_key = jnp.concatenate([send_key, ae_key], axis=1)
        sendable = jnp.concatenate([sendable, ae_key > 0], axis=1)
        m = m + ae

    tg_safe = jnp.clip(tg, 0, n - 1)
    msg_ok = (
        sendable[:, None, :]
        & valid_tgt[:, :, None]
        & alive[:, None, None]
        & alive[tg_safe][:, :, None]
        & (part[tg_safe] == part[:, None])[:, :, None]
    )
    drop = (
        jax.random.uniform(r_loss, msg_ok.shape)
        < jnp.maximum(params.loss, deg_loss)[:, None, None]
    )
    # telemetry (see swim.py): emitted = deliverable sends, lost = the
    # loss-injection slice; both from masks already materialized
    ev_emitted = _bsum(msg_ok)
    ev_lost = _bsum(msg_ok & drop)
    msg_ok = msg_ok & ~drop

    # ---- 4. delivery: bounded per-member inboxes -------------------------
    subj_gm = jnp.broadcast_to(send_subj[:, None, :], msg_ok.shape)
    key_gm = jnp.broadcast_to(send_key[:, None, :], msg_ok.shape)
    if params.gossip_mode == "shift":
        # receiver r's slot-j packet comes from sender (r - off_j) mod n:
        # an exact [N, f] row gather of the masked send planes (see
        # swim.tick_impl step 4 — identical contract incl. the bounded-
        # mailbox compaction when f*m exceeds the inbox cap)
        src = (idx[:, None] - shift_off[None, :]) % n  # [N, f]
        sub_m = jnp.where(msg_ok, subj_gm, n)
        key_m = jnp.where(msg_ok, key_gm, 0)
        jj = jnp.arange(f, dtype=jnp.int32)[None, :]
        in_subj = sub_m[src, jj].reshape(n, f * m)
        in_key = key_m[src, jj].reshape(n, f * m)
        if f * m > params.incoming_slots:
            order = jnp.argsort(in_subj == n, axis=1, stable=True)
            take = order[:, : params.incoming_slots]
            in_subj = jnp.take_along_axis(in_subj, take, axis=1)
            in_key = jnp.take_along_axis(in_key, take, axis=1)
    else:
        in_subj, in_key = dispatch_inbox(
            params.inbox_impl,
            n,
            params.incoming_slots,
            tg_safe.reshape(-1),
            subj_gm.reshape(-1, m),
            key_gm.reshape(-1, m),
            msg_ok.reshape(-1, m),
        )
    ev_delivered = _bsum(in_subj < n)

    # ---- 4b. announce/feed exchange over SLOT space ----------------------
    # identical window/rng structure to the dense kernel, but the window
    # slides over the K slot columns; pulled entries re-hash into the
    # receiver's row with one row-aligned max scatter
    fe = min(params.feed_entries, k)
    nfeeds = params.feeds_per_tick
    steps_per_sweep = -(-k // fe) if fe > 0 else 1
    spacing = max(1, steps_per_sweep // nfeeds) if nfeeds > 0 else 1

    def _feed_pull(pk, fk):
        """One feed's gathered window ([N, fe] packed) + partner rows
        + successful-exchange count (telemetry)."""
        r_feed = jax.random.fold_in(r_gossip, 104729 + fk)
        partner = _pick_known_alive(params, pk, idx, r_feed, 2, t)
        psafe = jnp.clip(partner, 0, n - 1)
        has_partner = (
            (partner < n) & alive & alive[psafe] & (part[psafe] == part)
        )
        j = (t + fk * spacing) % steps_per_sweep
        w = jnp.minimum(j * fe, k - fe)
        vw = jax.lax.dynamic_slice(pk, (jnp.int32(0), w), (n, fe))
        pulled = jnp.take(vw, psafe, axis=0)
        pulled = jnp.where(has_partner[:, None], pulled, 0)
        return pulled, psafe, _bsum(has_partner)

    def _feed_updates(pulled, prows):
        """(repacked values, hash columns) for pulled windows — the
        scatter-max operands, re-encoded into the receiver's rotation."""
        p_subj, p_key = _unpack(params, pulled, prows, t)
        repacked = jnp.where(
            pulled > 0,
            _pack(params, p_subj, p_key, idx[:, None], t),
            0,
        )
        return repacked, _hash(params, p_subj)

    def _feed_merge(pk, pulled, prows):
        repacked, cols = _feed_updates(pulled, prows)
        return pk.at[idx[:, None], cols].max(repacked)

    def _seed_pull(pk):
        """Bootstrap-seed window pull (see swim.py 4c: the reference's
        always-running bootstrap announcer; without it a healed
        partition never re-merges)."""
        seed_off = 1 + (t // jnp.int32(max(1, params.announce_period))) % 3
        sp = (idx + seed_off) % n
        seed_ok = alive & alive[sp] & (part[sp] == part)
        j = t % steps_per_sweep
        w = jnp.minimum(j * fe, k - fe)
        vw = jax.lax.dynamic_slice(pk, (jnp.int32(0), w), (n, fe))
        pulled = jnp.take(vw, sp, axis=0)
        return jnp.where(seed_ok[:, None], pulled, 0), sp, _bsum(seed_ok)

    ev_feed = jnp.int32(0)
    ev_seed = jnp.int32(0)
    feed_vals = feed_cols = None
    if fused:
        # every pull reads the TICK-START table ("batched" feed
        # semantics); the windows merge later as part of the single
        # post-barrier scatter chain (step 6)
        pulls, prows = [], []
        if fe > 0 and nfeeds > 0:
            for fk in range(nfeeds):
                pulled, psafe, np_f = _feed_pull(packed, fk)
                ev_feed = ev_feed + np_f
                pulls.append(pulled)
                prows.append(jnp.broadcast_to(psafe[:, None], (n, fe)))
        if fe > 0:
            pulled, sp, ev_seed = _seed_pull(packed)
            pulls.append(pulled)
            prows.append(jnp.broadcast_to(sp[:, None], (n, fe)))
        if pulls:
            feed_vals, feed_cols = _feed_updates(
                jnp.concatenate(pulls, axis=1),
                jnp.concatenate(prows, axis=1),
            )
    elif fe > 0:
        if nfeeds > 0:
            if params.feed_mode not in ("seq", "batched"):
                raise ValueError(f"unknown feed_mode: {params.feed_mode!r}")
            if params.feed_mode == "batched":
                # all picks read the PRE-feed table; the nfeeds windows
                # merge in a single [N, nfeeds*fe] scatter-max
                # (intra-tick picks are one merge staler — convergence
                # pinned by test_swim_pview.py)
                pulls, rows = [], []
                for fk in range(nfeeds):
                    pulled, psafe, np_f = _feed_pull(packed, fk)
                    ev_feed = ev_feed + np_f
                    pulls.append(pulled)
                    rows.append(
                        jnp.broadcast_to(psafe[:, None], (n, fe))
                    )
                packed = _feed_merge(
                    packed,
                    jnp.concatenate(pulls, axis=1),
                    jnp.concatenate(rows, axis=1),
                )
            else:

                def one_feed(fk, pk, n_pulls):
                    pulled, psafe, np_f = _feed_pull(pk, fk)
                    return _feed_merge(pk, pulled, psafe[:, None]), (
                        n_pulls + np_f
                    )

                # ALWAYS unrolled (nfeeds is static, default 4-8): a
                # fori_loop here is an inner while carrying the [N, K]
                # table inside tick_n's scan, and XLA's copy insertion
                # answers that nesting by double-buffering the carried
                # table (PROFILE.md "80k dense OOM" documents the dense
                # sibling) — at K=2048 that rejects the 1M-member table
                # (2 x 8.6 GiB) on a 16 GiB chip. A rolled fallback for
                # large nfeeds would be a silent memory cliff one notch
                # above the scripts' default of 8; unrolling instead
                # costs compile time linear in nfeeds, which is the
                # safer trade at any configuration this kernel
                # realistically sees.
                for _fk in range(nfeeds):
                    packed, ev_feed = one_feed(_fk, packed, ev_feed)

        # ---- 4c. bootstrap-seed exchange ---------------------------------
        pulled, sp, ev_seed = _seed_pull(packed)
        packed = _feed_merge(packed, pulled, sp[:, None])

    # ---- 5. refutation (inbox + own slot) --------------------------------
    about_self = (in_subj == idx[:, None]) & (key_prec(in_key) >= PREC_SUSPECT)
    worst_msg = jnp.max(jnp.where(about_self, key_inc(in_key), -1), axis=1)
    selfk = _lookup(params, packed, idx, t)
    worst_diag = jnp.where(key_prec(selfk) >= PREC_SUSPECT, key_inc(selfk), -1)
    worst = jnp.maximum(worst_msg, worst_diag)
    if lifeguard:
        # LHA-Refute buddy system (see swim.tick_impl phase 5); in
        # fused mode the suspect-entry lookup reads the tick-start
        # table like every other reader — one merge staler than r5,
        # the same staleness class as the refutation diag above
        tkey = _lookup(params, packed, target, t)
        tell = (
            will & alive & alive[tsafe] & (part[tsafe] == part)
            & (leg_u[:, 0] >= d_loss)
            & (key_prec(tkey) == PREC_SUSPECT)
        )
        buddy = (
            jnp.full((n,), -1, dtype=jnp.int32)
            .at[jnp.where(tell, tsafe, n)]
            .max(
                jnp.where(tell, jnp.maximum(key_inc(tkey), 0), -1),
                mode="drop",
            )
        )
        worst = jnp.maximum(worst, buddy)
    refute = alive & (worst >= 0) & (worst >= inc)
    # both bounds bind: the packed-slot word needs key*P < 2^31
    # (inc_cap(n)), and the shared packed buffer merge needs keys < 2^15
    # (INC_CAP, the dense kernel's generation cap) — see _buffer_merge
    cap = min(inc_cap(n), INC_CAP)
    inc = jnp.where(refute, jnp.minimum(worst + 1, cap), inc)
    own_upd_subj = own_upd_subj.at[:, 2].set(jnp.where(refute, idx, n))
    own_upd_key = own_upd_key.at[:, 2].set(
        jnp.where(refute, make_key(inc, PREC_ALIVE), 0)
    )

    # ---- 5b. periodic self-announce (staggered by member id) -------------
    # the bounded table's anti-extinction mechanism: see module docstring
    ev_announce = jnp.int32(0)
    if params.announce_period > 0:
        due = ((t + idx) % params.announce_period == 0) & alive
        own_upd_subj = own_upd_subj.at[:, 3].set(jnp.where(due, idx, n))
        own_upd_key = own_upd_key.at[:, 3].set(
            jnp.where(due, make_key(inc, PREC_ALIVE), 0)
        )
        ev_announce = _bsum(due)

    # ---- 5c. Lifeguard bookkeeping (see swim.tick_impl 5c) ---------------
    # reads only inbox planes + suspicion/FSM lanes — no table cell —
    # so everything here is barrier-safe in fused mode
    ev_conf = jnp.int32(0)
    if lifeguard:
        open_t = susp_subj < n
        msg_inc = key_inc(in_key)
        conf_msg = (
            (in_subj[:, None, :] == susp_subj[:, :, None])
            & (key_prec(in_key) == PREC_SUSPECT)[:, None, :]
            & (msg_inc[:, None, :] >= susp_inc[:, :, None])
        )
        conf_add = jnp.sum(conf_msg, axis=2, dtype=jnp.int32) * open_t
        ev_conf = jnp.sum(conf_add, dtype=jnp.int32)
        susp_conf = jnp.minimum(susp_conf + conf_add, params.susp_k)
        shrink = _susp_shrink_table(params)
        susp_deadline = jnp.where(
            open_t,
            susp_start + shrink[jnp.clip(susp_conf, 0, params.susp_k)],
            susp_deadline,
        )
        succ = (expire1 & ~fail1) | (expire2 & ~fail2)
        dec = succ & (jnp.mod(t, jnp.int32(params.lhm_decay_ticks)) == 0)
        lhm = jnp.clip(
            lhm
            + fail1.astype(jnp.int32)
            + fail2.astype(jnp.int32)
            + refute.astype(jnp.int32)
            - dec.astype(jnp.int32),
            0,
            params.lhm_max,
        )

    # telemetry lane + flight frame, merge_won still pending: every term
    # below reads only masks computed against the tick-start table, so
    # the vector is a legitimate barrier operand in fused mode (it pins
    # the table-derived reads it consumes ahead of the in-place merge,
    # like the FSM lanes).  The census half is likewise final here —
    # susp_subj/inc settled in phases 1-5, in_subj in phase 4 — and
    # deliberately reads no table cell (swim._census_frame).
    ev_suspect_fp = _bsum(fail2 & (psubj < n) & alive[psafe_t])
    fired_safe = jnp.clip(fired_subj, 0, n - 1)
    ev_down_fp = _bsum(fire & (fired_subj < n) & alive[fired_safe])
    ev_vec = _event_vector(
        gossip_emitted=ev_emitted,
        gossip_lost=ev_lost,
        inbox_delivered=ev_delivered,
        inbox_overflowed=ev_emitted - ev_lost - ev_delivered,
        merge_won=jnp.int32(0),
        feed_pulls=ev_feed,
        seed_pulls=ev_seed,
        suspect_raised=_bsum(fail2),
        down_declared=_bsum(fire),
        refuted=_bsum(refute),
        self_announced=ev_announce,
        suspicion_confirmations=ev_conf,
        suspect_fp=ev_suspect_fp,
        down_fp=ev_down_fp,
    )
    frame = jnp.concatenate(
        [ev_vec, _census_frame(n, alive, susp_subj, inc, in_subj, lhm)]
    )

    # ---- 6. row-aligned slot update + relay ------------------------------
    all_subj = jnp.concatenate([in_subj, own_upd_subj], axis=1)
    all_key = jnp.concatenate([in_key, own_upd_key], axis=1)
    safe = jnp.clip(all_subj, 0, n - 1)
    new_packed = jnp.where(
        all_subj < n, _pack(params, safe, all_key, idx[:, None], t), 0
    )
    cols = _hash(params, safe)
    prev = jnp.take_along_axis(packed, cols, axis=1)
    improved = new_packed > prev
    self_col = _hash(params, idx)
    if fused:
        # ---- the merge scatter chain -------------------------------------
        # Everything the tick ever READS from the table now exists: the
        # FSM lookups, target picks, anti-entropy lanes, feed pulls,
        # refutation diag and the relay's prev gather all consumed the
        # tick-start table above.  The optimization barrier makes that
        # ordering a data dependence — the scatter below consumes the
        # barriered table, so XLA must schedule every read (every other
        # barrier operand) first, and copy insertion has no reader left
        # that could justify a whole-table HLO-temp copy beside the
        # donated buffer (the 8.0 GiB copy.326 that rejected 1M×2048,
        # PROFILE.md "Round 5: 1M on chip").  Barrier operands include
        # the packed-derived values that leave through the FSM state
        # rather than the merge, so none of those gathers can slide
        # past the in-place mutation either.
        if feed_vals is None:
            feed_vals = jnp.zeros((n, 0), dtype=SLOT_DTYPE)
            feed_cols = jnp.zeros((n, 0), dtype=jnp.int32)
        (packed, feed_vals, feed_cols, new_packed, cols, prev, improved,
         phase, psubj, pdl, pok, susp_subj, susp_inc, susp_deadline, inc,
         frame, lhm, susp_conf, susp_start,
         ) = jax.lax.optimization_barrier(
            (packed, feed_vals, feed_cols, new_packed, cols, prev, improved,
             phase, psubj, pdl, pok, susp_subj, susp_inc, susp_deadline, inc,
             frame, lhm, susp_conf, susp_start)
        )
        # two in-place scatters, not one concatenated [N, W_total] plane:
        # the updates are all precomputed above, so ordering stays
        # provable, while XLA:CPU's scatter cost scales with the widest
        # single plane it has to re-materialize (PROFILE.md r4: one
        # [N, 8·fe] scatter measured 30% WORSE than eight [N, fe] ones)
        # and the TPU path keeps its launch count at two
        fw = feed_vals.shape[1]
        step = max(1, fe)
        for w0 in range(0, fw, step):
            w1 = min(w0 + step, fw)
            packed = packed.at[
                idx[:, None],
                jax.lax.slice_in_dim(feed_cols, w0, w1, axis=1),
            ].max(jax.lax.slice_in_dim(feed_vals, w0, w1, axis=1))
        packed = packed.at[idx[:, None], cols].max(new_packed)
        # own entry pinned: force-write (never evicted by a colliding
        # squatter); dead members' writes are masked by scattering them
        # out of bounds (dropped) instead of gathering-then-rewriting
        # the current cell — the gather would be a post-merge reader.
        self_key = make_key(inc, PREC_ALIVE)
        pin_rows = jnp.where(alive, idx, n)
        packed = packed.at[pin_rows, self_col].set(
            _pack(params, idx, self_key, idx, t), mode="drop"
        )
    else:
        packed = packed.at[idx[:, None], cols].max(new_packed)
        # own entry pinned: force-write (never evicted by a colliding
        # squatter)
        self_key = make_key(inc, PREC_ALIVE)
        cur_self = packed[idx, self_col]
        packed = packed.at[idx, self_col].set(
            jnp.where(alive, _pack(params, idx, self_key, idx, t), cur_self)
        )

    # merge_won lands now that `improved` is settled (post-barrier in
    # fused mode); the counter sums a mask, never re-reads the table
    frame = frame.at[_EV_IDX["merge_won"]].add(_bsum(improved))
    events = state.events + frame[:N_EVENTS]
    ring = state.ring
    if params.ring_ticks > 0:
        ring = _ring_write(ring, t, params.ring_ticks, frame)

    relay_ok = improved & (all_subj != idx[:, None]) & (all_subj < n)
    bin_subj = jnp.concatenate(
        [jnp.where(relay_ok, all_subj, n), own_upd_subj], axis=1
    )
    bin_key = jnp.concatenate(
        [jnp.where(relay_ok, all_key, 0), own_upd_key], axis=1
    )

    # re-encode the table's tie-break rotation for the next tick
    occupied = packed > 0
    s_raw, k_raw = _unpack(params, packed, idx[:, None], t)
    packed = jnp.where(
        occupied, _pack(params, s_raw, k_raw, idx[:, None], t + 1), 0
    )

    # _buffer_merge is shape-generic (uses only .n / .buffer_slots);
    # its 15-bit packed key domain holds here because pview incarnations
    # clip to min(inc_cap(n), INC_CAP) at every generation site:
    # same [N, B] gossip buffers as the dense kernel
    buf_subj, buf_key, buf_sent = _buffer_merge(
        params, buf_subj, buf_key, buf_sent, bin_subj, bin_key
    )

    return PViewState(
        t=t + 1,
        alive=alive,
        inc=inc,
        slot_packed=packed,
        buf_subj=buf_subj,
        # narrow the at-rest lanes back down (ranges proven: see
        # LANE_DTYPE — keys < 2^15, sent <= _SENT_CLAMP = 2^15-1,
        # incarnations <= INC_CAP)
        buf_key=buf_key.astype(LANE_DTYPE),
        buf_sent=jnp.minimum(buf_sent, _SENT_CLAMP).astype(LANE_DTYPE),
        probe_phase=phase,
        probe_subj=psubj,
        probe_deadline=pdl,
        probe_ok=pok,
        susp_subj=susp_subj,
        susp_inc=susp_inc.astype(LANE_DTYPE),
        susp_deadline=susp_deadline,
        partition=part,
        events=events,
        ring=ring,
        lhm=lhm,
        susp_conf=susp_conf,
        susp_start=susp_start,
        deg_loss=deg_loss,
        deg_lag=deg_lag,
    )


tick = functools.partial(jax.jit, static_argnames=("params",))(tick_impl)


def _tick_n_impl(
    state: PViewState, rng: jax.Array, params: PViewParams, k: int
) -> PViewState:
    def body(s, key):
        return tick_impl(s, key, params), None

    keys = jax.random.split(rng, k)
    out, _ = jax.lax.scan(body, state, keys)
    return out


tick_n = functools.partial(jax.jit, static_argnames=("params", "k"))(
    _tick_n_impl
)

tick_n_donated = functools.partial(
    jax.jit, static_argnames=("params", "k"), donate_argnums=(0,)
)(_tick_n_impl)


def set_alive(state: PViewState, member: int, value: bool) -> PViewState:
    """Churn injection: crash or (re)start a member process."""
    alive = state.alive.at[member].set(value)
    inc = jnp.where(
        value,
        jnp.minimum(state.inc.at[member].add(1), INC_CAP),
        state.inc,
    )
    return state._replace(alive=alive, inc=inc)


def set_alive_many(state: PViewState, members, value: bool) -> PViewState:
    """Batch churn injection: one vectorized update instead of one
    dispatch per member (a 1% churn at n=100k is 1000 members)."""
    idx = jnp.asarray(members, dtype=jnp.int32)
    alive = state.alive.at[idx].set(value)
    inc = (
        jnp.minimum(state.inc.at[idx].add(1), INC_CAP)
        if value
        else state.inc
    )
    return state._replace(alive=alive, inc=inc)


def set_partition(state: PViewState, groups) -> PViewState:
    """Partition injection (see swim.set_partition)."""
    return state._replace(partition=jnp.asarray(groups, dtype=jnp.int32))


# [B, K] row blocks for the stats pass, mirroring the dense kernel's
# _stats_sums: the whole-table formulation unpacks subj/key plus ~6
# derived [N, K] temporaries in one program — at n=512k (4.3 GiB
# table) that program crashed the tunnel's remote-compile helper
# outright (HTTP 500, tpu_compile_helper exit 1) while init and the
# tick itself compiled fine. Blocking caps every temp at [B, K]; the
# [n] in-degree/stale accumulators ride the loop carry.
_STATS_BLOCK_ROWS = 4096


@functools.partial(jax.jit, static_argnames=("params",))
def _stats_impl(params: PViewParams, packed, alive, t):
    n, k = params.n, params.slots
    af = alive.astype(jnp.float32)
    n_alive = jnp.maximum(jnp.sum(af), 1.0)
    b = min(n, _STATS_BLOCK_ROWS)
    nblocks = (n + b - 1) // b

    def body(i, acc):
        indeg, stale, total, fp_sum, occ_sum = acc
        start = jnp.minimum(i * b, n - b)
        blk = jax.lax.dynamic_slice(packed, (start, jnp.int32(0)), (b, k))
        row_ids = start + jnp.arange(b, dtype=jnp.int32)
        rows = row_ids[:, None]
        subj, key = _unpack(params, blk, rows, t)
        occupied = key > 0
        prec = key_prec(key)
        live_obs = jax.lax.dynamic_slice(alive, (start,), (b,))[:, None]
        subj_alive = alive[jnp.clip(subj, 0, n - 1)]
        # clamped last block: rows an earlier block already counted are
        # masked out (same dedupe as swim._stats_sums)
        fresh = (row_ids >= i * b)[:, None]
        # in-degree: for each subject, how many LIVE observers hold it
        # alive
        ka_entry = (
            occupied & (prec == PREC_ALIVE) & live_obs
            & (subj != rows) & fresh
        )
        indeg = indeg.at[jnp.where(ka_entry, subj, 0)].add(
            ka_entry.astype(jnp.int32)
        )
        # float32 accumulators: bool sums default to int32, and n·slots
        # crosses 2^31 at n=2M×K=2048 — the wrapped total made
        # `expected` negative and the pv_coverage threshold vacuously
        # true (caught on the first 2M rung; float32's ~2^-24 relative
        # rounding is irrelevant for a mean)
        total = total + jnp.sum((ka_entry & subj_alive).astype(jnp.float32))
        fp_entries = (
            occupied & (prec >= PREC_SUSPECT) & live_obs & subj_alive
            & fresh
        )
        fp_sum = fp_sum + jnp.sum(fp_entries.astype(jnp.float32))
        occ_sum = occ_sum + jnp.sum(
            (occupied & live_obs & fresh).astype(jnp.float32)
        )
        # churn detection: a dead member counts as DETECTED when no
        # live observer still holds an ALIVE entry for it
        # (suspect/down entries and absence both mean "won't be routed
        # to") — the partial-view analog of the dense kernel's "dead
        # members marked down" (swim.py stats)
        stale_entry = (
            occupied & (prec == PREC_ALIVE) & live_obs & ~subj_alive
            & fresh
        )
        stale = stale.at[jnp.where(stale_entry, subj, 0)].add(
            stale_entry.astype(jnp.int32)
        )
        return indeg, stale, total, fp_sum, occ_sum

    zeros_n = jnp.zeros(n, dtype=jnp.int32)
    zf = jnp.float32(0.0)
    indeg, stale_per_subject, total_entries, fp_sum, occ_sum = (
        jax.lax.fori_loop(
            0, nblocks, body, (zeros_n, zeros_n, zf, zf, zf)
        )
    )
    expected = total_entries / n_alive  # mean in-degree over live subjects
    live_indeg = jnp.where(alive, indeg, jnp.int32(INT32_MAX))
    min_in = jnp.min(live_indeg)
    pv_cov = jnp.sum(
        jnp.where(alive, (indeg.astype(jnp.float32) >= expected * 0.5), False)
    ) / n_alive
    fp = fp_sum / jnp.maximum(jnp.sum(af) * (n_alive - 1), 1.0)
    occ = occ_sum / (n_alive * params.slots)
    n_dead = jnp.sum(~alive)
    detected = jnp.where(
        n_dead > 0,
        jnp.sum((~alive) & (stale_per_subject == 0)) / jnp.maximum(n_dead, 1),
        1.0,
    )
    return jnp.stack(
        [
            pv_cov,
            expected,
            min_in.astype(jnp.float32),
            occ,
            fp.astype(jnp.float32),
            detected.astype(jnp.float32),
        ]
    )


def saturation_floor(n: int, slots: int) -> float:
    """The mean-in-degree bar a converged table must clear: 85% of the
    expected distinct-subject count of a FULL row.  A subject occupies
    exactly one hash column per row, so a full row holds
    K*(1-(1-1/K)^(n-1)) distinct subjects in expectation (≈ n-1 for
    n << K, ≈ K for n >> K; at n ≈ K it dips to K(1-1/e), which
    min(n-1, slots-1) would overshoot unreachably).  Single definition
    shared by the convergence scripts and the device-resident loop —
    the two predicates must agree or a device-loop "converged" could
    read as a host-loop miss."""
    return 0.85 * min(
        n - 1, slots * (1.0 - (1.0 - 1.0 / slots) ** (n - 1))
    )


def _run_to_converged_impl(
    state, rng, params, cov_target, quorum, check_every, max_ticks
):
    """Tick until the pview convergence bar holds, ENTIRELY on device
    (the pview counterpart of `swim.run_to_coverage`): a lax.while_loop
    of check_every-tick scans with the blocked stats pass as predicate.
    Bar (same four terms as scripts/pview_converge.py): pv_coverage >=
    cov_target, min_in_degree >= quorum, mean_in_degree >= the
    saturation floor, false_positive == 0.

    Zero host round-trips between dispatch and convergence — on a
    tunneled chip every host-side stats check costs a full RTT (~85 ms
    measured).  CAUTION for tunnel use: the whole loop is ONE device
    dispatch, and the axon tunnel kills executions past ~45-60 s
    (PROFILE.md) — callers behind the tunnel must keep the host-driven
    chunked loop instead.  Returns (state, stats_vec) with stats_vec the
    final `_stats_impl` row, so callers read the verdict without paying
    another stats dispatch."""
    sat = saturation_floor(params.n, params.slots)

    def _ok(vals):
        return (
            (vals[0] >= cov_target)
            & (vals[2] >= jnp.float32(quorum))
            & (vals[1] >= jnp.float32(sat))
            & (vals[4] == 0.0)
        )

    def cond(carry):
        st, _, vals = carry
        return ~_ok(vals) & (st.t + check_every <= max_ticks)

    def body(carry):
        st, rng, _ = carry
        rng, key = jax.random.split(rng)
        st = _tick_n_impl(st, key, params, check_every)
        return st, rng, _stats_impl(params, st.slot_packed, st.alive, st.t)

    init_vals = jnp.full((6,), -1.0, dtype=jnp.float32)
    state, _, vals = jax.lax.while_loop(cond, body, (state, rng, init_vals))
    return state, vals


run_to_converged = functools.partial(
    jax.jit,
    static_argnames=("params", "cov_target", "quorum", "check_every",
                     "max_ticks"),
    donate_argnums=(0,),
)(_run_to_converged_impl)


def stats_and_events(state: PViewState, params: PViewParams):
    """(stats dict, [N_EVENTS] uint32 event totals, FlightDrain) in ONE
    device→host readback — the telemetry lane and the flight ring
    piggyback on the stats transfer."""
    import numpy as np

    vals, ev, ring, t = jax.device_get(
        (
            _stats_impl(params, state.slot_packed, state.alive, state.t),
            state.events,
            state.ring,
            state.t,
        )
    )
    vals = np.asarray(vals)
    stats = {
        "pv_coverage": float(vals[0]),
        "mean_in_degree": float(vals[1]),
        "min_in_degree": float(vals[2]),
        "occupancy": float(vals[3]),
        "false_positive": float(vals[4]),
        "detected": float(vals[5]),
    }
    return (
        stats,
        np.asarray(ev).astype(np.uint32),
        FlightDrain(ring=np.asarray(ring), t=int(t)),
    )


def membership_stats(state: PViewState, params: PViewParams) -> dict:
    """Partial-view stability metrics, one stacked device→host readback.

    pv_coverage: fraction of live members whose in-degree (live observers
    holding them alive) is ≥ half the expected in-degree. mean_in_degree:
    that expectation. min_in_degree: the worst-known live member.
    occupancy: live members' slot-fill fraction. false_positive: live
    subject entries marked suspect/down, per live observer pair.
    """
    return stats_and_events(state, params)[0]


def memory_gb(n: int, slots: int) -> dict:
    """Per-chip memory math for a PView state of `n` members × `slots`
    hash-slot entries, sharded over a v5e-8. The single source for the
    scale scripts' recorded notes — sized from SLOT_DTYPE (the packed
    words need the full 31 bits, so unlike the dense kernel's VIEW_DTYPE
    this cannot narrow) for the table, plus the gossip/FSM lanes: one
    int32 subject column and two LANE_DTYPE (int16) columns per buffer
    slot (buf_key/buf_sent narrowed in r6), and ~10 int32-equivalent FSM
    fields per member."""
    i32 = jnp.dtype(jnp.int32).itemsize
    lane = jnp.dtype(LANE_DTYPE).itemsize
    table_gb = n * slots * jnp.dtype(SLOT_DTYPE).itemsize / 2**30
    bufs_gb = n * (16 * (i32 + 2 * lane) + 10 * i32) / 2**30
    return {
        "slot_table_gb": round(table_gb, 2),
        "buffers_fsm_gb": round(bufs_gb, 2),
        "per_chip_gb_v5e8": round((table_gb + bufs_gb) / 8, 3),
    }

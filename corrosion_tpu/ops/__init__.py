"""JAX kernels: batched SWIM membership, gossip dissemination, CRDT merge."""

"""Array-resident CRDT merge: the batch decision plane as a jitted kernel.

SURVEY §7 step 1 asks for the merge engine "as C++/XLA-custom-call or
Pallas kernels"; r4 shipped the host-native C++ engine
(`native/crdt_batch.cpp`) with an argued ceiling.  This module is the
measured counterpart: the same column-level LWW + causal-length decision
rules (`agent/util.rs:703-1310` semantics, pinned to
`store/crdt.py::_merge_table_python`) recast as a data-parallel program
that XLA can fuse and a TPU can run over a whole sync-flood batch at
once:

  1. one lexsorted pass by (pk-group, arrival) + a segmented exclusive
     prefix-max over causal lengths: which changes are causal
     transitions, which are equal-cl candidates, what each row's final
     cl / erasure watermark is;
  2. one lexsorted pass by ((pk,cid)-group, arrival) + a segmented
     exclusive prefix-max over the lexicographic key (cl, col_version,
     value-digest): the per-change win mask — a change wins iff it
     strictly beats everything before it (local baseline included);
  3. two masked segment-argmaxes over the same key: the final clock-row
     writer per cid (candidates at the final cl only — causal
     transitions reset clock rows) and the final cell writer per cid
     (candidates above the last applied delete's erasure watermark —
     odd re-creates keep surviving cell values).

Values enter the kernel as 128-bit order-preserving digests (type rank,
then numeric key or bytes prefix).  A digest is exact for NULLs,
numerics within float64-exact range, and text/blob ≤ 14 bytes; ties at
equal INEXACT digests cannot be decided on-device and surface in the
`ambiguous` output — the caller falls back to the host engine for that
batch (the reference's merge-equal-values rule needs the true value
order, `types/values.py::cmp_values`).

The host wrapper `merge_table_array` slots into the same engine contract
as `_merge_table_native` so the store can A/B the three engines on
identical inputs (CORRO_CRDT_ENGINE=array|native|python;
scripts/bench_crdt_merge.py records the measurement).
"""

from __future__ import annotations

import struct
from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np

from corrosion_tpu.runtime.metrics import (
    CRDT_MERGE_EVENTS,
    record_kernel_events,
)
from corrosion_tpu.runtime.records import FLIGHT

SENTINEL = "-1"

_F64_EXACT = 1 << 53


# ---------------------------------------------------------------------------
# value digests (host side)


def value_digest(val) -> tuple:
    """(d0, d1, d2, d3, exact): 112-bit order-preserving digest as four
    words that all fit int32 (≤28 payload bits each — the kernel runs
    without jax x64).  Order matches types/values.py::cmp_values:
    NULL < numeric < TEXT < BLOB; numerics by value, text/blob
    lexicographic bytewise.

    exact=True means the digest captures the full value order: NULLs,
    numerics representable exactly in float64, text/blob ≤ 13 bytes
    (13-byte prefix + capped length; equal-prefix ordering by length is
    the bytewise prefix rule, valid precisely when one side is fully
    captured)."""
    if val is None:
        return 0, 0, 0, 0, True
    if isinstance(val, bool):
        val = int(val)
    if isinstance(val, (int, float)):
        if isinstance(val, int):
            exact = -_F64_EXACT <= val <= _F64_EXACT
        else:
            exact = True
        f = float(val)
        # total-order map of float64 to uint64: flip sign bit for
        # positives, flip all bits for negatives
        bits = struct.unpack(">Q", struct.pack(">d", f))[0]
        if bits & (1 << 63):
            bits = (~bits) & 0xFFFFFFFFFFFFFFFF
        else:
            bits |= 1 << 63
        d0 = (1 << 28) | (bits >> 40)  # rank 1 + top 24 bits
        d1 = (bits >> 12) & 0xFFFFFFF
        d2 = (bits & 0xFFF) << 16
        return d0, d1, d2, 0, exact
    if isinstance(val, str):
        rank, data = 2, val.encode("utf-8")
    elif isinstance(val, (bytes, bytearray, memoryview)):
        rank, data = 3, bytes(val)
    else:  # pragma: no cover - schema guarantees sqlite types
        return (4 << 28) - 1, 0, 0, 0, False
    exact = len(data) <= 13
    # 13-byte prefix + min(len, 14): equal prefixes order by length when
    # one side is a true prefix (exact); two ≥14-byte values tie at 14
    # and surface as inexact
    w = int.from_bytes(
        data[:13].ljust(13, b"\x00") + bytes([min(len(data), 14)]), "big"
    )
    d0 = (rank << 28) | ((w >> 84) & 0xFFFFFFF)
    d1 = (w >> 56) & 0xFFFFFFF
    d2 = (w >> 28) & 0xFFFFFFF
    d3 = w & 0xFFFFFFF
    return d0, d1, d2, d3, exact


# ---------------------------------------------------------------------------
# the jitted decision kernel


def _lex_gt(a, b):
    """Strict lexicographic a > b over tuples of equal-length arrays."""
    import jax.numpy as jnp

    gt = jnp.zeros(a[0].shape, dtype=bool)
    eq = jnp.ones(a[0].shape, dtype=bool)
    for xa, xb in zip(a, b):
        gt = gt | (eq & (xa > xb))
        eq = eq & (xa == xb)
    return gt


def _lex_max(a, b):
    import jax.numpy as jnp

    take_b = _lex_gt(b, a)
    return tuple(jnp.where(take_b, xb, xa) for xa, xb in zip(a, b))


def _seg_exclusive_lexmax(keys, seg_start, neg, n_key: int):
    """Exclusive segmented prefix lexicographic max in sorted order.

    keys: tuple of arrays — the first ``n_key`` components order the
    max; any remaining components are payload carried with the winning
    element (e.g. its exactness bit).  seg_start: bool array; neg:
    per-component 'minus infinity' / default values."""
    import jax
    import jax.numpy as jnp

    n = keys[0].shape[0]
    # shift right by one: element i sees the max of [segment start, i)
    shifted = tuple(
        jnp.concatenate([jnp.full((1,), nv, dtype=k.dtype), k[:-1]])
        for k, nv in zip(keys, neg)
    )
    start = jnp.concatenate([jnp.ones((1,), bool), seg_start[1:]])
    reset = tuple(
        jnp.where(start, jnp.full((n,), nv, dtype=k.dtype), k)
        for k, nv in zip(shifted, neg)
    )

    def combine(x, y):
        xf, xk = x
        yf, yk = y
        take_y = yf | _lex_gt(yk[:n_key], xk[:n_key])
        merged = tuple(
            jnp.where(take_y, yc, xc) for xc, yc in zip(xk, yk)
        )
        return xf | yf, merged

    flags = start
    _, out = jax.lax.associative_scan(combine, (flags, reset))
    return out


@partial(
    __import__("jax").jit,
    static_argnames=("num_groups", "num_cells"),
)
def _merge_kernel(
    grp, cellg, cl, cv, d0, d1, d2, d3, exact, fake, pos, is_sent, valid,
    num_groups: int, num_cells: int,
):
    """All-batch merge decisions; see module docstring for the shape.

    Inputs are 1-D int32/bool arrays over changes + baseline rows
    (baselines carry pos = -1).  Padding rows have valid = False and
    grp/cellg pointing at reserved trailing segment ids."""
    import jax.numpy as jnp
    from jax import ops as jops

    neg = jnp.int32(-1)
    big = jnp.int32(2**31 - 1)

    # ---- pass 1: row-cl prefix maxima in arrival order -------------------
    order1 = jnp.lexsort((pos, grp))
    g1 = grp[order1]
    cl1 = jnp.where(valid[order1], cl[order1], neg)
    seg1 = jnp.concatenate([jnp.ones((1,), bool), g1[1:] != g1[:-1]])
    (prev_max,) = _seg_exclusive_lexmax((cl1,), seg1, (-1,), n_key=1)
    is_change1 = pos[order1] >= 0
    candidate1 = cl1 >= prev_max
    transition1 = is_change1 & (cl1 > prev_max) & valid[order1]
    equal_cl1 = is_change1 & (cl1 == prev_max) & valid[order1]

    # scatter back to original positions
    inv1 = jnp.zeros_like(order1).at[order1].set(jnp.arange(order1.shape[0]))
    transition = transition1[inv1]
    equal_cl = equal_cl1[inv1]
    candidate = (candidate1 & valid[order1])[inv1]

    # per-group aggregates
    gsafe = jnp.where(valid, grp, num_groups - 1)
    final_cl = jops.segment_max(
        jnp.where(valid, cl, neg), gsafe, num_segments=num_groups
    )
    any_transition = (
        jops.segment_max(
            jnp.where(transition, jnp.int32(1), jnp.int32(0)),
            gsafe, num_segments=num_groups,
        ) > 0
    )
    applied_even = transition & (cl % 2 == 0)
    max_erase = jops.segment_max(
        jnp.where(applied_even, cl, neg), gsafe, num_segments=num_groups
    )
    any_delete = (
        jops.segment_max(
            jnp.where(applied_even, jnp.int32(1), jnp.int32(0)),
            gsafe, num_segments=num_groups,
        ) > 0
    )

    # ---- pass 2: per-(pk,cid) key scans ----------------------------------
    key = (cl, cv, d0, d1, d2, d3)
    order2 = jnp.lexsort((pos, cellg))
    c2 = cellg[order2]
    seg2 = jnp.concatenate([jnp.ones((1,), bool), c2[1:] != c2[:-1]])
    key2 = tuple(jnp.where(valid[order2], k[order2], neg) for k in key)
    # exactness and fake-digest bits ride along as payload of the
    # running max element
    exact2 = jnp.where(valid[order2], exact[order2].astype(jnp.int32), 1)
    fake2 = jnp.where(valid[order2], fake[order2].astype(jnp.int32), 0)
    scanned = _seg_exclusive_lexmax(
        key2 + (exact2, fake2), seg2, (neg,) * 6 + (1, 0), n_key=6
    )
    prev_key2, prev_exact2, prev_fake2 = scanned[:6], scanned[6], scanned[7]
    beats_prev2 = _lex_gt(key2, prev_key2)
    # digest-level tie with EITHER side inexact → undecidable on-device
    eq_prev2 = ~beats_prev2 & ~_lex_gt(prev_key2, key2)
    fuzzy2 = eq_prev2 & ((exact2 == 0) | (prev_exact2 == 0))
    # (cl, cv)-level tie against a FAKE baseline digest (local value not
    # prefetched): the digest comparison is meaningless either way
    clcv_eq2 = (key2[0] == prev_key2[0]) & (key2[1] == prev_key2[1])
    fuzzy2 = fuzzy2 | (clcv_eq2 & (prev_fake2 == 1))
    inv2 = jnp.zeros_like(order2).at[order2].set(jnp.arange(order2.shape[0]))
    beats_prev = beats_prev2[inv2]
    eq_fuzzy = fuzzy2[inv2]

    # win mask (the loop's per-change outcome at its position)
    odd = cl % 2 == 1
    col_win = equal_cl & odd & ~is_sent & beats_prev
    win = (transition | col_win) & valid & (pos >= 0)

    # ambiguity: an equal-cl non-sentinel candidate tying the running max
    # on a digest either side of which is inexact — the host must
    # re-decide the batch with true value order
    tie_risk = (
        equal_cl & odd & ~is_sent & eq_fuzzy & valid & (pos >= 0)
    )
    ambiguous = jnp.any(tie_risk)

    # ---- final writers per (pk,cid) --------------------------------------
    csafe = jnp.where(valid, cellg, num_cells - 1)
    erase_of = max_erase[gsafe]
    final_of = final_cl[gsafe]
    cell_live = candidate & (cl > erase_of) & ~is_sent & valid
    # clock rows come only from ODD-cl writes: an even (delete)
    # transition carrying a non-sentinel cid records only its sentinel
    # entry in the reference loop
    clock_live = candidate & (cl == final_of) & ~is_sent & valid & win & odd
    # clock rows: baselines only count when no transition reset them
    base_clock_live = (
        (pos < 0) & ~is_sent & valid & (cl == final_of)
    )
    clock_cand = clock_live | base_clock_live
    cell_cand = cell_live & (win | (pos < 0))

    def seg_arglexmax(mask):
        import jax.numpy as jnp2

        # winner = lexicographically largest (key, -pos) among mask rows
        neg_pos = -pos  # later arrivals lose ties (first writer keeps)
        full = key + (neg_pos,)
        masked = tuple(jnp2.where(mask, k, neg) for k in full)
        # reduce per segment componentwise is wrong for lex order, so
        # sort instead: order by (cellg, key, -pos) and take the last
        # row of each segment
        o = jnp2.lexsort(tuple(reversed(masked)) + (csafe,))
        cs = csafe[o]
        is_last = jnp2.concatenate([cs[1:] != cs[:-1], jnp2.ones((1,), bool)])
        winner_rows = jnp2.where(is_last & mask[o], o, -1)
        winners = jnp2.full((num_cells,), -1, dtype=jnp2.int32)
        winners = winners.at[jnp2.where(is_last, cs, num_cells - 1)].set(
            jnp2.where(is_last, winner_rows, -1), mode="drop"
        )
        return winners

    cell_winner = seg_arglexmax(cell_cand)
    clock_winner = seg_arglexmax(clock_cand)

    # telemetry lane (CRDT_MERGE_EVENTS order, runtime/metrics.py):
    # per-batch decision outcomes, computed on-device from masks the
    # kernel already holds and drained by the host wrapper in the same
    # readback as the decisions themselves
    is_change = valid & (pos >= 0)
    events = jnp.stack(
        [
            jnp.sum(win, dtype=jnp.int32),          # decide_won
            jnp.sum(transition, dtype=jnp.int32),   # decide_transition
            jnp.sum(is_change & ~win, dtype=jnp.int32),  # decide_stale
            jnp.sum(tie_risk, dtype=jnp.int32),     # decide_ambiguous
        ]
    )

    return (
        win, transition, final_cl, any_transition, any_delete, max_erase,
        cell_winner, clock_winner, ambiguous, events,
    )


# ---------------------------------------------------------------------------
# host wrapper (engine contract of store/crdt.py::_merge_table_native)


def _pad(n: int) -> int:
    p = 64
    while p < n:
        p *= 2
    return p


def merge_table_array(
    store,
    tbl: str,
    chs: Sequence,
    st: Dict[bytes, dict],
    rcl: Dict[bytes, int],
    clr: set,
    ckf: Dict[bytes, Dict[str, tuple]],
    clf: Dict[bytes, Dict[str, object]],
    rdel: set,
    rens: set,
) -> Optional[List[bool]]:
    """Merge one table's changes through the jitted kernel; None → caller
    must use another engine (ambiguous value tie or out-of-range ints)."""
    from corrosion_tpu.store.crdt import _clock_entry

    n = len(chs)
    if n == 0:
        return []

    pks: List[bytes] = []
    pk_ids: Dict[bytes, int] = {}
    cell_ids: Dict[tuple, int] = {}
    rows_grp: List[int] = []
    rows_cell: List[int] = []
    rows_cl: List[int] = []
    rows_cv: List[int] = []
    rows_d = [[], [], [], []]
    rows_exact: List[bool] = []
    rows_fake: List[bool] = []
    rows_pos: List[int] = []
    rows_sent: List[bool] = []

    def add_row(g, c, cl, cv, dig, exact, pos, sent, fake=False):
        if not (0 <= cl < 2**31 and 0 <= cv < 2**31):
            raise OverflowError
        rows_grp.append(g)
        rows_cell.append(c)
        rows_cl.append(cl)
        rows_cv.append(cv)
        for k in range(4):
            rows_d[k].append(dig[k])
        rows_exact.append(exact)
        rows_fake.append(fake)
        rows_pos.append(pos)
        rows_sent.append(sent)

    def cell_id(g: int, cid: str) -> int:
        key = (g, cid)
        cid_idx = cell_ids.get(key)
        if cid_idx is None:
            cid_idx = len(cell_ids)
            cell_ids[key] = cid_idx
        return cid_idx

    try:
        # change rows (arrival order = pos)
        for j, ch in enumerate(chs):
            g = pk_ids.get(ch.pk)
            if g is None:
                g = len(pk_ids)
                pk_ids[ch.pk] = g
                pks.append(ch.pk)
            sent = ch.cid == SENTINEL
            d0, d1, d2, d3, exact = value_digest(
                None if sent else ch.val
            )
            add_row(
                g, cell_id(g, ch.cid), ch.cl,
                0 if sent else ch.col_version,
                (d0, d1, d2, d3), exact, j, sent,
            )
        # baseline rows: one per pk (row cl, as sentinel) + one per
        # locally-clocked cid that appears in this batch
        for pk, g in pk_ids.items():
            s = st[pk]
            local_cl = s["cl"]
            add_row(
                g, cell_id(g, SENTINEL), local_cl, 0,
                (0, 0, 0, 0), True, -1, True,
            )
            disk = s["disk"] or {}
            for cid, cv in s["clock"].items():
                if cid == SENTINEL or (g, cid) not in cell_ids:
                    continue
                if cid in disk:
                    d0, d1, d2, d3, exact = value_digest(disk[cid])
                    fake = False
                else:
                    # value not prefetched: the digest is a placeholder —
                    # ANY equal-(cl, cv) comparison against it must send
                    # the batch to a host engine
                    d0, d1, d2, d3, exact, fake = 0, 0, 0, 0, False, True
                add_row(
                    g, cell_ids[(g, cid)], local_cl, cv,
                    (d0, d1, d2, d3), exact, -1, False, fake=fake,
                )
    except OverflowError:
        return None

    total = len(rows_grp)
    pad_n = _pad(total)
    num_groups = _pad(len(pk_ids) + 1)
    num_cells = _pad(len(cell_ids) + 1)

    def arr(xs, dtype=np.int32, fill=0):
        a = np.full(pad_n, fill, dtype=dtype)
        a[:total] = xs
        return a

    valid = np.zeros(pad_n, dtype=bool)
    valid[:total] = True
    out = _merge_kernel(
        arr(rows_grp, fill=num_groups - 1),
        arr(rows_cell, fill=num_cells - 1),
        arr(rows_cl), arr(rows_cv),
        arr(rows_d[0]), arr(rows_d[1]), arr(rows_d[2]), arr(rows_d[3]),
        arr(rows_exact, dtype=bool), arr(rows_fake, dtype=bool),
        arr(rows_pos, fill=-1),
        arr(rows_sent, dtype=bool), valid,
        num_groups=num_groups, num_cells=num_cells,
    )
    (win, transition, final_cl, any_tr, any_del, _max_erase,
     cell_winner, clock_winner, ambiguous, events) = (
        np.asarray(x) for x in out
    )
    if bool(ambiguous):
        # the batch falls back to a host engine: only the ambiguity
        # count is real telemetry (the win/stale decisions are discarded
        # and re-made by the fallback — recording them would double-book)
        ev_list = [0, 0, 0, int(events[3])]
        record_kernel_events("crdt_merge", ev_list)
        FLIGHT.record_host_frame(
            "crdt_merge", dict(zip(CRDT_MERGE_EVENTS, ev_list))
        )
        return None
    record_kernel_events("crdt_merge", events)
    # the merge kernel has no scan carry, so its flight frames are
    # host-side: one per decided batch, same lanes as the counter drain
    FLIGHT.record_host_frame(
        "crdt_merge",
        dict(zip(CRDT_MERGE_EVENTS, (int(v) for v in events))),
    )

    # ---- rebuild the engine-contract flush plans -------------------------
    wins = [bool(win[j]) for j in range(n)]
    # single pass over changes: per-pk final-transition change + any-win
    final_transition: Dict[bytes, object] = {}
    any_win_pk: Dict[bytes, bool] = {}
    for j, ch in enumerate(chs):
        if wins[j]:
            any_win_pk[ch.pk] = True
        if transition[j] and ch.cl == int(final_cl[pk_ids[ch.pk]]):
            final_transition.setdefault(ch.pk, ch)
    for pk, g in pk_ids.items():
        s = st[pk]
        fcl = int(final_cl[g])
        if bool(any_tr[g]):
            s["cl"] = fcl
            rcl[pk] = fcl
            clr.add(pk)
            # sentinel clock entry from the transition that reached fcl
            ckf[pk] = {SENTINEL: _clock_entry(final_transition[pk], fcl)}
            s["clock"] = {SENTINEL: fcl}
        if bool(any_del[g]):
            rdel.add(pk)
            if fcl % 2 == 0:
                s["vals"] = {}
                clf.pop(pk, None)
        if fcl % 2 == 1 and any_win_pk.get(pk):
            rens.add(pk)

    # cell + clock winners
    for (g, cid), cidx in cell_ids.items():
        if cid == SENTINEL:
            continue
        pk = pks[g]
        if int(final_cl[g]) % 2 == 0:
            continue  # dead row: no cells
        cw = int(cell_winner[cidx])
        if 0 <= cw < n:
            ch = chs[cw]
            clf.setdefault(pk, {})[cid] = ch.val
            st[pk]["vals"][cid] = ch.val
        elif bool(any_del[g]):
            # erased and not rewritten: value gone with the delete
            pass
        kw = int(clock_winner[cidx])
        if 0 <= kw < n:
            ch = chs[kw]
            ckf.setdefault(pk, {})[cid] = _clock_entry(ch, ch.col_version)
            st[pk]["clock"][cid] = ch.col_version

    return wins

"""`python -m corrosion_tpu` → the corrosion CLI."""

from corrosion_tpu.cli import main

main()

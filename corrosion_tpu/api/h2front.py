"""Dual-protocol API front-end: HTTP/2 + HTTP/1.1 on one port.

The reference API port speaks both protocols — hyper's auto-mode server
sniffs the 24-byte h2c client preface and its client is HTTP/2-only
(`klukai-client/src/lib.rs:33-47`).  This front-end reproduces that on
asyncio:

- each accepted connection is sniffed byte-by-byte against the preface:
  the instant the buffer diverges it is an HTTP/1.1 connection and the
  bytes are replayed into a raw TCP proxy to the internal aiohttp
  listener; a full preface match terminates HTTP/2 here
  (`net/h2.py`) and forwards each multiplexed stream as an HTTP/1.1
  request to the same internal listener;
- forwarding preserves the whole aiohttp route surface (authz, limits,
  metrics, NDJSON streaming) with no duplicated handler logic — response
  bodies stream frame-by-frame, so one h2 connection can carry live
  subscriptions next to queries, like the reference's multiplexed h2.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

import aiohttp

from corrosion_tpu.net.h2 import (
    CANCEL,
    PREFACE,
    H2Request,
    H2Server,
    StreamReset,
)

log = logging.getLogger(__name__)

# hop-by-hop headers that must not cross the h1→h2 boundary (RFC 9113 §8.2.2)
_HOP_BY_HOP = {
    "connection", "keep-alive", "proxy-connection", "transfer-encoding",
    "upgrade", "te",
}


class _H2PayloadWriter:
    """aiohttp AbstractStreamWriter that emits h2 frames.

    aiohttp response objects (`Response`, `StreamResponse`) write their
    status line, headers and body through the request's payload writer;
    pointing that writer at an `H2Request` serves the whole aiohttp
    handler surface natively over HTTP/2 — no loopback hop, no h1
    re-parse (r4 weak #7: the hop cost h2 ~45% of h1 throughput)."""

    def __init__(self, req: H2Request) -> None:
        self._req = req
        self.transport = None
        self.output_size = 0
        self.buffer_size = 0
        self.length = None

    async def write_headers(self, status_line: str, headers) -> None:
        # "HTTP/1.1 200 OK" -> 200; header keys lowered for h2
        status = int(status_line.split(" ", 2)[1])
        out = {
            k.lower(): v for k, v in headers.items()
            if k.lower() not in _HOP_BY_HOP
        }
        await self._req.send_headers(status, out)

    async def write(self, chunk, *, drain: bool = True, LIMIT=0x10000) -> None:
        chunk = bytes(chunk)
        if chunk:
            self.output_size += len(chunk)
            await self._req.send_data(chunk)

    async def write_eof(self, chunk: bytes = b"") -> None:
        # one frame: the last body chunk carries END_STREAM itself
        # (plain json responses become a single DATA frame)
        chunk = bytes(chunk)
        self.output_size += len(chunk)
        await self._req.send_data(chunk, end_stream=True)

    async def drain(self) -> None:
        pass

    def enable_compression(self, encoding: str = "deflate") -> None:
        pass  # h2 responses go uncompressed; clients didn't negotiate

    def enable_chunking(self) -> None:
        pass  # h2 has its own framing; chunked transfer-encoding is h1

    def send_headers(self, *a, **kw) -> None:
        # aiohttp's Response.write_eof calls this SYNCHRONOUSLY as a
        # flush hook; headers were already written via write_headers
        pass


class _TransportStub:
    """Transport stand-in for `_ProtocolStub.transport`: aiohttp ≥ 3.9
    web.Request reads `protocol.transport.get_extra_info("sslcontext"/
    "peername")` AT CONSTRUCTION (older versions read `ssl_context`/
    `peername` off the protocol itself and tolerated transport=None —
    with 3.11 installed, transport=None made every native-h2 dispatch
    die on `assert transport is not None` before the handler ran: the
    HTTP-500 /v1/* cascade)."""

    def get_extra_info(self, name, default=None):
        return default

    def is_closing(self) -> bool:
        return False


class _ProtocolStub:
    """Minimal stand-in for aiohttp's RequestHandler protocol: just what
    web.Request and StreamReader touch on the serving path (a shared
    instance — per-request unittest.mock objects cost ~0.7 ms each,
    half the request budget at SELECT-1 sizes)."""

    _reading_paused = False
    transport = _TransportStub()
    writer = None
    ssl_context = None  # pre-3.9 aiohttp read these two at construction
    peername = None

    def is_connected(self) -> bool:
        return True

    # StreamReader flow-control hooks
    def pause_reading(self) -> None:
        pass

    def resume_reading(self) -> None:
        pass


_PROTOCOL_STUB = _ProtocolStub()


class NativeH2Dispatcher:
    """Serve h2 streams directly against an aiohttp Application: resolve
    the route, run the middleware chain, and stream the response out as
    h2 frames via `_H2PayloadWriter`."""

    def __init__(self, app) -> None:
        self._app = app

    def _build_request(self, req: H2Request, payload, writer):
        """A real web.Request over the h2 stream — the hand-rolled core
        of aiohttp.test_utils.make_mocked_request without its per-call
        Mock graph."""
        import asyncio as _asyncio

        from aiohttp import web
        from aiohttp.http_parser import RawRequestMessage
        from aiohttp.http_writer import HttpVersion
        from multidict import CIMultiDict, CIMultiDictProxy
        from yarl import URL

        # H2Request.headers already excludes pseudo-headers; the client's
        # authority pseudo-header becomes Host (RFC 9113 §8.3.1)
        hdrs = CIMultiDict(req.headers)
        if "host" not in hdrs:
            hdrs["host"] = req.authority or "h2"
        raw = tuple(
            (k.encode(), v.encode()) for k, v in hdrs.items()
        )
        # positional: the C-accelerated RawRequestMessage has no kwargs
        # (method, path, version, headers, raw_headers, should_close,
        #  compression, upgrade, chunked, url)
        message = RawRequestMessage(
            req.method, req.path, HttpVersion(1, 1),
            CIMultiDictProxy(hdrs), raw,
            False, None, False, False, URL(req.path),
        )
        return web.Request(
            message, payload, _PROTOCOL_STUB, writer,
            _asyncio.current_task(), _asyncio.get_event_loop(),
            # same body-size limit as the h1 side of this API (the
            # app's default): limits must not diverge by protocol
            client_max_size=self._app._client_max_size,
        )

    async def handle(self, req: H2Request) -> None:
        from aiohttp import streams, web

        body = await req.read_body()
        payload = streams.StreamReader(_PROTOCOL_STUB, limit=2**20)
        if body:
            payload.feed_data(body)
        payload.feed_eof()
        writer = _H2PayloadWriter(req)
        request = self._build_request(req, payload, writer)
        try:
            # the app's own dispatch (resolve + match_info freeze +
            # middleware chain + on_response_prepare signals) — the app
            # is frozen by runner.setup() before any frontend starts
            try:
                resp = await self._app._handle(request)
            except web.HTTPException as e:
                resp = e
            if resp is not None:
                if not resp.prepared:
                    await resp.prepare(request)
                await resp.write_eof()
        except (ConnectionError, StreamReset, asyncio.CancelledError):
            # StreamReset = client cancel/disconnect mid-response:
            # routine teardown, silenced by H2Server._run_stream
            raise
        except Exception:  # noqa: BLE001 — handler crash = 500 or RST
            log.exception("native h2 dispatch %s %s", req.method, req.path)
            if not req._sent_headers:
                await req.respond(500, b"internal error")
            else:
                await req._conn.send_rst(req._stream.sid, CANCEL)
                req._stream.fail(CANCEL)


class ApiFrontend:
    """One public listener: HTTP/2 served natively against the aiohttp
    app when one is provided, HTTP/1.1 bytes passed through to the
    internal listener (aiohttp's own parser/server)."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0, app=None):
        self.upstream_host = upstream_host
        self.upstream_port = upstream_port
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._native = app is not None
        if self._native:
            self._h2 = H2Server(NativeH2Dispatcher(app).handle)
        else:
            self._h2 = H2Server(self._forward)  # handle_connection only
        self._proxy_tasks: set = set()

    async def start(self) -> None:
        if not self._native:
            # the upstream session only backs the h1-per-stream forward
            # path; native mode proxies h1 with raw sockets and serves
            # h2 in-process
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0, keepalive_timeout=30.0)
            )
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    @property
    def addrs(self) -> List[str]:
        if self._server is None:
            return []
        return [
            f"{s.getsockname()[0]}:{s.getsockname()[1]}"
            for s in self._server.sockets
        ]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        await self._h2.stop()
        for t in list(self._proxy_tasks):
            t.cancel()
        if self._session is not None:
            await self._session.close()

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            buf = b""
            while len(buf) < len(PREFACE) and PREFACE.startswith(buf):
                chunk = await asyncio.wait_for(
                    reader.read(len(PREFACE) - len(buf)), 30.0
                )
                if not chunk:
                    writer.close()
                    return
                buf += chunk
        except (asyncio.TimeoutError, ConnectionError, OSError):
            writer.close()
            return
        if buf == PREFACE:
            await self._h2.handle_connection(reader, writer, preface_consumed=True)
        else:
            await self._proxy_h1(buf, reader, writer)

    # -- h1 pass-through ---------------------------------------------------

    async def _proxy_h1(
        self, head: bytes,
        reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
    ) -> None:
        """Raw byte pump: the sniffed prefix is replayed, then both
        directions stream until either side closes."""
        try:
            up_r, up_w = await asyncio.open_connection(
                self.upstream_host, self.upstream_port
            )
        except (ConnectionError, OSError):
            writer.close()
            return
        up_w.write(head)

        async def pump(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
            err = False
            try:
                while True:
                    data = await src.read(65536)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
            except (ConnectionError, OSError, asyncio.CancelledError):
                err = True
            finally:
                # clean EOF = the source half-closed its send side; pass
                # the half-close through (a client that shutdown(SHUT_WR)
                # after the request must still receive the streaming
                # response). Full close only on error.
                try:
                    if err or not dst.can_write_eof():
                        dst.close()
                    else:
                        dst.write_eof()
                except (ConnectionError, OSError):
                    pass

        t1 = asyncio.ensure_future(pump(reader, up_w))
        t2 = asyncio.ensure_future(pump(up_r, writer))
        self._proxy_tasks.update((t1, t2))
        try:
            await asyncio.gather(t1, t2, return_exceptions=True)
        finally:
            self._proxy_tasks.difference_update((t1, t2))
            for w in (up_w, writer):
                try:
                    w.close()
                except (ConnectionError, OSError):
                    pass

    # -- h2 stream forwarding ----------------------------------------------

    async def _forward(self, req: H2Request) -> None:
        """One h2 stream -> one upstream h1 request, streaming the
        response back as DATA frames (NDJSON streams stay live)."""
        assert self._session is not None
        body = await req.read_body()
        headers = {
            k: v for k, v in req.headers.items()
            if k not in _HOP_BY_HOP and k != "content-length"
        }
        url = (
            f"http://{self.upstream_host}:{self.upstream_port}{req.path}"
        )
        try:
            async with self._session.request(
                req.method, url, data=body if body else None,
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=None, connect=10.0),
            ) as resp:
                out_headers = {
                    k.lower(): v for k, v in resp.headers.items()
                    if k.lower() not in _HOP_BY_HOP
                }
                await req.send_headers(resp.status, out_headers)
                async for chunk in resp.content.iter_any():
                    if chunk:
                        await req.send_data(chunk)
                await req.send_data(b"", end_stream=True)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            log.debug("h2 forward failed %s %s: %s", req.method, req.path, e)
            if not req._sent_headers:
                await req.respond(502, b"upstream unavailable")
            else:
                # upstream died mid-stream: RST so the client's body
                # iterator errors and its reconnect logic kicks in —
                # never leave the stream open with no END_STREAM
                await req._conn.send_rst(req._stream.sid, CANCEL)
                req._stream.fail(CANCEL)

"""Dual-protocol API front-end: HTTP/2 + HTTP/1.1 on one port.

The reference API port speaks both protocols — hyper's auto-mode server
sniffs the 24-byte h2c client preface and its client is HTTP/2-only
(`klukai-client/src/lib.rs:33-47`).  This front-end reproduces that on
asyncio:

- each accepted connection is sniffed byte-by-byte against the preface:
  the instant the buffer diverges it is an HTTP/1.1 connection and the
  bytes are replayed into a raw TCP proxy to the internal aiohttp
  listener; a full preface match terminates HTTP/2 here
  (`net/h2.py`) and forwards each multiplexed stream as an HTTP/1.1
  request to the same internal listener;
- forwarding preserves the whole aiohttp route surface (authz, limits,
  metrics, NDJSON streaming) with no duplicated handler logic — response
  bodies stream frame-by-frame, so one h2 connection can carry live
  subscriptions next to queries, like the reference's multiplexed h2.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional

import aiohttp

from corrosion_tpu.net.h2 import CANCEL, PREFACE, H2Request, H2Server

log = logging.getLogger(__name__)

# hop-by-hop headers that must not cross the h1→h2 boundary (RFC 9113 §8.2.2)
_HOP_BY_HOP = {
    "connection", "keep-alive", "proxy-connection", "transfer-encoding",
    "upgrade", "te",
}


class ApiFrontend:
    """One public listener routing h2c and h1.1 to the internal listener."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0):
        self.upstream_host = upstream_host
        self.upstream_port = upstream_port
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._h2 = H2Server(self._forward)  # handle_connection only
        self._proxy_tasks: set = set()

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0, keepalive_timeout=30.0)
        )
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    @property
    def addrs(self) -> List[str]:
        if self._server is None:
            return []
        return [
            f"{s.getsockname()[0]}:{s.getsockname()[1]}"
            for s in self._server.sockets
        ]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        await self._h2.stop()
        for t in list(self._proxy_tasks):
            t.cancel()
        if self._session is not None:
            await self._session.close()

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            buf = b""
            while len(buf) < len(PREFACE) and PREFACE.startswith(buf):
                chunk = await asyncio.wait_for(
                    reader.read(len(PREFACE) - len(buf)), 30.0
                )
                if not chunk:
                    writer.close()
                    return
                buf += chunk
        except (asyncio.TimeoutError, ConnectionError, OSError):
            writer.close()
            return
        if buf == PREFACE:
            await self._h2.handle_connection(reader, writer, preface_consumed=True)
        else:
            await self._proxy_h1(buf, reader, writer)

    # -- h1 pass-through ---------------------------------------------------

    async def _proxy_h1(
        self, head: bytes,
        reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
    ) -> None:
        """Raw byte pump: the sniffed prefix is replayed, then both
        directions stream until either side closes."""
        try:
            up_r, up_w = await asyncio.open_connection(
                self.upstream_host, self.upstream_port
            )
        except (ConnectionError, OSError):
            writer.close()
            return
        up_w.write(head)

        async def pump(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
            err = False
            try:
                while True:
                    data = await src.read(65536)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
            except (ConnectionError, OSError, asyncio.CancelledError):
                err = True
            finally:
                # clean EOF = the source half-closed its send side; pass
                # the half-close through (a client that shutdown(SHUT_WR)
                # after the request must still receive the streaming
                # response). Full close only on error.
                try:
                    if err or not dst.can_write_eof():
                        dst.close()
                    else:
                        dst.write_eof()
                except (ConnectionError, OSError):
                    pass

        t1 = asyncio.ensure_future(pump(reader, up_w))
        t2 = asyncio.ensure_future(pump(up_r, writer))
        self._proxy_tasks.update((t1, t2))
        try:
            await asyncio.gather(t1, t2, return_exceptions=True)
        finally:
            self._proxy_tasks.difference_update((t1, t2))
            for w in (up_w, writer):
                try:
                    w.close()
                except (ConnectionError, OSError):
                    pass

    # -- h2 stream forwarding ----------------------------------------------

    async def _forward(self, req: H2Request) -> None:
        """One h2 stream -> one upstream h1 request, streaming the
        response back as DATA frames (NDJSON streams stay live)."""
        assert self._session is not None
        body = await req.read_body()
        headers = {
            k: v for k, v in req.headers.items()
            if k not in _HOP_BY_HOP and k != "content-length"
        }
        url = (
            f"http://{self.upstream_host}:{self.upstream_port}{req.path}"
        )
        try:
            async with self._session.request(
                req.method, url, data=body if body else None,
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=None, connect=10.0),
            ) as resp:
                out_headers = {
                    k.lower(): v for k, v in resp.headers.items()
                    if k.lower() not in _HOP_BY_HOP
                }
                await req.send_headers(resp.status, out_headers)
                async for chunk in resp.content.iter_any():
                    if chunk:
                        await req.send_data(chunk)
                await req.send_data(b"", end_stream=True)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            log.debug("h2 forward failed %s %s: %s", req.method, req.path, e)
            if not req._sent_headers:
                await req.respond(502, b"upstream unavailable")
            else:
                # upstream died mid-stream: RST so the client's body
                # iterator errors and its reconnect logic kicks in —
                # never leave the stream open with no END_STREAM
                await req._conn.send_rst(req._stream.sid, CANCEL)
                req._stream.fail(CANCEL)

"""HTTP plumbing for live queries and table updates.

Counterpart of `klukai-agent/src/api/public/pubsub.rs` (api_v1_subs
:699, api_v1_sub_by_id :38, catch_up_sub :387-651, NDJSON streaming
:818-980) and `api/public/update.rs:31-290`:

- `POST /v1/subscriptions` — params interpolated into the SQL
  (pubsub.rs:258-363), `SubsManager::get_or_insert`, response headers
  `corro-query-id` / `corro-query-hash`, NDJSON body: columns → rows
  (unless `skip_rows`) → eoq(change_id) → live change events;
- `GET /v1/subscriptions/{id}` — re-attach; `?from=<change_id>`
  replays the changes log (a pruned-away `from` is a 404: resubscribe
  anew), otherwise streams a fresh snapshot;
- `POST /v1/updates/{table}` — NotifyEvent NDJSON stream.

Event ordering: the subscriber queue is attached *before* the snapshot
or log replay is read, then live events with ids ≤ the replayed max are
dropped — every ChangeId is delivered exactly once, in order
(pubsub.rs:818-980 buffers for the same purpose).
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any, Optional

from aiohttp import web

from corrosion_tpu.api.types import (
    ev_columns,
    ev_eoq,
    ev_error,
    ev_lagging,
    ev_notify,
    ev_row,
    parse_statement,
)
from corrosion_tpu.pubsub.fanout import SinkClosed, StreamSink, SubLagging
from corrosion_tpu.pubsub.matcher import MatcherError, SubDead
from corrosion_tpu.pubsub.parse import ParseError


def expand_sql(stmt) -> str:
    """Interpolate params into the SQL text so identical subscriptions
    dedupe on the final query (pubsub.rs:258-363 uses sqlite's
    expanded_sql). Token-level substitution: placeholders inside string
    literals or prefix-colliding names are never touched."""
    from corrosion_tpu.pubsub.parse import tokenize, _join_tokens

    if not stmt.params and not stmt.named_params:
        return stmt.query
    tokens = tokenize(stmt.query)
    # a bare key binds any placeholder style (sqlite accepts :k, @k, $k)
    named = {}
    for k, v in (stmt.named_params or {}).items():
        if k[0] in ":@$":
            named[k] = v
        else:
            for prefix in ":@$":
                named[prefix + k] = v
    out = []
    params = stmt.params or []
    # sqlite ?N semantics: ?N binds params[N-1]; bare ? binds one past the
    # largest index assigned so far
    max_idx = 0
    for tok in tokens:
        if tok.kind == "param":
            if tok.text.startswith("?"):
                idx = int(tok.text[1:]) if len(tok.text) > 1 else max_idx + 1
                if not 1 <= idx <= len(params):
                    raise ParseError(
                        f"parameter {tok.text} out of range"
                        f" (got {len(params)} params)"
                    )
                max_idx = max(max_idx, idx)
                out.append(type(tok)("num", _literal(params[idx - 1])))
                continue
            if tok.text in named:
                out.append(type(tok)("num", _literal(named[tok.text])))
                continue
            raise ParseError(f"unbound parameter {tok.text}")
        out.append(tok)
    if params and max_idx != len(params):
        raise ParseError(
            f"statement uses {max_idx} positional params,"
            f" got {len(params)}"
        )
    return _join_tokens(out)


def _literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return "X'" + bytes(v).hex() + "'"
    return "'" + str(v).replace("'", "''") + "'"


def _admission_reject(api) -> Optional[web.Response]:
    """[subs] max_streams admission control (r16): a node at its stream
    ceiling refuses NEW streams with a typed 503 rather than admitting
    one it would only serve degraded — the client sees a retryable,
    machine-readable rejection, never a half-dead stream."""
    reason = api.subs.admission_reject()
    if reason is None:
        return None
    return web.json_response(
        {"error": reason, "code": "subs_admission"}, status=503
    )


async def handle_subscribe(api, request: web.Request) -> web.StreamResponse:
    try:
        stmt = parse_statement(await request.json())
        sql = expand_sql(stmt)
    except (ValueError, TypeError, ParseError) as e:
        return web.json_response({"error": str(e)}, status=400)

    try:
        skip_rows, from_id = _stream_params(request)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)

    rejected = _admission_reject(api)
    if rejected is not None:
        return rejected

    try:
        # the lease pins the (possibly deduped) matcher against the
        # linger reaper until our sink attaches
        handle, _created = await api.subs.get_or_insert(sql, lease=True)
    except ParseError as e:
        return web.json_response({"error": str(e)}, status=400)

    return await _stream_sub(request, handle, skip_rows, from_id, api.subs)


async def handle_subscription_by_id(
    api, request: web.Request
) -> web.StreamResponse:
    sub_id = request.match_info["id"]
    handle = api.subs.get(sub_id)
    if handle is None:
        return web.json_response({"error": "unknown subscription"}, status=404)
    if handle.error is not None:
        # dead matcher pending removal: re-attaching would hang forever
        return web.json_response({"error": handle.error}, status=404)
    rejected = _admission_reject(api)
    if rejected is not None:
        return rejected
    handle.lease()  # pin against the linger reaper until the sink attaches
    try:
        skip_rows, from_id = _stream_params(request)
    except ValueError as e:
        handle.release_lease()
        return web.json_response({"error": str(e)}, status=400)
    return await _stream_sub(request, handle, skip_rows, from_id, api.subs)


def _stream_params(request: web.Request):
    skip_rows = request.query.get("skip_rows", "") in ("true", "1")
    from_raw = request.query.get("from")
    try:
        from_id = int(from_raw) if from_raw is not None else None
    except ValueError:
        raise ValueError(f"malformed 'from' change id: {from_raw!r}")
    return skip_rows, from_id


async def _stream_sub_queue(
    request: web.Request,
    handle,
    skip_rows: bool,
    from_id: Optional[int],
) -> web.StreamResponse:
    """The r10 reference path: one drain task + one queue per stream.
    Kept verbatim behind `[subs] fanout="queue"` as the A/B baseline
    the SUBS_SCALE bench measures the shared writer against, and as the
    operational rollback lever."""
    import time

    resp = web.StreamResponse(
        headers={
            "content-type": "application/x-ndjson",
            "corro-query-id": handle.id,
            "corro-query-hash": handle.hash,
        }
    )
    q = None
    try:
        await resp.prepare(request)
        # attach FIRST so no event can fall between snapshot and live
        q = handle.attach()
    finally:
        handle.release_lease()

    async def line(s: str) -> None:
        await resp.write((s + "\n").encode())

    try:
        replayed_max = 0
        if from_id is not None:
            try:
                evs = await asyncio.to_thread(handle.changes_since, from_id)
            except MatcherError as e:
                await line(ev_error(str(e)))
                await resp.write_eof()
                return resp
            if evs is None:
                await line(
                    ev_error(
                        f"change id {from_id} is no longer in the log;"
                        " resubscribe anew"
                    )
                )
                await resp.write_eof()
                return resp
            for ev in evs:
                await line(ev.line())
                replayed_max = ev.change_id
        else:
            await line(ev_columns(handle.columns))
            rows, snap_id = await asyncio.to_thread(handle.matcher.snapshot)
            if not skip_rows:
                for rowid, values in rows:
                    await line(ev_row(rowid, values))
            await line(ev_eoq(0.0, snap_id if snap_id else None))
            replayed_max = snap_id

        while True:
            item = await q.get()
            # greedy drain: several batches coalesce into one socket
            # write under fan-out pressure (pubsub.rs:818-980)
            pending = [item]
            while True:
                try:
                    pending.append(q.get_nowait())
                except asyncio.QueueEmpty:
                    break
            chunks = []
            shipped = []
            terminal = None
            for item in pending:
                if item is None or isinstance(item, SubDead):
                    terminal = item
                    break
                if item and item[0].change_id > replayed_max:
                    chunks.append(item.payload())
                    shipped.append(item)
                else:
                    lines = [
                        ev.line()
                        for ev in item
                        if ev.change_id > replayed_max
                    ]
                    if lines:
                        chunks.append(("\n".join(lines) + "\n").encode())
                        shipped.append(item)
            if chunks:
                await resp.write(b"".join(chunks))
                from corrosion_tpu.runtime.latency import e2e_observe

                now = time.time()
                for item in shipped:
                    ew = getattr(item, "event_wall", None)
                    if ew is not None:
                        e2e_observe("deliver", now - ew)
                    og = getattr(item, "origin", None)
                    if og is not None:
                        e2e_observe("total", now - og)
            if terminal is None:
                continue
            if isinstance(terminal, SubDead):  # matcher died
                await line(ev_error(f"subscription failed: {terminal.error}"))
            else:  # clean manager stop
                await line(ev_error("subscription closed"))
            break
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        if q is not None:
            handle.detach(q)
    with _suppress_conn_err():
        await resp.write_eof()
    return resp


class _AioStreamSink(StreamSink):
    """h1 flavor: the aiohttp StreamResponse over a real TCP transport
    (the internal listener behind the front-end's byte-pump).  Writes
    go straight to the transport with the response's chunked framing
    applied — synchronous, never awaiting aiohttp's drain helper, so a
    paused (stalled-reader) transport can only clog THIS sink, never
    the shared writer task.  Lag lives in the transport buffer:
    `writable()` gates on its size against the sink's lag bound."""

    def __init__(self, resp, max_lag_bytes: int, max_lag_batches: int):
        super().__init__(max_lag_bytes, max_lag_batches)
        self._writer = resp._payload_writer
        self._chunked = bool(getattr(self._writer, "chunked", False))

    def _transport(self):
        tr = getattr(self._writer, "transport", None)
        if tr is None or tr.is_closing():
            raise SinkClosed("client transport closed")
        return tr

    def writable(self) -> bool:
        return (
            self._transport().get_write_buffer_size() <= self.max_lag_bytes
        )

    def write_some(self, data: bytes) -> int:
        tr = self._transport()
        if self._chunked:
            tr.write(b"%x\r\n%s\r\n" % (len(data), data))
        else:
            tr.write(data)
        self._writer.output_size += len(data)
        return len(data)


class _H2StreamSink(StreamSink):
    """Native-h2 flavor: DATA frames written synchronously up to the
    open flow-control windows (`H2Connection.send_data_nowait`).  A
    stalled client stops crediting its windows, so its lag surfaces
    within one window's worth of bytes — clog, then shed."""

    def __init__(self, req, max_lag_bytes: int, max_lag_batches: int):
        super().__init__(max_lag_bytes, max_lag_batches)
        self._conn = req._conn
        self._stream = req._stream

    def writable(self) -> bool:
        conn, stream = self._conn, self._stream
        if conn.closed or stream.reset_code is not None:
            raise SinkClosed("h2 stream closed")
        tr = conn.writer.transport
        if tr is None or tr.is_closing():
            raise SinkClosed("h2 transport closed")
        if tr.get_write_buffer_size() > self.max_lag_bytes:
            return False
        return conn.send_window > 0 and stream.send_window > 0

    def write_some(self, data: bytes) -> int:
        from corrosion_tpu.net.h2 import StreamReset

        try:
            return self._conn.send_data_nowait(self._stream.sid, data)
        except StreamReset as e:
            raise SinkClosed(str(e)) from e


def _make_sink(resp: web.StreamResponse, cfg) -> StreamSink:
    w = resp._payload_writer
    if hasattr(w, "_req"):  # api/h2front._H2PayloadWriter (native h2)
        return _H2StreamSink(w._req, cfg.max_lag_bytes, cfg.max_lag_batches)
    return _AioStreamSink(resp, cfg.max_lag_bytes, cfg.max_lag_batches)


async def _stream_sub(
    request: web.Request,
    handle,
    skip_rows: bool,
    from_id: Optional[int],
    subs,
) -> web.StreamResponse:
    """Serve one subscription stream.  r16: the stream's live tail is
    delivered by the manager's shared FanoutWriter through a per-stream
    sink — this handler streams the snapshot/replay phase, releases the
    sink into live mode, then PARKS on `sink.done` (no per-batch task
    wakeups) until a terminal: clean stop, matcher death, laggard shed,
    or peer disconnect.  `[subs] fanout="queue"` keeps the r10
    per-stream drain loop as the reference path (bench A/B + rollback
    lever; no shedding there — a stalled consumer stalls only itself)."""
    if subs.cfg.fanout == "queue":
        return await _stream_sub_queue(request, handle, skip_rows, from_id)
    resp = web.StreamResponse(
        headers={
            "content-type": "application/x-ndjson",
            "corro-query-id": handle.id,
            "corro-query-hash": handle.hash,
        }
    )
    sink = None
    try:
        await resp.prepare(request)
        # attach FIRST (in HOLD mode) so no event can fall between
        # snapshot and live tail; the lease taken at lookup is released
        # now that the sink holds a ref
        sink = _make_sink(resp, subs.cfg)
        handle.attach_sink(sink)
    finally:
        handle.release_lease()

    async def line(s: str) -> None:
        await resp.write((s + "\n").encode())

    try:
        replayed_max = 0
        if from_id is not None:
            try:
                evs = await asyncio.to_thread(handle.changes_since, from_id)
            except MatcherError as e:
                # dead matcher: typed terminal error, not a replay hang
                await line(ev_error(str(e)))
                await resp.write_eof()
                return resp
            if evs is None:
                await line(
                    ev_error(
                        f"change id {from_id} is no longer in the log;"
                        " resubscribe anew"
                    )
                )
                await resp.write_eof()
                return resp
            for ev in evs:
                await line(ev.line())
                replayed_max = ev.change_id
        else:
            await line(ev_columns(handle.columns))
            # rows + change id read atomically: no diff can land between
            rows, snap_id = await asyncio.to_thread(handle.matcher.snapshot)
            if not skip_rows:
                for rowid, values in rows:
                    await line(ev_row(rowid, values))
            await line(ev_eoq(0.0, snap_id if snap_id else None))
            replayed_max = snap_id

        sink.release(replayed_max)
        outcome = await sink.done
        if isinstance(outcome, SubLagging):
            # typed shed frame; the write itself is bounded — a shed
            # sink's transport may be the thing that stopped draining
            with contextlib.suppress(
                asyncio.TimeoutError, ConnectionError
            ):
                await asyncio.wait_for(
                    line(ev_lagging(outcome.lag_bytes, outcome.lag_batches)),
                    2.0,
                )
        elif isinstance(outcome, SubDead):  # matcher died
            await line(ev_error(f"subscription failed: {outcome.error}"))
        elif outcome is None:  # clean manager stop
            await line(ev_error("subscription closed"))
        # SinkClosed outcome: the peer is gone — nothing left to tell it
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        if sink is not None:
            handle.detach_sink(sink)
    with _suppress_conn_err():
        with contextlib.suppress(asyncio.TimeoutError):
            # bounded: a shed laggard's flow-control window may never
            # reopen for the END_STREAM/terminal chunk
            await asyncio.wait_for(resp.write_eof(), 5.0)
    return resp


async def handle_updates(api, request: web.Request) -> web.StreamResponse:
    table = request.match_info["table"]
    try:
        handle, _created = await api.updates.get_or_insert(table)
    except KeyError as e:
        return web.json_response({"error": str(e)}, status=404)

    resp = web.StreamResponse(
        headers={"content-type": "application/x-ndjson"}
    )
    await resp.prepare(request)
    q = handle.attach()
    try:
        while True:
            ev = await q.get()
            if ev is None:  # handle stopped
                break
            kind, pk_values = ev
            await resp.write((ev_notify(kind, pk_values) + "\n").encode())
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        handle.detach(q)
    with _suppress_conn_err():
        await resp.write_eof()
    return resp


def _suppress_conn_err():
    return contextlib.suppress(ConnectionResetError, RuntimeError)

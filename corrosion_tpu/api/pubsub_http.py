"""HTTP plumbing for live queries and table updates.

Counterpart of `klukai-agent/src/api/public/pubsub.rs` (api_v1_subs
:699, api_v1_sub_by_id :38, catch_up_sub :387-651, NDJSON streaming
:818-980) and `api/public/update.rs:31-290`:

- `POST /v1/subscriptions` — params interpolated into the SQL
  (pubsub.rs:258-363), `SubsManager::get_or_insert`, response headers
  `corro-query-id` / `corro-query-hash`, NDJSON body: columns → rows
  (unless `skip_rows`) → eoq(change_id) → live change events;
- `GET /v1/subscriptions/{id}` — re-attach; `?from=<change_id>`
  replays the changes log (a pruned-away `from` is a 404: resubscribe
  anew), otherwise streams a fresh snapshot;
- `POST /v1/updates/{table}` — NotifyEvent NDJSON stream.

Event ordering: the subscriber queue is attached *before* the snapshot
or log replay is read, then live events with ids ≤ the replayed max are
dropped — every ChangeId is delivered exactly once, in order
(pubsub.rs:818-980 buffers for the same purpose).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, List, Optional

from aiohttp import web

from corrosion_tpu.api.types import (
    ev_columns,
    ev_eoq,
    ev_error,
    ev_notify,
    ev_row,
    parse_statement,
)
from corrosion_tpu.pubsub.matcher import MatcherError, SubDead
from corrosion_tpu.pubsub.parse import ParseError


def expand_sql(stmt) -> str:
    """Interpolate params into the SQL text so identical subscriptions
    dedupe on the final query (pubsub.rs:258-363 uses sqlite's
    expanded_sql). Token-level substitution: placeholders inside string
    literals or prefix-colliding names are never touched."""
    from corrosion_tpu.pubsub.parse import tokenize, _join_tokens

    if not stmt.params and not stmt.named_params:
        return stmt.query
    tokens = tokenize(stmt.query)
    # a bare key binds any placeholder style (sqlite accepts :k, @k, $k)
    named = {}
    for k, v in (stmt.named_params or {}).items():
        if k[0] in ":@$":
            named[k] = v
        else:
            for prefix in ":@$":
                named[prefix + k] = v
    out = []
    params = stmt.params or []
    # sqlite ?N semantics: ?N binds params[N-1]; bare ? binds one past the
    # largest index assigned so far
    max_idx = 0
    for tok in tokens:
        if tok.kind == "param":
            if tok.text.startswith("?"):
                idx = int(tok.text[1:]) if len(tok.text) > 1 else max_idx + 1
                if not 1 <= idx <= len(params):
                    raise ParseError(
                        f"parameter {tok.text} out of range"
                        f" (got {len(params)} params)"
                    )
                max_idx = max(max_idx, idx)
                out.append(type(tok)("num", _literal(params[idx - 1])))
                continue
            if tok.text in named:
                out.append(type(tok)("num", _literal(named[tok.text])))
                continue
            raise ParseError(f"unbound parameter {tok.text}")
        out.append(tok)
    if params and max_idx != len(params):
        raise ParseError(
            f"statement uses {max_idx} positional params,"
            f" got {len(params)}"
        )
    return _join_tokens(out)


def _literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return "X'" + bytes(v).hex() + "'"
    return "'" + str(v).replace("'", "''") + "'"


async def handle_subscribe(api, request: web.Request) -> web.StreamResponse:
    try:
        stmt = parse_statement(await request.json())
        sql = expand_sql(stmt)
    except (ValueError, TypeError, ParseError) as e:
        return web.json_response({"error": str(e)}, status=400)

    try:
        skip_rows, from_id = _stream_params(request)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)

    try:
        handle, _created = await api.subs.get_or_insert(sql)
    except ParseError as e:
        return web.json_response({"error": str(e)}, status=400)

    return await _stream_sub(request, handle, skip_rows, from_id)


async def handle_subscription_by_id(
    api, request: web.Request
) -> web.StreamResponse:
    sub_id = request.match_info["id"]
    handle = api.subs.get(sub_id)
    if handle is None:
        return web.json_response({"error": "unknown subscription"}, status=404)
    if handle.error is not None:
        # dead matcher pending removal: re-attaching would hang forever
        return web.json_response({"error": handle.error}, status=404)
    try:
        skip_rows, from_id = _stream_params(request)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return await _stream_sub(request, handle, skip_rows, from_id)


def _stream_params(request: web.Request):
    skip_rows = request.query.get("skip_rows", "") in ("true", "1")
    from_raw = request.query.get("from")
    try:
        from_id = int(from_raw) if from_raw is not None else None
    except ValueError:
        raise ValueError(f"malformed 'from' change id: {from_raw!r}")
    return skip_rows, from_id


async def _stream_sub(
    request: web.Request,
    handle,
    skip_rows: bool,
    from_id: Optional[int],
) -> web.StreamResponse:
    resp = web.StreamResponse(
        headers={
            "content-type": "application/x-ndjson",
            "corro-query-id": handle.id,
            "corro-query-hash": handle.hash,
        }
    )
    await resp.prepare(request)

    async def line(s: str) -> None:
        await resp.write((s + "\n").encode())

    # attach FIRST so no event can fall between snapshot and live tail
    q = handle.attach()
    try:
        replayed_max = 0
        if from_id is not None:
            try:
                evs = await asyncio.to_thread(handle.changes_since, from_id)
            except MatcherError as e:
                # dead matcher: typed terminal error, not a replay hang
                await line(ev_error(str(e)))
                await resp.write_eof()
                return resp
            if evs is None:
                await line(
                    ev_error(
                        f"change id {from_id} is no longer in the log;"
                        " resubscribe anew"
                    )
                )
                await resp.write_eof()
                return resp
            for ev in evs:
                await line(ev.line())
                replayed_max = ev.change_id
        else:
            await line(ev_columns(handle.columns))
            # rows + change id read atomically: no diff can land between
            rows, snap_id = await asyncio.to_thread(handle.matcher.snapshot)
            if not skip_rows:
                for rowid, values in rows:
                    await line(ev_row(rowid, values))
            await line(ev_eoq(0.0, snap_id if snap_id else None))
            replayed_max = snap_id

        while True:
            item = await q.get()
            # greedy drain: queue items are whole diff batches (lists);
            # under fan-out pressure several batches coalesce into one
            # socket write, so per-event cost on this loop is a cached
            # string append + join (the reference buffers the same way,
            # pubsub.rs:818-980)
            pending = [item]
            while True:
                try:
                    pending.append(q.get_nowait())
                except asyncio.QueueEmpty:
                    break
            chunks: List[bytes] = []
            shipped: List[Any] = []
            terminal = None
            for item in pending:
                if item is None or isinstance(item, SubDead):
                    terminal = item
                    break
                if item and item[0].change_id > replayed_max:
                    # whole batch is post-replay (events are id-ordered):
                    # ship the ONE payload every subscriber shares
                    chunks.append(item.payload())
                    shipped.append(item)
                else:
                    lines = [
                        ev.line()
                        for ev in item
                        if ev.change_id > replayed_max
                    ]
                    if lines:
                        chunks.append(("\n".join(lines) + "\n").encode())
                        shipped.append(item)
            if chunks:
                await resp.write(b"".join(chunks))
                # r11 latency plane: event→delivered per shipped batch,
                # and origin-commit→delivered when the origin stamp
                # traveled the whole path (skew-clamped: the origin may
                # be another machine's clock)
                from corrosion_tpu.runtime.latency import e2e_observe

                now = time.time()
                for item in shipped:
                    ew = getattr(item, "event_wall", None)
                    if ew is not None:
                        e2e_observe("deliver", now - ew)
                    og = getattr(item, "origin", None)
                    if og is not None:
                        e2e_observe("total", now - og)
            if terminal is None:
                continue
            if isinstance(terminal, SubDead):  # matcher died
                await line(ev_error(f"subscription failed: {terminal.error}"))
            else:  # clean manager stop
                await line(ev_error("subscription closed"))
            break
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        handle.detach(q)
    with _suppress_conn_err():
        await resp.write_eof()
    return resp


async def handle_updates(api, request: web.Request) -> web.StreamResponse:
    table = request.match_info["table"]
    try:
        handle, _created = await api.updates.get_or_insert(table)
    except KeyError as e:
        return web.json_response({"error": str(e)}, status=404)

    resp = web.StreamResponse(
        headers={"content-type": "application/x-ndjson"}
    )
    await resp.prepare(request)
    q = handle.attach()
    try:
        while True:
            ev = await q.get()
            if ev is None:  # handle stopped
                break
            kind, pk_values = ev
            await resp.write((ev_notify(kind, pk_values) + "\n").encode())
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        handle.detach(q)
    with _suppress_conn_err():
        await resp.write_eof()
    return resp


def _suppress_conn_err():
    import contextlib

    return contextlib.suppress(ConnectionResetError, RuntimeError)

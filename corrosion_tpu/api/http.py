"""HTTP server: transactions, queries, migrations, table_stats (+ pubsub
routes once a SubsManager/UpdatesManager is attached).

Counterpart of the axum router in `klukai-agent/src/agent/util.rs:181-351`:
  - POST /v1/transactions   (concurrency 128)
  - POST /v1/queries        (streams NDJSON QueryEvents, 128)
  - POST /v1/migrations     (concurrency 4)
  - POST /v1/table_stats    (concurrency 4)
  - POST /v1/subscriptions, GET /v1/subscriptions/{id}
  - POST /v1/updates/{table}
  - GET  /v1/status         (r7: cluster status plane — one JSON
    snapshot of membership census, kernel event telemetry, loop lag and
    sync backlog, read non-mutatingly from the shared registry; the
    machine-readable sibling of /metrics for dashboards and obs_report)
  - GET  /v1/flight         (r8: the flight-recorder timeline plane —
    the last-K per-tick frames stitched from the device rings, the
    tick-RESOLVED sibling of /v1/status's cumulative totals)
  - bearer-token authz middleware (`util.rs:330-351`), load-shed → 503
"""

from __future__ import annotations

import asyncio
import contextlib
import sqlite3
import time
from typing import Any, List, Optional

from aiohttp import web

from corrosion_tpu.agent.handle import Agent
from corrosion_tpu.agent.run import make_broadcastable_changes
from corrosion_tpu.api.types import (
    Statement,
    dump_value,
    ev_columns,
    ev_eoq,
    ev_error,
    ev_row,
    exec_response,
    parse_statement,
)
from corrosion_tpu.runtime.metrics import (
    METRICS,
    kernel_event_totals,
)
from corrosion_tpu.store.schema import SchemaError


def _held_versions(agent: Agent) -> int:
    """Versions this node holds (the catch-up census's local half) —
    host-state reads only, same contract as the rest of /v1/status."""
    from corrosion_tpu.sync import held_total

    return held_total(agent.bookie)


def _trace_census() -> dict:
    """The /v1/status `traces` block: tail-sampler occupancy + keep/drop
    totals (a locked-copy read, poll-safe like the rest of the plane)."""
    from corrosion_tpu.runtime import tracestore

    st = tracestore.store()
    return st.census() if st is not None else {"enabled": False}


class _Limit:
    """Load-shedding concurrency limit: full ⇒ 503 (util.rs:181-328)."""

    def __init__(self, n: int):
        self._sem = asyncio.Semaphore(n)

    async def __aenter__(self):
        if self._sem.locked():
            raise web.HTTPServiceUnavailable(text="overloaded")
        await self._sem.acquire()

    async def __aexit__(self, *exc):
        self._sem.release()


def _profile_census() -> dict:
    from corrosion_tpu.runtime import profiler

    prof = profiler.get()
    return prof.census() if prof is not None else {"enabled": False}


class ApiServer:
    def __init__(self, agent: Agent, subs=None, updates=None):
        self.agent = agent
        self.subs = subs if subs is not None else agent.subs
        self.updates = updates if updates is not None else agent.updates
        self._tx_limit = _Limit(128)
        self._query_limit = _Limit(128)
        self._slow_limit = _Limit(4)
        self._runner: Optional[web.AppRunner] = None
        self.addrs: List[str] = []
        self._fronts: list = []

    def build_app(self) -> web.Application:
        app = web.Application(middlewares=[self._metrics_mw, self._authz])
        app.router.add_post("/v1/transactions", self.h_transactions)
        app.router.add_post("/v1/queries", self.h_queries)
        app.router.add_post("/v1/migrations", self.h_migrations)
        app.router.add_post("/v1/table_stats", self.h_table_stats)
        app.router.add_post("/v1/subscriptions", self.h_subscribe)
        app.router.add_get("/v1/subscriptions/{id}", self.h_subscription_by_id)
        app.router.add_post("/v1/updates/{table}", self.h_updates)
        app.router.add_get("/v1/status", self.h_status)
        app.router.add_get("/v1/flight", self.h_flight)
        app.router.add_get("/v1/slo", self.h_slo)
        app.router.add_get("/v1/cluster", self.h_cluster)
        app.router.add_get("/v1/traces", self.h_traces)
        app.router.add_get("/v1/alerts", self.h_alerts)
        app.router.add_get("/v1/remediation", self.h_remediation)
        app.router.add_get("/v1/profile", self.h_profile)
        return app

    async def start(self) -> None:
        # short shutdown grace: live NDJSON subscription streams otherwise
        # hold the runner open indefinitely on cleanup
        self._runner = web.AppRunner(self.build_app(), shutdown_timeout=2.0)
        await self._runner.setup()
        # the aiohttp app binds one internal loopback port serving the
        # HTTP/1.1 side; every public bind addr gets a dual-protocol
        # front-end (api/h2front.py) — the reference's hyper auto-mode
        # server on one port.  HTTP/2 is served NATIVELY against the
        # same Application (route resolution + middleware chain +
        # streaming responses as h2 frames); only h1 bytes take the
        # loopback pass-through to aiohttp's own parser.
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        internal_port = site._server.sockets[0].getsockname()[1]
        from corrosion_tpu.api.h2front import ApiFrontend

        for bind in self.agent.config.api.bind_addr:
            host, _, port = bind.rpartition(":")
            front = ApiFrontend(
                "127.0.0.1", internal_port,
                host=host or "127.0.0.1", port=int(port),
                app=self._runner.app,
            )
            await front.start()
            self._fronts.append(front)
            self.addrs.extend(front.addrs)

    async def stop(self) -> None:
        # end live subscription/update streams first (their handlers block
        # on queue.get() until a None sentinel arrives), then tear down
        if self.subs is not None:
            await self.subs.stop_all()
        if self.updates is not None:
            await self.updates.stop_all()
        for front in self._fronts:
            await front.stop()
        self._fronts.clear()
        if self._runner is not None:
            await self._runner.cleanup()

    # -- middleware --------------------------------------------------------

    @web.middleware
    async def _metrics_mw(self, request: web.Request, handler):
        """Per-endpoint request counters + latency histograms (the
        reference exports these via axum/metrics middleware)."""
        start = time.monotonic()
        # canonical route template, NOT the raw path: parameterized
        # routes (/v1/subscriptions/{id}) and unauthenticated path spray
        # must not mint unbounded metric label values
        resource = request.match_info.route.resource if request.match_info else None
        endpoint = resource.canonical if resource is not None else "unmatched"
        # BaseException default: a handler cancelled mid-request (agent
        # restart under churn — the r18 chaos matrix's churn-storm
        # scenario found this) produces NO response and NO Exception,
        # and an unbound `status` here turned the clean CancelledError
        # into an UnboundLocalError in the finally
        status: object = "cancelled"
        try:
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        except Exception:
            status = 500
            raise
        finally:
            METRICS.counter(
                "corro.api.requests", endpoint=endpoint, status=str(status)
            ).inc()
            METRICS.histogram(
                "corro.api.request.seconds", endpoint=endpoint
            ).observe(time.monotonic() - start)

    @web.middleware
    async def _authz(self, request: web.Request, handler):
        expected = self.agent.config.api.authz_bearer
        if expected:
            got = request.headers.get("Authorization", "")
            if got != f"Bearer {expected}":
                raise web.HTTPUnauthorized(text="invalid bearer token")
        return await handler(request)

    # -- handlers ----------------------------------------------------------

    @staticmethod
    def _timeout_param(request: web.Request) -> Optional[float]:
        """`?timeout=<seconds>` (TimeoutParams, api/public/mod.rs:203,525):
        bounds statement runtime; overruns are interrupted server-side."""
        raw = request.query.get("timeout")
        if raw is None:
            return None
        try:
            t = float(raw)
        except ValueError:
            return None
        return t if t > 0 else None

    async def h_transactions(self, request: web.Request) -> web.Response:
        async with self._tx_limit:
            start = time.monotonic()
            timeout = self._timeout_param(request)
            try:
                body = await request.json()
                stmts = [parse_statement(s) for s in body]
            except (ValueError, TypeError) as e:
                return web.json_response(
                    {"results": [{"error": str(e)}], "time": 0.0},
                    status=400,
                )

            results: List[dict] = []

            def apply(tx) -> List[Any]:
                # overrunning statements are interrupted via the store
                # watchdog (InterruptibleTransaction analog) and surface
                # as an 'interrupted' sqlite error → 400
                guard = (
                    self.agent.store.interrupt_after(timeout)
                    if timeout
                    else contextlib.nullcontext()
                )
                out = []
                with guard:
                    for stmt in stmts:
                        t0 = time.monotonic()
                        n = _execute_stmt(tx, stmt)
                        out.append(
                            {
                                "rows_affected": n,
                                "time": time.monotonic() - t0,
                            }
                        )
                return out

            try:
                res = await make_broadcastable_changes(self.agent, apply)
            except sqlite3.Error as e:
                return web.json_response(
                    {"results": [{"error": str(e)}], "time": 0.0},
                    status=400,
                )
            results = res.results
            return web.json_response(
                exec_response(
                    results,
                    time.monotonic() - start,
                    res.version or None,
                    str(self.agent.actor_id),
                )
            )

    async def h_queries(self, request: web.Request) -> web.StreamResponse:
        async with self._query_limit:
            timeout = self._timeout_param(request)
            try:
                stmt = parse_statement(await request.json())
            except (ValueError, TypeError) as e:
                return web.json_response({"error": str(e)}, status=400)

            resp = web.StreamResponse(
                headers={"content-type": "application/x-ndjson"}
            )
            await resp.prepare(request)
            start = time.monotonic()
            loop = asyncio.get_running_loop()

            def run_query():
                import threading

                from corrosion_tpu.runtime.trace import timed_query

                with self.agent.store.pooled_read() as conn:
                    # ?timeout= interrupt (mod.rs:336: "sql call took more
                    # than {timeout}, interrupting"). disarm-before-fire
                    # is lock-checked so a timer firing as the query
                    # finishes can never interrupt the pool's NEXT user.
                    lk, live = threading.Lock(), [True]
                    timer = None
                    if timeout:
                        def fire():
                            with lk:
                                if live[0]:
                                    conn.interrupt()
                        timer = threading.Timer(timeout, fire)
                        timer.daemon = True
                        timer.start()
                    try:
                        with timed_query(stmt.query, shape="query:api"):
                            cur = conn.execute(
                                stmt.query, _bind_params(stmt)
                            )
                        cols = (
                            [d[0] for d in cur.description]
                            if cur.description
                            else []
                        )
                        rows = cur.fetchall()
                        return cols, rows
                    finally:
                        with lk:
                            live[0] = False
                        if timer is not None:
                            timer.cancel()

            try:
                cols, rows = await loop.run_in_executor(None, run_query)
                METRICS.counter("corro.api.queries.count").inc()
                METRICS.histogram(
                    "corro.api.queries.processing.time.seconds"
                ).observe(time.monotonic() - start)
                await resp.write((ev_columns(cols) + "\n").encode())
                for i, row in enumerate(rows):
                    line = ev_row(i + 1, [row[k] for k in row.keys()])
                    await resp.write((line + "\n").encode())
                await resp.write(
                    (ev_eoq(time.monotonic() - start) + "\n").encode()
                )
            except sqlite3.Error as e:
                await resp.write((ev_error(str(e)) + "\n").encode())
            await resp.write_eof()
            return resp

    async def h_migrations(self, request: web.Request) -> web.Response:
        async with self._slow_limit:
            start = time.monotonic()
            try:
                body = await request.json()
                sql = "\n".join(body) if isinstance(body, list) else str(body)
            except ValueError as e:
                return web.json_response(
                    {"results": [{"error": str(e)}], "time": 0.0}, status=400
                )

            def apply():
                self.agent.store.apply_schema_sql(sql)

            try:
                async with self.agent.write_gate.priority():
                    await asyncio.get_running_loop().run_in_executor(
                        None, apply
                    )
            except (SchemaError, sqlite3.Error) as e:
                return web.json_response(
                    {"results": [{"error": str(e)}], "time": 0.0}, status=400
                )
            return web.json_response(
                exec_response(
                    [{"rows_affected": 0, "time": 0.0}],
                    time.monotonic() - start,
                    None,
                    str(self.agent.actor_id),
                )
            )

    async def h_table_stats(self, request: web.Request) -> web.Response:
        async with self._slow_limit:
            try:
                body = await request.json()
                tables = body.get("tables") if isinstance(body, dict) else None
            except ValueError:
                tables = None
            if not tables:
                tables = list(self.agent.store.schema.tables)

            def stats():
                with self.agent.store.pooled_read() as conn:
                    total = 0
                    invalid = []
                    for t in tables:
                        if t not in self.agent.store.schema.tables:
                            continue
                        n = conn.execute(
                            f'SELECT COUNT(*) AS n FROM "{t}"'
                        ).fetchone()["n"]
                        total += n
                        clock_n = conn.execute(
                            "SELECT COUNT(DISTINCT pk) AS n FROM"
                            f' "{t}__crdt_clock"'
                        ).fetchone()["n"]
                        if clock_n > n:
                            invalid.append(t)
                    return total, invalid

            total, invalid = await asyncio.get_running_loop().run_in_executor(
                None, stats
            )
            return web.json_response(
                {"total_row_count": total, "invalid_tables": invalid}
            )

    async def h_status(self, request: web.Request) -> web.Response:
        """Cluster status plane: one JSON snapshot of what an operator
        asks first — who is in the cluster, what the kernels did, is the
        event loop healthy, is sync keeping up.  Every value is either
        host state readable without I/O or a non-mutating registry peek
        (`Registry.snapshot`), so the endpoint is safe to poll."""
        agent = self.agent
        from corrosion_tpu.agent.membership import MemberState

        by_state = {s.name: 0 for s in MemberState}
        # worker-thread rule from agent_metrics.collect_once: copy the
        # dict under the GIL before iterating
        for m in list(agent.membership.members.values()):
            by_state[m.state.name] = by_state.get(m.state.name, 0) + 1

        # one registry pass feeds every metric-derived field below
        snap = METRICS.snapshot()

        def peek(name: str, default: float = 0.0, **labels) -> float:
            for _kind, sname, slabels, value in snap:
                if sname == name and slabels == labels:
                    return value
            return default

        phase_seconds: dict = {}
        for kind, name, labels, value in snap:
            if kind == "gauge" and name == "corro.kernel.phase.seconds":
                phase_seconds.setdefault(labels.get("kernel", "?"), {})[
                    labels.get("phase", "?")
                ] = value

        # r9 Lifeguard census: local health + open-suspicion pressure —
        # the host-side mirror of the kernels' lhm_max /
        # suspicion_confirmations flight lanes
        suspects = [
            m for m in list(agent.membership.members.values())
            if m.state == MemberState.SUSPECT
        ]
        # r18 chaos census: the drill-vs-outage discriminator — elevated
        # p99s WITH a populated chaos block is an exercise, not a page
        from corrosion_tpu.chaos.faults import CENSUS as CHAOS_CENSUS

        status = {
            "actor_id": str(agent.actor_id),
            "chaos": CHAOS_CENSUS.snapshot(),
            "cluster": {
                "size": agent.membership.cluster_size,
                "member_states": by_state,
                "members_tracked": len(agent.members.states),
                "bookie_actors": len(agent.bookie.items()),
                "lifeguard": {
                    "enabled": agent.membership.config.lifeguard,
                    "lhm": agent.membership.lhm,
                    "multiplier": agent.membership.lhm_multiplier,
                    "open_suspects": len(suspects),
                    "suspicion_confirmations": sum(
                        len(m.suspectors) for m in suspects
                    ),
                },
            },
            "kernel_events": kernel_event_totals(METRICS),
            "kernel_phase_seconds": phase_seconds,
            # r10 subscription serving plane: how many live queries, how
            # the change router is spending the write path, and whether
            # the shared diff executor is backing up (depth > workers =
            # matchers queueing for a diff slot)
            "subscriptions": {
                # r12: the matcher's candidate-batching window — the
                # knob the SLO plane named as the match-stage p50 floor
                "candidate_batch_wait": agent.config.pubsub.candidate_batch_wait,
                "count": len(self.subs.handles()) if self.subs else 0,
                "streams": self.subs.stream_count() if self.subs else 0,
                # r16 serving-plane asymptote census: admission ceiling,
                # laggard sheds, dedupe pressure and the shared writer's
                # coalescing behavior — the numbers that say whether the
                # node is at its stream ceiling and who is paying for it
                "max_streams": agent.config.subs.max_streams,
                "admission_rejected": peek(
                    "corro.subs.admission.rejected.total"
                ),
                "shed": peek("corro.subs.shed.total"),
                "dedupe_hits": peek("corro.subs.dedupe.hits.total"),
                "writer_writes": peek("corro.subs.writer.writes.total"),
                "writer_coalesced_batches": peek(
                    "corro.subs.writer.coalesced.batches.total"
                ),
                "writer_clogged": peek("corro.subs.writer.clogged"),
                "router_tables": peek("corro.subs.router.tables"),
                "router_changes": peek("corro.subs.router.changes.total"),
                "router_matched": peek("corro.subs.router.matched.total"),
                "router_fanout": peek("corro.subs.router.fanout.total"),
                "executor_depth": peek("corro.subs.executor.depth"),
                "executor_submitted": peek(
                    "corro.subs.executor.submitted.total"
                ),
            },
            # r15 direct change capture: how local writes are being
            # captured (direct in-memory vs trigger fallback) — a
            # rising `fallback` means hot statements carry bound
            # values outside the provably-identical set, a rising
            # `trigger` means raw/unrecognized SQL on the write path
            "write_capture": {
                "enabled": agent.config.perf.direct_capture,
                "direct": peek("corro.write.capture.direct.total"),
                "trigger": peek("corro.write.capture.trigger.total"),
                "fallback": peek("corro.write.capture.fallback.total"),
            },
            # r19 trace census: is the tail sampler on, how full is the
            # in-flight buffer, how many traces were kept vs dropped
            # (full kept traces live at GET /v1/traces)
            "traces": _trace_census(),
            # r20 alerts census: which rules are firing/pending right
            # now and how sick this node judges itself (full lifecycle
            # rows + history live at GET /v1/alerts)
            "alerts": (
                agent.alerts.census()
                if agent.alerts is not None else {"enabled": False}
            ),
            # r22 remediation census: is the plane armed (vs observe-
            # only) and what it has done (full actuator table + typed
            # action history live at GET /v1/remediation)
            "remediation": (
                agent.remediation.census()
                if agent.remediation is not None
                else {"enabled": False}
            ),
            # r23 continuous-profiling census: sampler rate/shed state,
            # measured overhead, held windows (flamegraphs live at
            # GET /v1/profile)
            "profile": _profile_census(),
            # r11 SLO plane pointer: the canary's live numbers (full
            # per-stage percentiles live at GET /v1/slo)
            "slo": {
                "canary_enabled": agent.config.slo.canary,
                "canary_writes": peek("corro.slo.canary.writes.total"),
                "canary_missed": peek("corro.slo.canary.missed.total"),
                "canary_last_seconds": peek(
                    "corro.slo.canary.last.seconds"
                ),
            },
            "loop": {
                "lag_max_seconds": peek(
                    "corro.runtime.loop.lag.max.seconds"
                ),
                "tasks_alive": peek("corro.runtime.loop.tasks.alive"),
                "monitor_ticks": peek("corro.runtime.loop.ticks"),
            },
            "sync": {
                "changes_in_queue": peek("corro.agent.changes.in_queue"),
                "gaps": peek("corro.db.gaps.count"),
                "gap_versions": peek("corro.db.gaps.versions"),
                "buffered_change_versions": peek(
                    "corro.db.buffered_changes.versions"
                ),
                "client_rounds": peek("corro.sync.client.rounds"),
                "server_permits_available": getattr(
                    agent.sync_serve_sem, "_value", 0
                ),
                # r17 catch-up plane census: is this node (or anyone
                # pulling from it) catching up, how, and is the
                # fault-tolerance machinery engaging — the one block an
                # operator reads during a cold-node join or post-
                # partition repair
                "catchup": {
                    "snapshot_enabled": agent.config.sync.snapshot,
                    "bootstrap": dict(agent.catchup_census),
                    "held_versions": _held_versions(agent),
                    "resume_waves": peek("corro.sync.resume.waves.total"),
                    "resume_versions": peek(
                        "corro.sync.resume.versions.total"
                    ),
                    "circuits_open": sum(
                        1
                        for c in agent.sync_circuits.values()
                        if not c.allows(time.monotonic())
                    ),
                    "snapshot_installs": peek("corro.snapshot.install.total"),
                    "snapshot_serves": peek("corro.snapshot.serve.total"),
                    "snapshot_cache_age_secs": (
                        round(agent.snapshots.age(), 3)
                        if agent.snapshots is not None
                        and agent.snapshots.age() is not None
                        else None
                    ),
                    "snapshot_cache_bytes": (
                        agent.snapshots.compressed_bytes
                        if agent.snapshots is not None
                        else 0
                    ),
                },
            },
        }
        return web.json_response(status)

    async def h_flight(self, request: web.Request) -> web.Response:
        """Flight-recorder timeline plane: the last-K per-tick frames
        (`?window=K`, default 64, capped at the recorder's capacity;
        `?kernel=` filters one kernel's timeline).  Each frame is one
        protocol period: event DELTAS + census levels, wall-clock
        stamped at drain — where /v1/status answers "how much, total",
        this answers "when" (the distinction a convergence-stall
        post-mortem actually needs)."""
        from corrosion_tpu.runtime.metrics import FLIGHT_CENSUS, KERNEL_EVENTS
        from corrosion_tpu.runtime.records import FLIGHT

        try:
            window = int(request.query.get("window", "64"))
        except ValueError:
            raise web.HTTPBadRequest(text="window must be an integer")
        kernel = request.query.get("kernel") or None
        frames = FLIGHT.window(max(1, min(window, 4096)), kernel=kernel)
        return web.json_response(
            {
                "window": len(frames),
                "event_lanes": list(KERNEL_EVENTS),
                "census_lanes": list(FLIGHT_CENSUS),
                "frames": frames,
            }
        )

    async def h_slo(self, request: web.Request) -> web.Response:
        """SLO latency plane (r11): per-stage windowed p50/p90/p99/p999
        of the write→event path (`corro.e2e.*`), cumulative percentiles,
        the configured targets, and error-budget burn per stage — the
        question every perf round is judged by ("what is p99 write→event
        latency right now"), answered from the log-bucketed windowed
        histograms without a sorted-array pass.  `?window=K` overrides
        the sliding window (seconds).  Checking ALSO advances the
        breach tracker: a sustained breach trips a flight-recorder
        incident dump, so polling this endpoint (or running the canary)
        is what arms the black box."""
        from corrosion_tpu.runtime.latency import SloMonitor

        agent = self.agent
        window: Optional[float] = None
        raw = request.query.get("window")
        if raw is not None:
            try:
                window = float(raw)
            except ValueError:
                raise web.HTTPBadRequest(text="window must be a number")
            if window <= 0:
                raise web.HTTPBadRequest(text="window must be positive")
        slo = agent.slo
        if slo is None:  # agents assembled without setup() (tests)
            slo = agent.slo = SloMonitor(targets=agent.config.slo.targets)
        stages = slo.check(window_secs=window)

        # r19 exemplars: each stage row names the kept traces whose
        # worst span of THAT stage is slowest — the jump from "p99
        # breached" to "this write, through these nodes"
        from corrosion_tpu.runtime import tracestore

        st = tracestore.store()
        for stage, row in stages.items():
            row["slowest_trace_ids"] = (
                st.slowest_ids(stage, 3) if st is not None else []
            )

        snap = METRICS.snapshot()

        def peek(name: str, default: float = 0.0, **labels) -> float:
            for _kind, sname, slabels, value in snap:
                if sname == name and slabels == labels:
                    return value
            return default

        skew = {
            labels["stage"]: value
            for _k, name, labels, value in snap
            if name == "corro.e2e.skew.clamped.total" and "stage" in labels
        }
        return web.json_response(
            {
                "actor_id": str(agent.actor_id),
                "window_secs": window
                if window is not None
                else slo.window_secs,
                "objective": slo.objective,
                "stages": stages,
                "skew_clamped": skew,
                "canary": {
                    "enabled": agent.config.slo.canary,
                    "writes": peek("corro.slo.canary.writes.total"),
                    "missed": peek("corro.slo.canary.missed.total"),
                    "last_seconds": peek("corro.slo.canary.last.seconds"),
                    "observed": peek(
                        "corro.e2e.canary.seconds_count", scope="local"
                    )
                    + peek("corro.e2e.canary.seconds_count", scope="remote"),
                },
            }
        )

    async def h_traces(self, request: web.Request) -> web.Response:
        """End-to-end write-trace plane (r19): the slowest-N KEPT traces
        from the tail sampler — each one write's full
        write→broadcast→apply→match→deliver causality with a per-stage
        breakdown, the keep reason (error / forced / slo:<stage> /
        lottery), the actors it crossed, and whether a chaos injection
        was live at capture.  Where /v1/slo answers "which stage is
        slow in aggregate", this answers "which WRITE, through which
        nodes, stalled where".  Filters: `?n=` (default 20),
        `?stage=`, `?actor=`, `?table=`; `?spans=0` drops the
        per-span rows for compact dashboards."""
        from corrosion_tpu.runtime import tracestore

        st = tracestore.store()
        if st is None:
            return web.json_response(
                {
                    "actor_id": str(self.agent.actor_id),
                    "census": {"enabled": False},
                    "traces": [],
                }
            )
        try:
            n = int(request.query.get("n", "20"))
        except ValueError:
            raise web.HTTPBadRequest(text="n must be an integer")
        traces = st.kept(
            n=max(1, min(n, st.keep_max)),
            stage=request.query.get("stage") or None,
            actor=request.query.get("actor") or None,
            table=request.query.get("table") or None,
        )
        if request.query.get("spans") == "0":
            traces = [
                {k: v for k, v in t.items() if k != "spans"} for t in traces
            ]
        return web.json_response(
            {
                "actor_id": str(self.agent.actor_id),
                "census": st.census(),
                "traces": traces,
            }
        )

    async def h_alerts(self, request: web.Request) -> web.Response:
        """Alerting plane (r20): the typed, lifecycle-tracked alerts
        the `[alerts]` rules raised over the metrics TSDB.  Default
        scope serves THIS node's engine (rule states, active alerts
        with drill marks / exemplar trace ids / incident paths, and
        the transition history; `?history=0` trims it);
        `?scope=cluster` serves every node's digest-carried active
        alerts plus a per-rule rollup — from ANY single node, over the
        observatory's anti-entropy store."""
        if request.query.get("scope") == "cluster":
            obs = self.agent.observatory
            if obs is None:
                raise web.HTTPNotImplemented(
                    text="cluster observatory disabled "
                         "([cluster] digests=false)"
                )
            return web.json_response(obs.cluster_alerts())
        eng = self.agent.alerts
        if eng is None:
            return web.json_response(
                {"enabled": False, "rules": [], "active": []}
            )
        report = eng.report(
            history=request.query.get("history") != "0"
        )
        report["actor_id"] = str(self.agent.actor_id)
        return web.json_response(report)

    async def h_remediation(self, request: web.Request) -> web.Response:
        """Remediation plane (r22): the actuator census (alert rule →
        action → cooldown → revert, with live cooldown remainders) and
        the typed action history — every acted / would_act / deferred /
        refused / reverted decision with its wall stamp, drill mark and
        detail.  `?history=0` trims the history."""
        sup = self.agent.remediation
        if sup is None:
            return web.json_response(
                {"enabled": False, "actuators": [], "history": []}
            )
        report = sup.report(
            history=request.query.get("history") != "0"
        )
        report["actor_id"] = str(self.agent.actor_id)
        return web.json_response(report)

    async def h_profile(self, request: web.Request) -> web.Response:
        """Continuous profiling plane (r23): the always-on wall-clock
        stack sampler's folded output.  `?window=` bounds the lookback
        in seconds (default 60); `?format=folded` serves the collapsed-
        stack text every flamegraph tool ingests, `?format=speedscope`
        a speedscope.app document, default JSON a summary (top self-time
        frames, statement-shape table, overhead gauge, census).
        `?scope=cluster` serves the digest-carried per-node hotspot
        rollup — any node answers for the whole cluster."""
        from corrosion_tpu.runtime import profiler

        if request.query.get("scope") == "cluster":
            obs = self.agent.observatory
            if obs is None:
                raise web.HTTPNotImplemented(
                    text="cluster observatory disabled "
                         "([cluster] digests=false)"
                )
            return web.json_response(obs.cluster_hotspots())
        prof = profiler.get()
        if prof is None:
            return web.json_response({"enabled": False})
        try:
            window = float(request.query.get("window", "60"))
        except ValueError:
            raise web.HTTPBadRequest(text="window must be a number")
        fmt = request.query.get("format", "json")
        if fmt not in ("json", "folded", "speedscope"):
            raise web.HTTPBadRequest(
                text="format must be json|folded|speedscope"
            )
        out = prof.export(window_secs=window, fmt=fmt)
        if fmt == "folded":
            return web.Response(text=out, content_type="text/plain")
        if isinstance(out, dict) and fmt == "json":
            out["actor_id"] = str(self.agent.actor_id)
        return web.json_response(out)

    async def h_cluster(self, request: web.Request) -> web.Response:
        """Cluster observatory plane (r12): the CLUSTER-wide answer any
        single node can serve — digest coverage/staleness per node,
        per-node health roll-up (census, LHM, loop lag, sync backlog),
        exact cluster-merged write→event stage percentiles (the gossiped
        digests carry mergeable histograms), and the view-divergence
        verdict.  Serving rebuilds the local digest and runs one
        divergence check, so polling this endpoint also advances
        detection — same discipline as /v1/slo's breach tracker."""
        obs = self.agent.observatory
        if obs is None:
            raise web.HTTPNotImplemented(
                text="cluster observatory disabled ([cluster] digests=false)"
            )
        return web.json_response(obs.cluster_report())

    # -- pubsub routes (wired when managers are attached) ------------------

    async def h_subscribe(self, request: web.Request) -> web.StreamResponse:
        if self.subs is None:
            raise web.HTTPNotImplemented(text="subscriptions not enabled")
        from corrosion_tpu.api.pubsub_http import handle_subscribe

        return await handle_subscribe(self, request)

    async def h_subscription_by_id(
        self, request: web.Request
    ) -> web.StreamResponse:
        if self.subs is None:
            raise web.HTTPNotImplemented(text="subscriptions not enabled")
        from corrosion_tpu.api.pubsub_http import handle_subscription_by_id

        return await handle_subscription_by_id(self, request)

    async def h_updates(self, request: web.Request) -> web.StreamResponse:
        if self.updates is None:
            raise web.HTTPNotImplemented(text="updates not enabled")
        from corrosion_tpu.api.pubsub_http import handle_updates

        return await handle_updates(self, request)


def _bind_params(stmt: Statement):
    if stmt.named_params:
        return {k.lstrip(":@$"): v for k, v in stmt.named_params.items()}
    return tuple(stmt.params)


def _execute_stmt(tx, stmt: Statement) -> int:
    # both paths go through WriteTx.execute: one trace/timing point and
    # one faithful rows_affected mapping (DML counts pass through, -1
    # row-less statement classes report 0)
    if stmt.named_params:
        return tx.execute(
            stmt.query,
            {k.lstrip(":@$"): v for k, v in stmt.named_params.items()},
        )
    return tx.execute(stmt.query, stmt.params)

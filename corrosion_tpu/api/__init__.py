"""Client-facing HTTP API (L5) — the 7 public endpoints + NDJSON streams.

Counterpart of `klukai-agent/src/api/public/` served by the axum router
assembled in `agent/util.rs:181-328`. JSON payload shapes mirror
`klukai-types/src/api.rs` so reference clients work unchanged.
"""

from corrosion_tpu.api.http import ApiServer

__all__ = ["ApiServer"]

"""JSON payload (de)serialization for the public API.

Byte-compatible with the serde layouts in `klukai-types/src/api.rs`:
  - `Statement` (untagged, api.rs:231-240): "sql" | ["sql", [params]] |
    ["sql", {named}] | {"query": ..., "params"/"named_params": ...}
  - `QueryEvent` (externally tagged, api.rs:67-78): {"columns": [...]},
    {"row": [rowid, [values]]}, {"eoq": {"time": t, "change_id"?: id}},
    {"change": [type, rowid, [values], change_id]}, {"error": "..."}
  - `ExecResponse`/`ExecResult` (api.rs:260-272)
  - SqliteValue: untagged JSON scalar; blobs as byte arrays
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from corrosion_tpu.types.values import SqliteValue


@dataclass
class Statement:
    query: str
    params: List[SqliteValue] = field(default_factory=list)
    named_params: Optional[Dict[str, SqliteValue]] = None


def parse_value(v: Any) -> SqliteValue:
    if v is None or isinstance(v, (int, float, str)):
        return v
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, list):  # blob as byte array
        return bytes(v)
    raise ValueError(f"unsupported param: {v!r}")


def dump_value(v: SqliteValue) -> Any:
    if isinstance(v, (bytes, bytearray, memoryview)):
        return list(bytes(v))
    return v


def parse_statement(obj: Any) -> Statement:
    if isinstance(obj, str):
        return Statement(query=obj)
    if isinstance(obj, list) and obj and isinstance(obj[0], str):
        if len(obj) == 2 and isinstance(obj[1], list):
            return Statement(obj[0], [parse_value(p) for p in obj[1]])
        if len(obj) == 2 and isinstance(obj[1], dict):
            return Statement(
                obj[0],
                named_params={
                    k: parse_value(v) for k, v in obj[1].items()
                },
            )
        # flat params variant: ["sql", p1, p2, ...]
        return Statement(obj[0], [parse_value(p) for p in obj[1:]])
    if isinstance(obj, dict) and "query" in obj:
        return Statement(
            obj["query"],
            [parse_value(p) for p in obj.get("params") or []],
            named_params=(
                {k: parse_value(v) for k, v in obj["named_params"].items()}
                if obj.get("named_params")
                else None
            ),
        )
    raise ValueError(f"malformed statement: {obj!r}")


# -- events ---------------------------------------------------------------


def ev_columns(cols: List[str]) -> str:
    return json.dumps({"columns": cols}, separators=(",", ":"))


def ev_row(rowid: int, values: List[SqliteValue]) -> str:
    return json.dumps(
        {"row": [rowid, [dump_value(v) for v in values]]},
        separators=(",", ":"),
    )


def ev_eoq(time_s: float, change_id: Optional[int] = None) -> str:
    body: Dict[str, Any] = {"time": time_s}
    if change_id is not None:
        body["change_id"] = change_id
    return json.dumps({"eoq": body}, separators=(",", ":"))


def ev_error(err: str) -> str:
    return json.dumps({"error": err}, separators=(",", ":"))


def ev_lagging(lag_bytes: int, lag_batches: int) -> str:
    """Typed terminal frame for a SHED laggard stream (r16 admission
    control): the subscription itself is healthy — the client's socket
    fell `lag_bytes`/`lag_batches` behind the live fan-out and the node
    dropped the stream rather than stall its siblings.  Clients resume
    from their last observed change id (client.py reconnects on it)."""
    return json.dumps(
        {"lagging": {"lag_bytes": lag_bytes, "lag_batches": lag_batches}},
        separators=(",", ":"),
    )


def ev_notify(kind: str, pk_values: List[SqliteValue]) -> str:
    return json.dumps(
        {"notify": [kind, [dump_value(v) for v in pk_values]]},
        separators=(",", ":"),
    )


def exec_response(
    results: List[Dict[str, Any]],
    time_s: float,
    version: Optional[int],
    actor_id: Optional[str],
) -> Dict[str, Any]:
    return {
        "results": results,
        "time": time_s,
        "version": version,
        "actor_id": actor_id,
    }

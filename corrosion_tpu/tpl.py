"""Template engine: render files from live query results.

Counterpart of the reference's rhai-tpl engine (`klukai/src/tpl/mod.rs`,
`klukai/src/command/tpl.rs`, ~1131 LoC): templates embed script blocks
that call `sql("SELECT ...")` and iterate rows; `.to_json()` / `.to_csv()`
render whole result sets; `hostname()` is available. Specs are
`SRC:DST[:CMD]` — render to a temp file, atomically rename over DST, then
run CMD. Watch mode re-renders when any queried data changes (100 ms
debounce, like the reference's TemplateCommand::Render loop) and
recompiles when the template file itself changes.

Template syntax (classic mini-template, compiled to Python):
    text …
    <%= expr %>                 emit str(expr)
    <% for row in sql("…") %>   statements / control flow
    …
    <% end %>                   closes for/if blocks

Script blocks run a *Python expression subset* in a namespace exposing
only the template API (sql, hostname, row/cell helpers). Templates are
operator-supplied — the same trust model as the reference's rhai
templates, which can also run `exec_cmd`.
"""

from __future__ import annotations

import asyncio
import csv
import io
import json
import os
import re
import socket
import tempfile
import zlib
from pathlib import Path
from typing import Any, Callable, List, Optional, Sequence, Tuple


class TemplateError(Exception):
    pass


# -- result-set objects ----------------------------------------------------


class Row:
    """One result row: index by position or column name."""

    __slots__ = ("_cols", "_vals")

    def __init__(self, cols: Sequence[str], vals: Sequence[Any]):
        self._cols = cols
        self._vals = list(vals)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._vals[key]
        return self._vals[self._cols.index(key)]

    def __getattr__(self, name):
        try:
            return self._vals[self._cols.index(name)]
        except ValueError:
            raise AttributeError(name) from None

    def __iter__(self):
        return iter(self._vals)

    def __len__(self):
        return len(self._vals)

    def to_json(self) -> str:
        return json.dumps(dict(zip(self._cols, self._vals)))

    def cells(self) -> List["Cell"]:
        """The row as (name, value) cells — the reference's Cell type
        with name()/value()/is_null()/to_json()/to_string()
        (tpl/mod.rs:493-500)."""
        return [Cell(c, v) for c, v in zip(self._cols, self._vals)]


class Cell:
    """One (column, value) pair (tpl/mod.rs Cell + SqliteValueWrap)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: Any):
        self.name = name
        self.value = value

    def is_null(self) -> bool:
        return self.value is None

    def to_json(self) -> str:
        return json.dumps(self.value)

    def to_string(self) -> str:
        return _stringify(self.value)


class QueryResponse:
    """Iterable result set with to_json()/to_csv() (tpl/mod.rs:38-98)."""

    def __init__(self, cols: List[str], rows: List[List[Any]]):
        self.columns = cols
        self._rows = rows

    def __iter__(self):
        return (Row(self.columns, r) for r in self._rows)

    def __len__(self):
        return len(self._rows)

    def to_json(self, pretty: bool = False) -> str:
        data = [dict(zip(self.columns, r)) for r in self._rows]
        return json.dumps(data, indent=2 if pretty else None)

    def to_csv(self, header: bool = True) -> str:
        buf = io.StringIO()
        w = csv.writer(buf)
        if header:
            w.writerow(self.columns)
        for r in self._rows:
            w.writerow(r)
        return buf.getvalue()


# -- compiler --------------------------------------------------------------

_TAG = re.compile(r"<%(=?)(.*?)%>", re.DOTALL)
_BLOCK_OPEN = re.compile(r"^\s*(for|if|while|elif|else)\b")
_DEDENT = re.compile(r"^\s*(elif|else)\b")


def compile_template(text: str) -> Callable[[dict], str]:
    """Compile template text into a callable(namespace) -> rendered str."""
    src: List[str] = ["def __render__(__ns__):", " __out__ = []"]
    indent = 1

    def emit(line: str) -> None:
        src.append(" " * indent + line.lstrip())

    pos = 0
    for m in _TAG.finditer(text):
        literal = text[pos : m.start()]
        if literal:
            emit(f" __out__.append({literal!r})")
        is_expr, body = m.group(1), m.group(2).strip()
        if is_expr:
            emit(f" __out__.append(__str__({body}))")
        elif body == "end":
            indent -= 1
            if indent < 1:
                raise TemplateError("unbalanced <% end %>")
        elif _DEDENT.match(body):
            indent -= 1
            if indent < 1:
                raise TemplateError(f"unbalanced <% {body} %>")
            emit(f" {body}:")
            indent += 1
        elif _BLOCK_OPEN.match(body):
            emit(f" {body}:")
            indent += 1
        else:
            emit(f" {body}")
        pos = m.end()
    if text[pos:]:
        emit(f" __out__.append({text[pos:]!r})")
    if indent != 1:
        raise TemplateError("unclosed block: missing <% end %>")
    src.append(" return ''.join(__out__)")

    code_obj = compile("\n".join(src), "<template>", "exec")

    def run(ns: dict) -> str:
        # the template body resolves names (sql, hostname, …) through its
        # globals, so inject the namespace there
        g = {"__str__": _stringify, **ns}
        exec(code_obj, g)
        return g["__render__"](ns)

    return run


def _stringify(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


# -- render state ----------------------------------------------------------


class TemplateState:
    """Per-render context: the sql() binding plus collected subscriptions
    (tpl/mod.rs TemplateState: cmd channel + cancellation)."""

    def __init__(self, api_addr: str, token: Optional[str], loop, watch: bool):
        self.api_addr = api_addr
        self.token = token
        self.loop = loop
        self.watch = watch
        # watch mode: (client, live aiter) pairs still streaming change
        # events after the initial snapshot was consumed
        self.streams: List[Tuple[Any, Any]] = []

    # sql() runs on the render thread; the HTTP round-trip happens on the
    # main loop (the reference equally block_in_place()s rhai evaluation)
    def sql(self, stmt: Any) -> QueryResponse:
        fut = asyncio.run_coroutine_threadsafe(self._sql(stmt), self.loop)
        return fut.result(timeout=30)

    async def _sql(self, stmt: Any) -> QueryResponse:
        from corrosion_tpu.client import CorrosionApiClient

        if not self.watch:
            async with CorrosionApiClient(
                self.api_addr, token=self.token
            ) as c:
                cols: List[str] = []
                rows: List[List[Any]] = []
                async for ev in c.query(stmt):
                    if "columns" in ev:
                        cols = ev["columns"]
                    elif "row" in ev:
                        rows.append(ev["row"][1])
                    elif "error" in ev:
                        raise TemplateError(ev["error"])
                return QueryResponse(cols, rows)
        # watch mode: subscribe so data changes re-render; keep the live
        # stream past eoq — further events are the re-render signal
        c = CorrosionApiClient(self.api_addr, token=self.token)
        it = c.subscribe(stmt).__aiter__()
        cols = []
        rows = []
        async for ev in it:
            if "columns" in ev:
                cols = ev["columns"]
            elif "row" in ev:
                rows.append(ev["row"][1])
            elif "eoq" in ev:
                break
            elif "error" in ev:
                raise TemplateError(ev["error"])
        self.streams.append((c, it))
        return QueryResponse(cols, rows)

    async def close(self) -> None:
        for c, it in self.streams:
            with _suppress(Exception):
                await it.aclose()
            with _suppress(Exception):
                await c.close()
        self.streams = []

    def exec_cmd(self, cmd: str, *args: str, timeout: float = 10.0) -> str:
        """Run a subprocess from inside a template and return its stdout.

        The upstream templating engine exposes user scripting with
        command execution; this reference snapshot's rhai engine stops at
        the write/to_json/to_csv surface (tpl/mod.rs:451-500), so the
        contract here is the minimal safe form: argv (no shell), bounded
        by `timeout`, non-zero exit raises. Renders run in a worker
        thread (render_once), so blocking is fine.

        OFF by default: a template file is data, and silently granting it
        command execution would widen the agent's attack surface to
        anything that can write a .tpl. Enable explicitly with
        CORRO_TPL_ALLOW_EXEC=1 in the agent's environment."""
        import subprocess

        if os.environ.get("CORRO_TPL_ALLOW_EXEC", "") not in ("1", "true"):
            raise TemplateError(
                "exec_cmd is disabled; set CORRO_TPL_ALLOW_EXEC=1 to allow"
                " templates to run commands"
            )

        try:
            res = subprocess.run(
                [cmd, *args],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            raise TemplateError(
                f"exec_cmd {cmd!r} timed out after {timeout}s"
            ) from None
        except OSError as e:
            raise TemplateError(f"exec_cmd {cmd!r} failed: {e}") from None
        if res.returncode != 0:
            raise TemplateError(
                f"exec_cmd {cmd!r} exited {res.returncode}:"
                f" {res.stderr.strip()[:200]}"
            )
        return res.stdout

    def namespace(self) -> dict:
        return {
            "sql": self.sql,
            "hostname": lambda: socket.gethostname(),
            "exec_cmd": self.exec_cmd,
        }


# -- spec handling ---------------------------------------------------------


def parse_spec(spec: str) -> Tuple[str, str, Optional[str]]:
    parts = spec.split(":", 2)
    if len(parts) < 2:
        raise TemplateError(f"spec needs SRC:DST[:CMD], got {spec!r}")
    src, dst = parts[0], parts[1]
    cmd = parts[2] if len(parts) > 2 else None
    return src, dst, cmd


async def render_once(
    api_addr: str,
    token: Optional[str],
    src: str,
    dst: str,
    cmd: Optional[str],
    watch: bool = False,
) -> TemplateState:
    """Render one template spec: compile, evaluate, atomic-replace DST,
    run CMD (command/tpl.rs render loop body)."""
    text = Path(src).read_text()
    template = compile_template(text)
    loop = asyncio.get_running_loop()
    state = TemplateState(api_addr, token, loop, watch)
    rendered = await asyncio.to_thread(template, state.namespace())

    Path(dst).parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(Path(dst).parent))
    try:
        with os.fdopen(fd, "w") as f:
            f.write(rendered)
        os.replace(tmp, dst)
    except BaseException:
        with _suppress(OSError):
            os.unlink(tmp)
        raise

    if cmd:
        import shlex

        proc = await asyncio.create_subprocess_exec(*shlex.split(cmd))
        await proc.wait()
    return state


class _suppress:
    def __init__(self, *exc):
        self.exc = exc

    def __enter__(self):
        return self

    def __exit__(self, et, e, tb):
        return et is not None and issubclass(et, self.exc)


async def render_specs(cfg, specs: List[str]) -> int:
    """One-shot render of every spec (template --once path)."""
    api_addr = cfg.api.bind_addr[0]
    for spec in specs:
        src, dst, cmd = parse_spec(spec)
        await render_once(api_addr, cfg.api.authz_bearer, src, dst, cmd)
        print(f"rendered {src} -> {dst}")
    return 0


async def watch_specs(cfg, specs: List[str], tripwire=None) -> None:
    """Continuous mode: re-render on data-change events from any
    subscription the template opened, or when the template file changes
    (mtime + crc32, command/tpl.rs:154-216). 100 ms debounce."""
    api_addr = cfg.api.bind_addr[0]
    tasks = [
        asyncio.ensure_future(
            _watch_one(api_addr, cfg.api.authz_bearer, spec, tripwire)
        )
        for spec in specs
    ]
    try:
        await asyncio.gather(*tasks)
    finally:
        for t in tasks:
            t.cancel()


async def _watch_one(
    api_addr: str, token: Optional[str], spec: str, tripwire
) -> None:
    src, dst, cmd = parse_spec(spec)
    checksum = zlib.crc32(Path(src).read_bytes())
    mtime = os.path.getmtime(src)

    while tripwire is None or not tripwire.tripped:
        state = await render_once(api_addr, token, src, dst, cmd, watch=True)

        # wake on: any subscription change event, or template file change
        wake = asyncio.Event()

        async def sub_listener(it) -> None:
            try:
                async for ev in it:
                    if "change" in ev:
                        wake.set()
            except Exception:
                pass
            wake.set()  # stream died: re-render to resubscribe

        listeners = [
            asyncio.ensure_future(sub_listener(it))
            for _c, it in state.streams
        ]

        async def file_poller() -> None:
            nonlocal checksum, mtime
            while True:
                await asyncio.sleep(1.0)
                try:
                    new_mtime = os.path.getmtime(src)
                except FileNotFoundError:
                    continue
                if new_mtime != mtime:
                    mtime = new_mtime
                    new_sum = zlib.crc32(Path(src).read_bytes())
                    if new_sum != checksum:
                        checksum = new_sum
                        wake.set()
                        return

        poller = asyncio.ensure_future(file_poller())
        try:
            if tripwire is not None:
                from corrosion_tpu.runtime.tripwire import Outcome

                outcome, _ = await tripwire.preemptible(wake.wait())
                if outcome is Outcome.PREEMPTED:
                    return
            else:
                await wake.wait()
            await asyncio.sleep(0.1)  # debounce (DEBOUNCE_DEADLINE)
        finally:
            poller.cancel()
            for t in listeners:
                t.cancel()
            await state.close()

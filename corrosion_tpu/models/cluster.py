"""ClusterSim: drive the batched SWIM kernel as a simulated devcluster.

This is the TPU replacement for `klukai-devcluster` spawning one OS process
per node (`crates/klukai-devcluster/src/main.rs:107-232`): instead, 10^4+
members advance as array rows through `ops.swim.tick`. The measurement
surface mirrors §6 of SURVEY.md: time-to-stable-membership and
false-positive detection rates under churn.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import swim, swim_pview
from corrosion_tpu.runtime import trace
from corrosion_tpu.runtime.metrics import (
    record_kernel_events,
    record_phase_seconds,
)
from corrosion_tpu.runtime.records import FLIGHT


@dataclass
class TickMetrics:
    tick: int
    coverage: float
    detected: float
    false_positive: float
    wall_s: float


def _publish_event_deltas(
    kernel: str, prev: np.ndarray, cur: np.ndarray
) -> np.ndarray:
    """Publish the device telemetry lane's growth since the last drain
    as `corro.kernel.events.total{kernel=,event=}` counter increments.
    The device totals wrap mod 2^32 (int32 lane); uint32 subtraction
    makes the delta wrap-safe as long as one drain window stays under
    2^32 events — every driver drains at least once per stats check.
    Span-wrapped so an OTLP trace shows WHEN each publish window landed
    (runtime/trace.py; flight frames carry the same wall clock)."""
    with trace.span("sim.events.publish", kernel=kernel):
        delta = (cur - prev).astype(np.uint32)
        record_kernel_events(kernel, delta.tolist())
    return cur


def _drain_flight(kernel: str, drain, since: int) -> int:
    """Stitch one drained device ring into the process-global flight
    recorder (span-wrapped for the OTLP ↔ flight wall-clock line-up);
    returns the new per-sim cursor."""
    with trace.span("sim.flight.drain", kernel=kernel, tick=drain.t):
        FLIGHT.record_ring(kernel, drain, since=since)
    return drain.t


class ClusterSim:
    """A simulated SWIM cluster of `n` members on one device (see
    `corrosion_tpu.parallel` for the sharded multi-device variant)."""

    def __init__(
        self,
        n: int,
        seed: int = 0,
        seeds_per_member: int = 3,
        seed_mode: str = "ring",
        **param_overrides,
    ):
        self.params = swim.SwimParams(n=n, **param_overrides)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_key = jax.random.split(self._rng)
        self.state = swim.init_state(
            self.params, init_key, seeds_per_member, seed_mode
        )
        self.history: List[TickMetrics] = []
        self.ticks = 0  # host-side mirror of state.t (no device readback)
        self._ev_prev = np.zeros(swim.N_EVENTS, dtype=np.uint32)
        self._flight_next = 0  # flight-recorder cursor (see _drain_flight)

    def step(self, ticks: int = 1) -> None:
        """Advance `ticks` protocol periods in ONE device dispatch
        (swim.tick_n scan) — host round-trips, not compute, dominate on
        tunneled TPU links."""
        self._rng, key = jax.random.split(self._rng)
        if ticks == 1:
            self.state = swim.tick(self.state, key, self.params)
        else:
            # donated: the [N, N] view updates in place, halving peak HBM
            # (ClusterSim owns its state and always replaces the reference)
            self.state = swim.tick_n_donated(self.state, key, self.params, ticks)
        self.ticks += ticks

    def crash(self, member: int) -> None:
        self.state = swim.set_alive(self.state, member, False)

    def restart(self, member: int) -> None:
        self.state = swim.set_alive(self.state, member, True)

    def degrade(self, members, loss: float = 0.0, lag: int = 0) -> None:
        """Degraded-node fault injection (r9): flaky, not dead — see
        swim.set_degraded. loss=0, lag=0 restores."""
        self.state = swim.set_degraded(self.state, members, loss, lag)

    def stats(self) -> Dict[str, float]:
        """Convergence stats; the device telemetry lane AND the flight
        ring drain in the SAME readback — deltas go to the shared
        registry (`corro.kernel.events.total{kernel="dense"}`), per-tick
        frames to the global `FLIGHT` recorder."""
        s, ev, fl = swim.stats_and_events(self.state)
        self._ev_prev = _publish_event_deltas("dense", self._ev_prev, ev)
        self._flight_next = _drain_flight("dense", fl, self._flight_next)
        return s

    def run_until_stable(
        self,
        coverage_target: float = 0.999,
        max_ticks: int = 10_000,
        record_every: int = 1,
        fine_every: Optional[int] = None,
        fine_threshold: float = 0.9,
    ) -> Optional[int]:
        """Advance up to `max_ticks` further steps until live-member
        coverage reaches the target; returns the (global) tick count at
        stability or None. Records metric history. Tick counting is
        host-side so no device readback happens between stats checks.

        With `fine_every`, stepping switches to the smaller chunk once
        coverage crosses `fine_threshold` — coarse chunks amortize
        dispatch early on, fine chunks avoid overshooting the target by
        most of a coarse chunk at the end."""
        start = time.monotonic()
        done = 0
        step_size = record_every
        while done < max_ticks:
            batch = min(step_size, max_ticks - done)
            self.step(batch)
            done += batch
            s = self.stats()
            self.history.append(
                TickMetrics(
                    tick=self.ticks,
                    coverage=s["coverage"],
                    detected=s["detected"],
                    false_positive=s["false_positive"],
                    wall_s=time.monotonic() - start,
                )
            )
            if s["coverage"] >= coverage_target:
                return self.ticks
            if fine_every is not None and s["coverage"] >= fine_threshold:
                step_size = fine_every
        return None

    def run_until_stable_device(
        self,
        coverage_target: float = 0.999,
        max_ticks: int = 10_000,
        check_every: int = 5,
    ) -> Optional[int]:
        """`run_until_stable` with the tick/check loop resident ON
        DEVICE (swim.run_to_coverage): one dispatch, zero host
        round-trips until convergence.  No per-check history is recorded
        (the loop never surfaces intermediate state); returns the
        absolute tick at stability rounded up to ``check_every``, or
        None.  A tight ``check_every`` (default 5) costs ~5% extra
        bandwidth but cuts the average overshoot a coarse host cadence
        pays at the end."""
        if check_every < 1:
            raise ValueError("check_every must be >= 1 (0 would make the"
                             " on-device while_loop spin forever)")
        self._rng, key = jax.random.split(self._rng)
        limit = self.ticks + max_ticks
        self.state, cov = swim.run_to_coverage(
            self.state, key, self.params,
            float(coverage_target), int(check_every), int(limit),
        )
        self.ticks = int(self.state.t)
        # one readback: the loop verdict + the telemetry lane + the
        # flight ring the device loop accumulated while it ran unobserved
        cov_v, ev, ring = jax.device_get(
            (cov, self.state.events, self.state.ring)
        )
        self._ev_prev = _publish_event_deltas(
            "dense", self._ev_prev, np.asarray(ev).astype(np.uint32)
        )
        self._flight_next = _drain_flight(
            "dense",
            swim.FlightDrain(ring=np.asarray(ring), t=self.ticks),
            self._flight_next,
        )
        # verdict must use the same precision the on-device predicate
        # compared at (f32), else a loop-satisfied coverage in
        # [f32(target), f64(target)) reads as a false non-convergence
        return self.ticks if float(cov_v) >= np.float32(coverage_target) else None

    def warm_device_loop(
        self,
        coverage_target: float = 0.999,
        max_ticks: int = 10_000,
        check_every: int = 5,
    ) -> None:
        """Compile the device loop without advancing a tick: the static
        args MUST equal a subsequent run_until_stable_device call's (the
        executable is keyed on them), and run_to_coverage's cond sees
        t >= the tick limit so it exits before the first body.  The
        donated-then-returned state is reassigned with its real t."""
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        limit = self.ticks + max_ticks
        capped = self.state._replace(t=jnp.int32(limit))
        out, _ = swim.run_to_coverage(
            capped, jax.random.PRNGKey(0), self.params,
            float(coverage_target), int(check_every), int(limit),
        )
        self.state = out._replace(t=jnp.int32(self.ticks))

    def run_until_detected(
        self, detect_target: float = 1.0, max_extra_ticks: int = 200
    ) -> Optional[int]:
        """After a crash, advance until every live member marked the dead
        ones down; returns ticks taken or None."""
        for i in range(1, max_extra_ticks + 1):
            self.step()
            if self.stats()["detected"] >= detect_target:
                return i
        return None


class PViewClusterSim:
    """The bounded partial-view counterpart of ClusterSim: drives
    `ops.swim_pview` as a simulated devcluster past the dense kernel's
    [N, N] memory wall.  Same driver shape (step / crash / stats /
    run-until loops); convergence is the pview bar — pv_coverage +
    in-degree quorum + table saturation + FP 0, the four terms
    `scripts/pview_converge.py` banks rungs under.

    Wall-clock per step() is published to the shared metrics registry
    (`corro.kernel.phase.seconds{kernel="pview", phase="tick"}`), so an
    agent embedding a simulation exposes tick cost on /metrics the same
    way its loops expose lag.  Every stats() readback also drains the
    kernel's device telemetry lane into
    `corro.kernel.events.total{kernel="pview", event=...}` counters —
    the event-level visibility (drops, overflows, suspicion churn) that
    makes a perf investigation diagnosable without code changes."""

    def __init__(
        self,
        n: int,
        slots: int = 1024,
        seed: int = 0,
        seed_mode: str = "fingers",
        **param_overrides,
    ):
        self.params = swim_pview.PViewParams(n=n, slots=slots, **param_overrides)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_key = jax.random.split(self._rng)
        self.state = swim_pview.init_state(
            self.params, init_key, seed_mode=seed_mode
        )
        self.ticks = 0  # host-side mirror of state.t (no device readback)
        self._ev_prev = np.zeros(swim.N_EVENTS, dtype=np.uint32)
        self._flight_next = 0  # flight-recorder cursor (see _drain_flight)

    def step(self, ticks: int = 1) -> None:
        """Advance `ticks` protocol periods in ONE donated dispatch."""
        self._rng, key = jax.random.split(self._rng)
        t0 = time.monotonic()
        self.state = swim_pview.tick_n_donated(
            self.state, key, self.params, ticks
        )
        jax.block_until_ready(self.state.slot_packed)
        record_phase_seconds(
            "pview", "tick", (time.monotonic() - t0) / max(1, ticks)
        )
        self.ticks += ticks

    def crash_many(self, members) -> None:
        self.state = swim_pview.set_alive_many(self.state, members, False)

    def restart_many(self, members) -> None:
        self.state = swim_pview.set_alive_many(self.state, members, True)

    def degrade(self, members, loss: float = 0.0, lag: int = 0) -> None:
        """Degraded-node fault injection (r9): flaky, not dead — see
        swim.set_degraded. loss=0, lag=0 restores."""
        self.state = swim_pview.set_degraded(self.state, members, loss, lag)

    def stats(self) -> Dict[str, float]:
        """Four-term-bar stats; drains + publishes the telemetry lane
        and the flight ring in the same readback (see class docstring)."""
        s, ev, fl = swim_pview.stats_and_events(self.state, self.params)
        self._ev_prev = _publish_event_deltas("pview", self._ev_prev, ev)
        self._flight_next = _drain_flight("pview", fl, self._flight_next)
        return s

    def converged(self, stats: Dict[str, float], cov_target: float = 0.99,
                  quorum: int = 8) -> bool:
        return (
            stats["pv_coverage"] >= cov_target
            and stats["min_in_degree"] >= quorum
            and stats["mean_in_degree"]
            >= swim_pview.saturation_floor(self.params.n, self.params.slots)
            and stats["false_positive"] == 0.0
        )

    def run_until_converged(
        self,
        cov_target: float = 0.99,
        quorum: int = 8,
        max_ticks: int = 2000,
        check_every: int = 10,
    ) -> Optional[int]:
        """Host-driven chunked loop (the tunnel-safe shape): advance
        `check_every` ticks per dispatch, check the four-term bar on
        host.  Returns the tick count at convergence or None."""
        while self.ticks < max_ticks:
            self.step(min(check_every, max_ticks - self.ticks))
            if self.converged(self.stats(), cov_target, quorum):
                return self.ticks
        return None

    def run_until_converged_device(
        self,
        cov_target: float = 0.99,
        quorum: int = 8,
        max_ticks: int = 2000,
        check_every: int = 10,
    ) -> Optional[int]:
        """`run_until_converged` with the tick/check loop resident ON
        DEVICE (swim_pview.run_to_converged): one dispatch, zero host
        round-trips until the bar holds.  NOT for the tunneled chip —
        the tunnel kills single executions past ~45-60 s (PROFILE.md);
        use the host loop there."""
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        self._rng, key = jax.random.split(self._rng)
        limit = self.ticks + max_ticks
        self.state, vals = swim_pview.run_to_converged(
            self.state, key, self.params,
            float(cov_target), int(quorum), int(check_every), int(limit),
        )
        self.ticks = int(self.state.t)
        # one readback: the four-term verdict + the device loop's lane +
        # its flight ring
        vals, ev, ring = jax.device_get(
            (vals, self.state.events, self.state.ring)
        )
        self._ev_prev = _publish_event_deltas(
            "pview", self._ev_prev, np.asarray(ev).astype(np.uint32)
        )
        self._flight_next = _drain_flight(
            "pview",
            swim.FlightDrain(ring=np.asarray(ring), t=self.ticks),
            self._flight_next,
        )
        vals = np.asarray(vals)
        sat = swim_pview.saturation_floor(self.params.n, self.params.slots)
        ok = (
            vals[0] >= np.float32(cov_target)
            and vals[2] >= quorum
            and vals[1] >= np.float32(sat)
            and vals[4] == 0.0
        )
        return self.ticks if ok else None


# ---------------------------------------------------------------------------
# Lifeguard A/B harness (r9): the degraded-node experiment, shared by the
# tier-1 regression test (tests/test_lifeguard.py, tiny shapes) and the
# banked chaos phase (scripts/chaos_soak.py --phase flaky-node).
# ---------------------------------------------------------------------------

from corrosion_tpu.runtime.metrics import KERNEL_EVENTS  # noqa: E402

_EV = {name: i for i, name in enumerate(KERNEL_EVENTS)}


def _mk_sim(kernel: str, n: int, slots: int, seed: int, lifeguard: bool,
            **overrides):
    lg = dict(lhm_max=8, susp_ceiling=3, susp_k=3) if lifeguard else {}
    if kernel == "dense":
        return ClusterSim(n, seed=seed, **lg, **overrides)
    if kernel == "pview":
        return PViewClusterSim(
            n, slots=slots, seed=seed, seed_mode="fingers", **lg, **overrides
        )
    raise ValueError(f"unknown kernel {kernel!r}")


def flaky_node_ab(
    kernel: str = "dense",
    seed: int = 0,
    n: int = 96,
    slots: int = 48,
    boot_ticks: int = 40,
    window: int = 240,
    lag: int = 2,
    loss: float = 0.0,
    chunk: int = 20,
    detect_chunk: int = 5,
    detect_cap: int = 200,
    suspicion_ticks: int = 4,
    drain_flight: bool = False,
    **overrides,
) -> dict:
    """One seeded vanilla-vs-Lifeguard A/B on a batched kernel.

    Scenario: boot `n` members, then (phase A) degrade member 1 —
    processing lag `lag` ticks and/or outbound loss `loss`, the node is
    ALIVE throughout — and run `window` ticks counting ground-truth
    false-positive suspicions/downs from the kernel's `suspect_fp`/
    `down_fp` event lanes; then (phase B) crash member 2 outright and
    count ticks until every live observer has it detected.  Both modes
    replay the SAME seed; the vanilla run uses lhm_max=0 (bit-equal to
    the pre-Lifeguard kernel, the compat pin), the lifeguard run
    lhm_max=8.  Returns per-mode FP totals, detection ticks, and the
    flight-recorder suspicion timeline of the lifeguard run.
    """
    out: dict = {"kernel": kernel, "seed": seed, "n": n, "lag": lag,
                 "loss": loss, "window": window}
    for mode in ("vanilla", "lifeguard"):
        mode_wall = time.time()
        sim = _mk_sim(
            kernel, n, slots, seed, mode == "lifeguard",
            suspicion_ticks=suspicion_ticks, **overrides,
        )
        done = 0
        while done < boot_ticks:
            sim.step(min(chunk, boot_ticks - done))
            done += chunk
        # ---- phase A: one flaky member, count false accusations ----
        sim.degrade([1], loss=loss, lag=lag)
        ev0 = np.asarray(jax.device_get(sim.state.events)).astype(np.int64)
        done = 0
        while done < window:
            sim.step(min(chunk, window - done))
            done += chunk
            if drain_flight:
                sim.stats()  # drain the device ring into FLIGHT per chunk
        ev1 = np.asarray(jax.device_get(sim.state.events)).astype(np.int64)
        delta = ev1 - ev0
        rec = {
            "suspect_fp": int(delta[_EV["suspect_fp"]]),
            "down_fp": int(delta[_EV["down_fp"]]),
            "suspect_raised": int(delta[_EV["suspect_raised"]]),
            "refuted": int(delta[_EV["refuted"]]),
            "confirmations": int(delta[_EV["suspicion_confirmations"]]),
        }
        rec["lhm_degraded"] = int(
            np.asarray(jax.device_get(sim.state.lhm))[1]
        )
        # ---- phase B: a REAL crash must still be detected fast ----
        if kernel == "dense":
            sim.crash(2)
        else:
            sim.crash_many([2])
        base = sim.ticks
        det = None
        while sim.ticks - base < detect_cap:
            sim.step(detect_chunk)
            s = sim.stats()  # drains events + flight ring as it goes
            if s["detected"] >= 1.0:
                det = sim.ticks - base
                break
        rec["detect_ticks"] = det
        rec["detect_base"] = base
        if drain_flight:
            # tick-resolved suspicion timeline of THIS mode's run, from
            # the flight recorder (frames are wall-stamped at drain, so
            # the mode boundary separates the two runs' frames even
            # though their tick counters overlap)
            frames = [
                f for f in FLIGHT.window(4096, kernel=kernel)
                if f["wall"] >= mode_wall
            ]
            rec["timeline"] = [
                {
                    "tick": f["tick"],
                    "suspect_raised": f["events"]["suspect_raised"],
                    "suspect_fp": f["events"]["suspect_fp"],
                    "down_declared": f["events"]["down_declared"],
                    "down_fp": f["events"]["down_fp"],
                    "refuted": f["events"]["refuted"],
                    "confirmations": f["events"][
                        "suspicion_confirmations"
                    ],
                    "lhm_max": f["census"].get("lhm_max", 0),
                    "open_timers": f["census"].get("census_suspect", 0),
                }
                for f in frames
                if f["events"]["suspect_raised"]
                or f["events"]["down_declared"]
                or f["events"]["refuted"]
            ][-64:]
        out[mode] = rec
    v, lf = out["vanilla"], out["lifeguard"]
    out["fp_ratio"] = (
        v["suspect_fp"] / max(1, lf["suspect_fp"])
        if lf["suspect_fp"] or v["suspect_fp"] else None
    )
    out["detect_ratio"] = (
        lf["detect_ticks"] / v["detect_ticks"]
        if lf["detect_ticks"] and v["detect_ticks"] else None
    )
    return out


# ---------------------------------------------------------------------------
# r12 cluster-observatory scenario harness (agent-level, mem-net)


async def cluster_observatory_scenario(
    scenario: str,
    seed: int = 0,
    nodes: int = 3,
    writes: int = 12,
    interval: float = 0.15,
    batch_wait: float = 0.1,
    hold_secs: float = 2.5,
    timeline: Optional[List[dict]] = None,
) -> dict:
    """One cluster-observatory episode on a real in-process devcluster
    (shared by `scripts/chaos_soak.py --phase cluster`, the obs_report
    cluster section, and the tier-1 live replica in
    tests/test_cluster_obs.py).

    Boots `nodes` agents over a mem network with a LONG suspicion
    window (the realistic regime where a partition is not instantly
    indistinguishable from a crash), runs a small write→event workload
    so the gossiped digests carry non-empty stage histograms, waits for
    full digest coverage on every node, then injects the scenario:

      quiet      — nothing; pins full coverage + exact aggregation
                   (cluster-merged stage percentiles == the merge of
                   the per-node /v1/slo cumulative histograms)
      partition  — the last node is cut from everyone for `hold_secs`,
                   then healed: the divergence detector must open ONE
                   episode per observing side within a bounded number
                   of digest rounds, dump ONE incident per episode, and
                   clear after heal
      churn      — the last node is taken down (crash-style silence)
                   and brought back: same detection surface, but the
                   episode must ALSO clear once digests flow again

    `timeline`, when given, receives one row per digest round with the
    first node's divergence gauges — the obs_report render feed.
    """
    import asyncio

    from corrosion_tpu.agent.membership import SwimConfig
    from corrosion_tpu.agent.run import make_broadcastable_changes, shutdown
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import CorrosionApiClient
    from corrosion_tpu.net.mem import MemNetwork
    from corrosion_tpu.runtime import latency as lat

    if scenario not in ("quiet", "partition", "churn"):
        raise ValueError(f"unknown scenario {scenario!r}")

    from tests.test_agent import boot, fast_config, wait_until

    net = MemNetwork(seed=seed)
    names = [f"cobs-{seed}-{i}" for i in range(nodes)]
    agents = []
    for i, name in enumerate(names):
        cfg = fast_config(name, tuple(names[:i][-2:]))
        cfg.pubsub.candidate_batch_wait = batch_wait
        cfg.cluster.digest_interval_secs = interval
        cfg.cluster.silent_after_mult = 3.0
        cfg.cluster.divergence_checks = 2
        ag = await boot(net, name, cfg=cfg)
        # fast probing, LONG suspicion: the observatory must win the
        # race against the failure detector's down-eviction
        ag.membership.config = SwimConfig(
            probe_period=0.05, probe_rtt=0.02, suspicion_mult=60.0
        )
        agents.append(ag)
    first, last = agents[0], agents[-1]
    api = client = it = None
    out: dict = {"scenario": scenario, "seed": seed, "nodes": nodes,
                 "digest_interval_secs": interval}
    try:
        assert await wait_until(
            lambda: all(len(a.members) == nodes - 1 for a in agents),
            timeout=30.0,
        ), "membership never converged"

        api = ApiServer(first)
        first.config.api.bind_addr = ["127.0.0.1:0"]
        await api.start()
        client = CorrosionApiClient(api.addrs[0])
        stream = client.subscribe("SELECT id, text FROM tests")
        it = stream.__aiter__()
        while True:
            ev = await asyncio.wait_for(it.__anext__(), 10)
            if "eoq" in ev:
                break
        got = 0
        for i in range(writes):
            await make_broadcastable_changes(
                last,
                lambda tx, i=i: [tx.execute(
                    "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                    [i, f"{scenario}-{i}"],
                )],
            )
            while got <= i:
                ev = await asyncio.wait_for(it.__anext__(), 30)
                if "change" in ev:
                    got += 1

        # full digest coverage on EVERY node, timed in digest rounds
        t0 = time.monotonic()
        assert await wait_until(
            lambda: all(
                len(a.observatory._store) == nodes for a in agents
            ),
            timeout=30.0,
        ), "digest coverage never completed"
        out["coverage_rounds"] = max(
            1, int((time.monotonic() - t0) / interval) + 1
        )

        # the exact-aggregation pin: the shared in-process registry
        # makes every node's cumulative stage histogram identical, so
        # once the gossiped digests have caught up with the last sample
        # the cluster merge must hold exactly nodes × the local counts
        # and reproduce the local quantiles bucket-for-bucket (merging
        # k identical histograms scales counts, never quantiles)
        local = lat.stage_hists(window_secs=None)
        rep = None

        def merged_caught_up() -> bool:
            nonlocal rep
            rep = first.observatory.cluster_report()
            return all(
                rep["stages"][s]["count"] == nodes * h.count
                for s, h in local.items()
            )

        assert await wait_until(
            merged_caught_up, timeout=15.0, step=interval
        ), {s: (rep["stages"][s]["count"], nodes * h.count)
            for s, h in local.items()}
        out["coverage"] = rep["coverage"]
        out["nodes_report"] = rep["nodes"]  # per-node digest roll-up rows
        assert rep["coverage"]["fresh"] == nodes, rep["coverage"]
        # the same rows over the wire: GET /v1/cluster on one node
        import aiohttp

        async with aiohttp.ClientSession() as sess:
            async with sess.get(
                f"http://{api.addrs[0]}/v1/cluster"
            ) as resp:
                assert resp.status == 200
                http_rep = await resp.json()
        assert http_rep["coverage"]["fresh"] == nodes
        assert len(http_rep["nodes"]) == nodes
        for stage, h in local.items():
            assert (
                http_rep["stages"][stage]["count"] == nodes * h.count
            ), (stage, http_rep["stages"][stage])
        for stage, h in local.items():
            crow = rep["stages"][stage]
            if h.count == 0:
                continue
            for q in lat.QUANTILES:
                assert crow[lat._qname(q)] == h.quantile(q), (
                    stage, q, crow, h.quantile(q),
                )
        out["stages"] = {
            s: {k: v for k, v in r.items()}
            for s, r in rep["stages"].items()
        }
        out["divergence_quiet"] = rep["divergence"]["divergent"]

        if scenario == "quiet":
            assert not rep["divergence"]["episode_open"]
            return out

        # -- fault injection ------------------------------------------------
        victim = names[-1]
        observers = agents[:-1]
        if scenario == "partition":
            for name in names[:-1]:
                net.partition(name, victim)
        else:  # churn: crash-style silence, then return
            net.take_down(victim)
        t0 = time.monotonic()

        async def sample_rounds(pred, cap_s: float) -> Optional[int]:
            """Poll once per digest round; rows feed `timeline`."""
            deadline = time.monotonic() + cap_s
            while time.monotonic() < deadline:
                if timeline is not None:
                    d = first.observatory.check_divergence()
                    timeline.append({
                        "t": round(time.monotonic() - t0, 2),
                        "groups": d["groups"],
                        "silent": len(d["silent"]),
                        "episode_open": d["episode_open"],
                    })
                if pred():
                    return max(
                        1, int((time.monotonic() - t0) / interval) + 1
                    )
                await asyncio.sleep(interval)
            return None

        detect = await sample_rounds(
            lambda: all(a.observatory._episode_open for a in observers),
            cap_s=30.0,
        )
        assert detect is not None, "divergence episode never opened"
        out["detect_rounds"] = detect
        out["detect_secs"] = round(time.monotonic() - t0, 2)
        await asyncio.sleep(max(0.0, hold_secs - (time.monotonic() - t0)))

        # -- heal -----------------------------------------------------------
        if scenario == "partition":
            for name in names[:-1]:
                net.heal(name, victim)
        else:
            net.bring_up(victim)
        t0 = time.monotonic()
        heal = await sample_rounds(
            lambda: not any(a.observatory._episode_open for a in agents),
            cap_s=30.0,
        )
        assert heal is not None, "divergence episode never cleared"
        out["heal_rounds"] = heal
        out["episodes"] = {
            names[i]: a.observatory._episodes
            for i, a in enumerate(agents)
        }
        # exactly ONE episode per node that observed the fault
        for a in observers:
            assert a.observatory._episodes == 1, out["episodes"]
        out["episodes_total"] = sum(
            a.observatory._episodes for a in agents
        )
        return out
    finally:
        for ag in agents:
            if ag.observatory is not None:
                # planned teardown: peers going quiet one by one must
                # not read as fresh divergence episodes
                ag.observatory.disarm()
        if it is not None:
            with contextlib.suppress(Exception):
                await it.aclose()
        if client is not None:
            await client.close()
        if api is not None:
            await api.stop()
        for ag in agents:
            await shutdown(ag)

"""Kernel-peer bridge: batched-kernel members as virtual SWIM peers.

SURVEY §2.6's TPU-native equivalence says the `Transport` seam lets "a
tpu-sim transport implement delivery as gather/scatter into per-member
inboxes" while real agents keep speaking the wire protocol. This module
makes that literal: a `KernelPeerBridge` registers every member of a
batched-kernel cluster (`models/cluster.ClusterSim` over `ops/swim.py`)
as a virtual peer address on a `MemNetwork`, and answers real agents'
SWIM datagrams (`net/gossip_codec.py`) straight from the kernel's array
state:

- PING → ACK iff the kernel's ground-truth `alive[j]` says so — a
  crashed simulated member goes silent exactly like a crashed process,
  so the REAL agent's own probe/suspicion pipeline detects it;
- ANNOUNCE → FEED with a packet-budgeted sample of virtual members
  (the reference's join snapshot, `broadcast/mod.rs` announce path);
- every reply piggybacks a random sample of virtual-member updates, so
  a real agent's member table epidemically absorbs a 10^3–10^5-member
  simulated population through nothing but the normal SWIM channel;
- PING_REQ / INDIRECT_PING for virtual targets are answered through the
  same lookup (the indirect-probe path works against simulated peers).

The kernel side needs no per-packet device work: replies are served from
a host snapshot of the ground-truth arrays (`refresh()` re-pulls after
`sim.step()` / crashes — two [N] transfers), which is what keeps one
bridge cheap enough to front hundreds of thousands of simulated members.

Membership is one-directional by design: real agents track the simulated
population; the fixed-shape kernel does not grow rows for real agents
(dynamic membership of the array world is `init_state`-time — see
`ops/swim.py`). That is the devcluster use case: scale the OBSERVED
cluster far past what real processes could provide
(`klukai-devcluster/src/main.rs:107-232` lineage).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

import numpy as np

from corrosion_tpu.net.gossip_codec import (
    MemberState,
    MemberUpdate,
    MsgKind,
    SwimMessage,
    decode_swim,
    encode_swim,
    fill_updates,
)
from corrosion_tpu.net.mem import MemNetwork
from corrosion_tpu.types.actor import Actor, ActorId, ClusterId
from corrosion_tpu.types.base import Timestamp


def sim_actor_id(j: int) -> ActorId:
    """Deterministic 16-byte id for virtual member j."""
    return ActorId(b"SIM" + j.to_bytes(13, "big"))


class KernelPeerBridge:
    """Registers kernel members as `sim:<j>` peers on a MemNetwork."""

    def __init__(
        self,
        net: MemNetwork,
        sim,
        cluster_id: int = 0,
        piggyback: int = 24,
        addr_prefix: str = "sim",
        seed: int = 0,
        gossip_down: bool = True,
    ):
        # gossip_down=False keeps the bridge silent about dead members
        # (like peers that haven't detected yet): the real agent must
        # then find them with its OWN probe/suspicion pipeline
        self.net = net
        self.sim = sim
        self.gossip_down = gossip_down
        self.n = sim.params.n
        self.cluster_id = ClusterId(cluster_id)
        self.piggyback = piggyback
        self.prefix = addr_prefix
        self._rng = np.random.default_rng(seed)
        self._alive: Optional[np.ndarray] = None
        self._inc: Optional[np.ndarray] = None
        # hot-update queue: member -> send count (reference max_transmissions
        # decay); fed by refresh() diffs, drained into every piggyback
        self._hot: Dict[int, int] = {}
        self.max_transmissions = 10
        self._fill_pos = 0  # rotating cursor for the completeness fill
        self._listeners: List = []
        self._actors: Dict[int, Actor] = {}
        self.refresh()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Register every virtual member's address on the network."""
        for j in range(self.n):
            listener = self.net.listener(self.addr(j))

            async def on_datagram(src: str, data: bytes, j=j) -> None:
                await self._handle(j, src, data)

            async def on_uni(src: str, data: bytes) -> None:
                pass  # virtual members don't ingest broadcasts

            async def on_bi(stream) -> None:
                stream.close()

            listener.serve(on_datagram, on_uni, on_bi)
            self._listeners.append(listener)

    async def stop(self) -> None:
        for listener in self._listeners:
            await listener.close()
        self._listeners.clear()

    def refresh(self) -> None:
        """Re-snapshot ground truth from the kernel arrays (call after
        sim.step() / crash / restart).  Members whose (alive, inc)
        changed since the last snapshot enter the hot-update queue:
        piggyback carries FRESH updates first with a send-count decay —
        the reference's dissemination shape (`broadcast/mod.rs:653-779`
        re-send decay), without which a dead member's DOWN only reaches
        a peer by uniform-random luck (~n/piggyback replies at scale)."""
        state = self.sim.state
        alive = np.asarray(state.alive).astype(bool)
        inc = np.asarray(state.inc, dtype=np.int32)
        if self._alive is not None:
            changed = np.nonzero(
                (alive != self._alive) | (inc != self._inc)
            )[0]
            for j in changed:
                self._hot[int(j)] = 0  # reset send count
        self._alive = alive
        self._inc = inc

    def crash(self, j: int) -> None:
        self.sim.crash(j)
        self.refresh()

    def restart(self, j: int) -> None:
        self.sim.restart(j)
        self.refresh()

    # -- identity ----------------------------------------------------------

    def addr(self, j: int) -> str:
        return f"{self.prefix}:{j}"

    def actor(self, j: int) -> Actor:
        a = self._actors.get(j)
        if a is None:
            a = Actor(
                id=sim_actor_id(j),
                addr=self.addr(j),
                ts=Timestamp(0),
                cluster_id=self.cluster_id,
                bump=0,
            )
            self._actors[j] = a
        return a

    # -- wire handling -------------------------------------------------------

    def _update_for(self, j: int) -> MemberUpdate:
        return MemberUpdate(
            self.actor(j),
            int(self._inc[j]),
            MemberState.ALIVE if self._alive[j] else MemberState.DOWN,
        )

    def _sample_updates(self, exclude: int) -> List[MemberUpdate]:
        """Piggyback: hot (recently changed) updates first with a
        send-count decay, then a random fill (size-capped by
        fill_updates at send time)."""
        out: List[MemberUpdate] = []
        if self._hot:
            spent = []
            for j, sent in self._hot.items():
                if j == exclude:
                    continue
                if not self._alive[j] and not self.gossip_down:
                    continue
                out.append(self._update_for(j))
                self._hot[j] = sent + 1
                if sent + 1 >= self.max_transmissions:
                    spent.append(j)
                if len(out) >= self.piggyback:
                    break
            for j in spent:
                self._hot.pop(j, None)
        # completeness fill: a rotating cursor sweep (foca's feed sends
        # consecutive member-list snapshots, not uniform samples) — a
        # uniform-random fill left mass absorption with a coupon-collector
        # tail (~n·H(n)/k replies; measured: the last 1% of 100k members
        # took as long as the first 80%)
        budget = self.piggyback - len(out)
        for _ in range(min(self.piggyback * 2, self.n)):
            j = self._fill_pos
            self._fill_pos = (self._fill_pos + 1) % self.n
            if j == exclude:
                continue
            if not self._alive[j] and not self.gossip_down:
                continue
            out.append(self._update_for(j))
            budget -= 1
            if budget <= 0:
                break
        return out

    async def _reply(self, j: int, dst: str, msg: SwimMessage) -> None:
        # exact packet budgeting (incl. target/origin actors) is shared
        # with the agent's announce path: gossip_codec.fill_updates
        fill_updates(msg, self._sample_updates(j))
        transport = self.net.transport(self.addr(j))
        await transport.send_datagram(dst, encode_swim(msg))

    async def _handle(self, j: int, src: str, data: bytes) -> None:
        if not self._alive[j]:
            return  # crashed members are silent
        try:
            msg = decode_swim(data)
        except (ValueError, struct.error):
            return
        me = self.actor(j)
        k = msg.kind
        if k == MsgKind.PING:
            await self._reply(
                j, msg.sender.addr,
                SwimMessage(MsgKind.ACK, msg.probe_no, me),
            )
        elif k == MsgKind.ANNOUNCE:
            await self._reply(
                j, msg.sender.addr,
                SwimMessage(MsgKind.FEED, 0, me),
            )
        elif k == MsgKind.PING_REQ and msg.target is not None:
            # asked to indirect-probe `target` for `sender`: if the target
            # is one of ours, answer from the arrays; else forward a real
            # INDIRECT_PING so mixed topologies keep working
            tj = self._index_of(msg.target.addr)
            if tj is not None:
                if self._alive[tj]:
                    await self._reply(
                        j, msg.sender.addr,
                        SwimMessage(
                            MsgKind.FORWARDED_ACK, msg.probe_no,
                            self.actor(tj), origin=msg.sender,
                        ),
                    )
            else:
                await self._reply(
                    j, msg.target.addr,
                    SwimMessage(
                        MsgKind.INDIRECT_PING, msg.probe_no, me,
                        target=msg.target, origin=msg.sender,
                    ),
                )
        elif k == MsgKind.INDIRECT_PING and msg.origin is not None:
            await self._reply(
                j, msg.sender.addr,
                SwimMessage(
                    MsgKind.INDIRECT_ACK, msg.probe_no, me,
                    origin=msg.origin,
                ),
            )
        elif k == MsgKind.INDIRECT_ACK and msg.origin is not None:
            # the relay leg back: a REAL target we indirect-probed on a
            # real agent's behalf answered — forward like membership.py's
            # helper path does (membership.py:384-393), else the origin
            # falsely suspects a live peer
            await self._reply(
                j, msg.origin.addr,
                SwimMessage(
                    MsgKind.FORWARDED_ACK, msg.probe_no, me,
                    target=msg.sender,
                ),
            )
        # ACK / FEED / LEAVE / FORWARDED_ACK aimed at a virtual member
        # need no reaction: the kernel's own protocol state advances in
        # sim.step(), not per packet

    def _index_of(self, addr: str) -> Optional[int]:
        if not addr.startswith(self.prefix + ":"):
            return None
        try:
            j = int(addr.rsplit(":", 1)[1])
        except ValueError:
            return None
        return j if 0 <= j < self.n else None

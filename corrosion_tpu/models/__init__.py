"""Simulation models: batched SWIM clusters (the devcluster scale engine)."""

from corrosion_tpu.models.cluster import ClusterSim

__all__ = ["ClusterSim"]

"""Anti-entropy sync protocol: state summaries and need computation.

Behavioral counterpart of `klukai-types/src/sync.rs` (SyncStateV1,
compute_available_needs, generate_sync) and the client/server loops in
`klukai-agent/src/api/peer/mod.rs:1082,1485`. The set algebra here is the
correctness-critical piece: given my summary and a peer's summary, derive
exactly which version ranges and seq sub-ranges to request.

Wire shapes live in `corrosion_tpu.types.codec` (SyncState/NeedFull/
NeedPartial/NeedEmpty); this module supplies the algebra + generation from
a Bookie.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from corrosion_tpu.store.bookkeeping import Bookie
from corrosion_tpu.types.actor import ActorId
from corrosion_tpu.types.base import Timestamp
from corrosion_tpu.types.codec import (
    NeedEmpty,
    NeedFull,
    NeedPartial,
    SyncState,
)
from corrosion_tpu.types.rangeset import RangeSet

Range = Tuple[int, int]


def generate_sync(bookie: Bookie, actor_id: ActorId) -> SyncState:
    """Summarize what we have/need per origin actor (sync.rs:446-540)."""
    heads: Dict[ActorId, int] = {}
    need: Dict[ActorId, List[Range]] = {}
    partial_need: Dict[ActorId, Dict[int, List[Range]]] = {}

    for aid, booked in bookie.items().items():
        with booked.read() as bv:
            last = bv.last()
            if last is None:
                continue
            heads[aid] = last
            needed = list(bv.needed)
            if needed:
                need[aid] = needed
            partials = {
                version: list(pv.gaps())
                for version, pv in bv.partials.items()
                if not pv.is_complete()
            }
            partials = {v: g for v, g in partials.items() if g}
            if partials:
                partial_need[aid] = partials

    return SyncState(
        actor_id=actor_id,
        heads=heads,
        need=need,
        partial_need=partial_need,
        last_cleared_ts=None,
    )


def compute_available_needs(
    ours: SyncState, theirs: SyncState
) -> Dict[ActorId, List[object]]:
    """What can we usefully request from this peer? (sync.rs:126-248)

    For every origin actor the peer has heard of:
      - intersect our needed gaps with the versions the peer *fully* has
        (their head minus their own needs/partials)
      - for our partial versions: request remaining seqs if the peer has
        the version fully, or the seq overlap both of us are missing-less
        (peer further along the same partial)
      - request everything above our head up to their head
    """
    needs: Dict[ActorId, List[object]] = {}

    for actor_id, head in theirs.heads.items():
        if actor_id == ours.actor_id:
            continue
        if head == 0:
            continue

        other_haves = RangeSet([(1, head)])
        for s, e in theirs.need.get(actor_id, ()):
            other_haves.remove(s, e)
        for v in theirs.partial_need.get(actor_id, {}):
            other_haves.remove(v, v)

        our_need = ours.need.get(actor_id)
        if our_need:
            for s, e in our_need:
                for os_, oe in other_haves.overlapping(s, e):
                    needs.setdefault(actor_id, []).append(
                        NeedFull((max(s, os_), min(e, oe)))
                    )

        our_partials = ours.partial_need.get(actor_id)
        if our_partials:
            for version, seq_gaps in our_partials.items():
                if other_haves.contains(version):
                    needs.setdefault(actor_id, []).append(
                        NeedPartial(version, tuple(seq_gaps))
                    )
                    continue
                their_gaps = theirs.partial_need.get(actor_id, {}).get(version)
                if their_gaps:
                    # the peer is also partial on this version: request only
                    # the seqs we're missing that the peer is NOT missing
                    max_their = max(e for _, e in their_gaps)
                    max_ours = max(e for _, e in seq_gaps)
                    end = max(max_their, max_ours)
                    their_haves = RangeSet([(0, end)])
                    for s, e in their_gaps:
                        their_haves.remove(s, e)
                    seqs: List[Range] = []
                    for s, e in seq_gaps:
                        for os_, oe in their_haves.overlapping(s, e):
                            seqs.append((max(s, os_), min(e, oe)))
                    if seqs:
                        needs.setdefault(actor_id, []).append(
                            NeedPartial(version, tuple(seqs))
                        )

        our_head = ours.heads.get(actor_id)
        if our_head is None:
            needs.setdefault(actor_id, []).append(NeedFull((1, head)))
        elif head > our_head:
            needs.setdefault(actor_id, []).append(NeedFull((our_head + 1, head)))

    return needs


def need_count(need) -> int:
    if isinstance(need, NeedFull):
        return need.versions[1] - need.versions[0] + 1
    return 1


def state_need_len(state: SyncState) -> int:
    """Total version-count a node is missing (sync.rs:89-107); used for
    peer choice ordering in the sync scheduler."""
    total = sum(
        e - s + 1 for ranges in state.need.values() for s, e in ranges
    )
    partial_chunks = (
        sum(
            e - s + 1
            for versions in state.partial_need.values()
            for ranges in versions.values()
            for s, e in ranges
        )
        // 50
    )
    return total + partial_chunks


def held_total(bookie: Bookie) -> int:
    """Versions this node actually HOLDS across all origin actors: head
    minus needed gaps minus incomplete partials.  The local half of the
    r17 snapshot-bootstrap gap heuristic (the remote half is a peer's
    digest-advertised `heads_total` or a probed SyncState)."""
    total = 0
    for _aid, booked in bookie.items().items():
        with booked.read() as bv:
            last = bv.last()
            if last is None:
                continue
            total += last
            total -= sum(e - s + 1 for s, e in bv.needed)
            total -= sum(
                1 for p in bv.partials.values() if not p.is_complete()
            )
    return total


def state_held_total(state: SyncState) -> int:
    """Versions a peer holds, from its sync summary — what a state
    probe yields when no digest has arrived yet (cold boot)."""
    total = sum(state.heads.values())
    total -= sum(
        e - s + 1 for ranges in state.need.values() for s, e in ranges
    )
    total -= sum(len(v) for v in state.partial_need.values())
    return total


def chunk_range(start: int, end: int, size: int) -> List[Range]:
    """Split an inclusive version range into ≤size chunks
    (peer/mod.rs:986-1004)."""
    out = []
    s = start
    while s <= end:
        e = min(s + size - 1, end)
        out.append((s, e))
        s = e + 1
    return out

"""Python client library for the HTTP API.

Counterpart of `klukai-client` (`crates/klukai-client/src/lib.rs:33-420`,
`src/sub.rs`): execute/query/schema plus line-framed NDJSON streams for
queries, subscriptions and table updates. `SubscriptionStream` tracks the
last observed ChangeId and transparently reconnects + resubscribes from
it on gap or disconnect (`sub.rs:328-388`).

Protocol note: the reference client is HTTP/2-only (`lib.rs:33-47`,
hyper with `http2_only(true)`, keep-alive PINGs every 10 s). This client
matches it: by default requests ride one multiplexed h2c connection
(`net/h2.py` — the in-repo HTTP/2 implementation; the server front-end
`api/h2front.py` speaks both protocols on the API port). `http2=False`
falls back to aiohttp HTTP/1.1 with per-stream keep-alive connections —
identical paths, headers, and NDJSON framing either way.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import sqlite3
import threading
import urllib.parse
from typing import Any, AsyncIterator, Dict, List, Optional

import aiohttp

from corrosion_tpu.net.h2 import H2Client, StreamReset
from corrosion_tpu.runtime.backoff import Backoff


def _reconnect_backoff():
    """Full-jitter reconnect pacing (the r9 announcer discipline,
    runtime/backoff.py): when an agent restart drops thousands of
    subscription streams at once, deterministic doubling would re-dial
    them all in the same beat at exactly the moment the server is
    busiest re-admitting.  Uniform-in-[0, base] spreads the stampede;
    the cap keeps a capped-retry stream's total stall bounded."""
    return iter(Backoff(
        min_interval=0.2, max_interval=2.0, factor=2.0,
        mode="full", retries=None,
    ))


class _H2Resp:
    """Duck-typed slice of aiohttp.ClientResponse the client uses:
    .status, .headers.get, .text(), .content.iter_any()."""

    def __init__(self, resp):
        self._resp = resp
        self.status = resp.status
        self.headers = resp.headers

    async def text(self) -> str:
        return (await self._resp.read()).decode()

    async def json(self) -> Any:
        return json.loads(await self._resp.read())

    @property
    def content(self) -> "_H2Resp":
        return self

    def iter_any(self) -> AsyncIterator[bytes]:
        return self._resp.body()


class _H2Ctx:
    def __init__(self, session: "_H2Session", method: str, url: str,
                 json_body: Any, params: Optional[Dict[str, str]]):
        self._session = session
        self._method = method
        self._url = url
        self._json = json_body
        self._params = params
        self._resp = None

    async def __aenter__(self) -> _H2Resp:
        split = urllib.parse.urlsplit(self._url)
        path = split.path or "/"
        qs = split.query
        if self._params:
            extra = urllib.parse.urlencode(self._params)
            qs = f"{qs}&{extra}" if qs else extra
        if qs:
            path = f"{path}?{qs}"
        body = b""
        if self._json is not None:
            body = json.dumps(self._json).encode()
        try:
            # bound connect+send+response-headers like the h1 session's
            # total timeout did — a wedged server must not hang callers
            # forever just because its TCP + PINGs stay healthy. (Body
            # streaming is deliberately unbounded: subscriptions are
            # infinite by design and reconnect on transport errors.)
            self._resp = await asyncio.wait_for(
                self._session.h2.request(
                    self._method, path,
                    headers=self._session.headers, body=body,
                ),
                self._session.request_timeout,
            )
        except (StreamReset, ConnectionError, OSError, asyncio.TimeoutError) as e:
            # surface transport failures as the retry-able client error
            # type the reconnect loops already handle
            raise aiohttp.ClientConnectionError(str(e)) from e
        return _H2Resp(self._resp)

    async def __aexit__(self, *exc) -> None:
        if self._resp is not None:
            await self._resp.aclose()


class _H2Session:
    """aiohttp.ClientSession-shaped facade over one multiplexed H2Client."""

    def __init__(self, host: str, port: int, headers: Dict[str, str],
                 request_timeout: float = 300.0):
        self.h2 = H2Client(host, port)
        self.headers = headers
        self.request_timeout = request_timeout
        self.closed = False

    def post(self, url: str, json: Any = None,
             params: Optional[Dict[str, str]] = None) -> _H2Ctx:
        return _H2Ctx(self, "POST", url, json, params)

    def get(self, url: str,
            params: Optional[Dict[str, str]] = None) -> _H2Ctx:
        return _H2Ctx(self, "GET", url, None, params)

    async def close(self) -> None:
        self.closed = True
        await self.h2.close()


class CorrosionApiClient:
    def __init__(self, addr: str, token: Optional[str] = None,
                 http2: bool = True):
        self.base = f"http://{addr}"
        self.http2 = http2
        host, sep, port = addr.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]  # [::1]:8080 — open_connection wants ::1
        if sep and port.isdigit():
            self._host, self._port = host or "127.0.0.1", int(port)
        else:  # bare hostname: default http port, as the h1 path resolves it
            self._host, self._port = addr, 80
        self._headers = {"content-type": "application/json"}
        if token:
            self._headers["authorization"] = f"Bearer {token}"
        self._session = None

    async def _ensure(self):
        if self._session is None or self._session.closed:
            if self.http2:
                self._session = _H2Session(
                    self._host, self._port, self._headers
                )
            else:
                self._session = aiohttp.ClientSession(headers=self._headers)
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def __aenter__(self) -> "CorrosionApiClient":
        await self._ensure()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- one-shot calls ----------------------------------------------------

    @staticmethod
    def _timeout_params(timeout: Optional[float]) -> Optional[Dict[str, str]]:
        # the reference client threads ?timeout= through query_typed /
        # execute (lib.rs:53-58); the server interrupts overruns
        return {"timeout": str(timeout)} if timeout else None

    async def execute(
        self, statements: List[Any], timeout: Optional[float] = None
    ) -> Dict[str, Any]:
        s = await self._ensure()
        async with s.post(
            f"{self.base}/v1/transactions", json=statements,
            params=self._timeout_params(timeout),
        ) as resp:
            body = await _body_json(resp)
            if resp.status >= 400:
                raise ClientError(resp.status, body)
            return body

    async def schema(self, statements: List[str]) -> Dict[str, Any]:
        s = await self._ensure()
        async with s.post(
            f"{self.base}/v1/migrations", json=statements
        ) as resp:
            body = await _body_json(resp)
            if resp.status >= 400:
                raise ClientError(resp.status, body)
            return body

    async def schema_from_paths(self, paths: List[str]) -> Dict[str, Any]:
        stmts = []
        for p in paths:
            with open(p) as f:
                stmts.append(f.read())
        return await self.schema(stmts)

    async def table_stats(
        self, tables: Optional[List[str]] = None
    ) -> Dict[str, Any]:
        s = await self._ensure()
        async with s.post(
            f"{self.base}/v1/table_stats", json={"tables": tables or []}
        ) as resp:
            return await resp.json()

    async def query(
        self, statement: Any, timeout: Optional[float] = None
    ) -> AsyncIterator[Dict[str, Any]]:
        """Stream QueryEvents for one statement."""
        s = await self._ensure()
        async with s.post(
            f"{self.base}/v1/queries", json=statement,
            params=self._timeout_params(timeout),
        ) as resp:
            if resp.status >= 400:
                raise ClientError(resp.status, await _body_json(resp))
            async for line in _lines(resp):
                yield json.loads(line)

    async def query_rows(self, statement: Any) -> List[List[Any]]:
        """Convenience: collect just the row values."""
        rows = []
        async for ev in self.query(statement):
            if "row" in ev:
                rows.append(ev["row"][1])
            elif "error" in ev:
                raise ClientError(200, ev)
        return rows

    async def profile(
        self, window: Optional[float] = None, format: str = "json"
    ) -> Any:
        """Continuous-profiling plane (r23): the node's folded-stack
        profile.  `format="json"` (default) returns the summary dict,
        `"speedscope"` the speedscope.app document (dict),
        `"folded"` collapsed-stack text (str)."""
        s = await self._ensure()
        params: Dict[str, str] = {"format": format}
        if window is not None:
            params["window"] = str(float(window))
        async with s.get(
            f"{self.base}/v1/profile", params=params
        ) as resp:
            if resp.status >= 400:
                raise ClientError(resp.status, await resp.text())
            if format == "folded":
                return await resp.text()
            return await resp.json()

    # -- streams -----------------------------------------------------------

    def subscribe(
        self,
        statement: Any,
        skip_rows: bool = False,
        from_change: Optional[int] = None,
        raw: bool = False,
    ) -> "SubscriptionStream":
        """`raw=True` yields undecoded NDJSON lines (str) instead of
        parsed dicts — the high-throughput observer mode: no json.loads
        per event, change ids still tracked for reconnect via a cheap
        tail parse."""
        return SubscriptionStream(
            self, statement, skip_rows, from_change, raw
        )

    async def updates(self, table: str) -> AsyncIterator[Dict[str, Any]]:
        s = await self._ensure()
        async with s.post(f"{self.base}/v1/updates/{table}") as resp:
            if resp.status >= 400:
                raise ClientError(resp.status, await resp.text())
            async for line in _lines(resp):
                yield json.loads(line)


class ClientError(Exception):
    def __init__(self, status: int, body: Any):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class SubShedError(Exception):
    """The server SHED this stream as a laggard (`{"lagging": ...}`
    terminal frame, r16 admission control): the subscription is healthy
    but our socket fell behind the live fan-out.  `SubscriptionStream`
    treats it as a retryable disconnect and resumes from the last
    observed change id — the matcher's changes log replays what the
    shed dropped."""

    def __init__(self, lag: Any):
        super().__init__(f"stream shed as laggard: {lag}")
        self.lag = lag


class SubscriptionStream:
    """Auto-resubscribing NDJSON event stream (client/src/sub.rs:328-388).

    Iterate to receive QueryEvents; on disconnect or ChangeId gap the
    stream reconnects by query-id from `last_change_id`.
    """

    def __init__(self, client, statement, skip_rows, from_change, raw=False):
        self.client = client
        self.statement = statement
        self.skip_rows = skip_rows
        self.raw = raw
        self.last_change_id: Optional[int] = from_change
        self.query_id: Optional[str] = None
        self._max_retries = 5

    def __aiter__(self) -> AsyncIterator[Dict[str, Any]]:
        return self._run()

    async def _run(self):
        retries = 0
        boff = _reconnect_backoff()
        while True:
            try:
                async for ev in self._connect_once():
                    if retries:
                        # progress: the retry budget AND the backoff
                        # ramp both restart from the bottom
                        retries = 0
                        boff = _reconnect_backoff()
                    yield ev
                return  # server ended the stream cleanly
            except SubShedError:
                # shed as a laggard: resume from last_change_id — the
                # server replays the gap from the matcher's changes log
                # (a pruned-away id surfaces as the documented
                # resubscribe-anew error).  Retry-capped like any other
                # disconnect so a chronically slow consumer surfaces
                # the error instead of thrashing subscribe/shed cycles.
                retries += 1
                if self.query_id is None or retries > self._max_retries:
                    raise
                await asyncio.sleep(next(boff))
            except (aiohttp.ClientError, asyncio.TimeoutError, ClientError,
                    StreamReset, ConnectionError):
                # a mid-request agent restart lands here as a TYPED
                # retryable error (the h2 session's wait_for + the
                # transport error set — never a hang); past the retry
                # cap it surfaces to the caller (pinned in
                # tests/test_chaos.py)
                retries += 1
                if self.query_id is None or retries > self._max_retries:
                    raise
                await asyncio.sleep(next(boff))

    async def _connect_once(self):
        s = await self.client._ensure()
        if self.query_id is not None:
            url = f"{self.client.base}/v1/subscriptions/{self.query_id}"
            params = {}
            if self.last_change_id is not None:
                params["from"] = str(self.last_change_id)
            if self.skip_rows:
                params["skip_rows"] = "true"
            ctx = s.get(url, params=params)
        else:
            params = {}
            if self.skip_rows:
                params["skip_rows"] = "true"
            if self.last_change_id is not None:
                params["from"] = str(self.last_change_id)
            ctx = s.post(
                f"{self.client.base}/v1/subscriptions",
                json=self.statement,
                params=params,
            )
        async with ctx as resp:
            if resp.status >= 400:
                raise ClientError(resp.status, await resp.text())
            qid = resp.headers.get("corro-query-id")
            if qid:
                self.query_id = qid
            # a server ending the stream ALWAYS writes a terminal frame
            # first ({"error": ...} or {"lagging": ...}); a bare EOF
            # means the transport died mid-stream (or a shed laggard's
            # terminal frame could not be delivered through its clogged
            # socket) — treated as a retryable disconnect below, the
            # reference client's hangup-reconnect behavior (sub.rs)
            terminal = False
            async for line in _lines(resp):
                if self.raw:
                    # change lines end `...,<change_id>]}`: track the id
                    # without decoding the event (reconnect still works)
                    if line.startswith('{"change":['):
                        try:
                            self.last_change_id = int(
                                line[:-2].rsplit(",", 1)[1]
                            )
                        except (ValueError, IndexError):
                            pass
                    elif line.startswith('{"lagging":'):
                        raise SubShedError(line)
                    elif line.startswith('{"error":'):
                        terminal = True
                    yield line
                    continue
                ev = json.loads(line)
                if "change" in ev:
                    self.last_change_id = ev["change"][3]
                elif "eoq" in ev and ev["eoq"].get("change_id") is not None:
                    self.last_change_id = ev["eoq"]["change_id"]
                elif "lagging" in ev:
                    raise SubShedError(ev["lagging"])
                elif "error" in ev:
                    terminal = True
                yield ev
            if not terminal and self.query_id is not None:
                raise ConnectionResetError(
                    "subscription stream ended without a terminal frame"
                )


async def _lines(resp) -> AsyncIterator[str]:
    buf = b""
    async for chunk in resp.content.iter_any():
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                yield line.decode()
    if buf.strip():
        yield buf.decode()


async def _body_json(resp) -> Any:
    raw = await resp.text()
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return raw


class CorrosionClient(CorrosionApiClient):
    """API client + a direct read-only sqlite pool over the agent's local
    database file — the reference's `CorrosionClient`
    (`klukai-client/src/lib.rs:365-403`): writes go through the HTTP API
    (the only correct write path), while local reads skip HTTP entirely.
    The consul-sync sidecar is the canonical user.

    The pool holds up to `pool_size` lazily-opened read-only connections
    (reference default 5); `read()` checks one out as a context manager.
    """

    def __init__(
        self,
        addr: str,
        db_path: str,
        token: Optional[str] = None,
        pool_size: int = 5,
    ):
        super().__init__(addr, token=token)
        self.db_path = db_path
        self._pool_size = pool_size
        self._pool: List["sqlite3.Connection"] = []
        self._pool_lock = threading.Lock()

    def _open_read_conn(self):
        import sqlite3

        conn = sqlite3.connect(
            f"file:{self.db_path}?mode=ro",
            uri=True,
            check_same_thread=False,
        )
        conn.row_factory = sqlite3.Row
        return conn

    @contextlib.contextmanager
    def read(self):
        """Check a read-only connection out of the local pool."""
        with self._pool_lock:
            conn = self._pool.pop() if self._pool else None
        if conn is None:
            conn = self._open_read_conn()
        try:
            yield conn
        finally:
            with self._pool_lock:
                if len(self._pool) < self._pool_size:
                    self._pool.append(conn)
                    conn = None
            if conn is not None:
                conn.close()

    def local_query(self, sql: str, params=()) -> List[tuple]:
        """Convenience: run a read-only query against the local db."""
        with self.read() as conn:
            return [tuple(r) for r in conn.execute(sql, params).fetchall()]

    async def close(self) -> None:
        await super().close()
        with self._pool_lock:
            for conn in self._pool:
                conn.close()
            self._pool.clear()

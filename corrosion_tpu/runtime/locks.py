"""Instrumented read/write locks with a central registry + watchdog.

Counterpart of the reference's `CountedTokioRwLock`/`LockRegistry`
(`klukai-types/src/agent.rs:707-1066`) and the setup-time watchdog
(`klukai-agent/src/agent/setup.rs:188-246`): every acquisition registers
{label, kind, state, started_at} in an ordered map so an operator can see,
live, which bookie/member locks are held or queued and for how long. A
watchdog task logs any lock held longer than 10 s and bumps a metric at
60 s (the reference fires an Antithesis invariant there).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from corrosion_tpu.runtime.metrics import METRICS

log = logging.getLogger(__name__)

_WARN_HELD_S = 10.0
_INVARIANT_HELD_S = 60.0


@dataclass
class LockMeta:
    id: int
    label: str
    kind: str  # "read" | "write"
    state: str  # "acquiring" | "locked"
    started_at: float

    def held_for(self) -> float:
        return time.monotonic() - self.started_at


class LockRegistry:
    """Ordered map of live lock acquisitions (agent.rs:760-818)."""

    def __init__(self):
        self._ids = itertools.count(1)
        self._live: Dict[int, LockMeta] = {}

    def register(self, label: str, kind: str) -> LockMeta:
        meta = LockMeta(
            id=next(self._ids),
            label=label,
            kind=kind,
            state="acquiring",
            started_at=time.monotonic(),
        )
        self._live[meta.id] = meta
        return meta

    def acquired(self, meta: LockMeta) -> None:
        meta.state = "locked"
        meta.started_at = time.monotonic()

    def release(self, meta: LockMeta) -> None:
        self._live.pop(meta.id, None)

    def snapshot(self, top: Optional[int] = None) -> List[LockMeta]:
        """Longest-held first (the admin `locks` command view)."""
        items = sorted(self._live.values(), key=lambda m: m.started_at)
        return items[:top] if top is not None else items

    async def watchdog(self, interval: float = 1.0) -> None:
        """Logs locks held > 10 s; metric at 60 s (setup.rs:188-246)."""
        warned = set()
        while True:
            await asyncio.sleep(interval)
            for meta in list(self._live.values()):
                held = meta.held_for()
                if held > _WARN_HELD_S and meta.id not in warned:
                    warned.add(meta.id)
                    log.warning(
                        "lock %s (%s/%s) %s for %.1fs",
                        meta.id, meta.label, meta.kind, meta.state, held,
                    )
                if held > _INVARIANT_HELD_S:
                    from corrosion_tpu.runtime.invariants import (
                        InvariantViolation,
                        assert_always,
                    )

                    # metric FIRST — it must fire even in strict mode
                    METRICS.counter(
                        "corro_lock_held_over_invariant", label=meta.label
                    ).inc()
                    # ref assert_always: no lock held past 60s
                    # (setup.rs:231). Contained: strict mode must not
                    # kill the watchdog task itself — the violation is
                    # recorded and monitoring continues
                    try:
                        assert_always(
                            False,
                            "locks.held_under_60s",
                            {"label": meta.label, "held_s": round(held, 1)},
                        )
                    except InvariantViolation:
                        log.error(
                            "lock invariant violated (watchdog continues): "
                            "%s held %.1fs", meta.label, held,
                        )
            warned &= set(self._live)


class CountedRwLock:
    """Async RW lock whose acquisitions are tracked in a LockRegistry.

    Writer-preferring: readers queue behind a waiting writer, matching
    tokio::sync::RwLock fairness closely enough for our uses. `blocking_*`
    variants from the reference (used off the async runtime) map to the
    same async methods here — the whole runtime is one event loop.
    """

    def __init__(self, registry: LockRegistry, label: str):
        self._registry = registry
        self._label = label
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self._cond = asyncio.Condition()

    def read(self, label_extra: str = "") -> "_Guard":
        return _Guard(self, "read", label_extra)

    def write(self, label_extra: str = "") -> "_Guard":
        return _Guard(self, "write", label_extra)

    async def _acquire(self, kind: str) -> None:
        async with self._cond:
            if kind == "read":
                while self._writer or self._writers_waiting:
                    await self._cond.wait()
                self._readers += 1
            else:
                self._writers_waiting += 1
                try:
                    while self._writer or self._readers:
                        await self._cond.wait()
                finally:
                    self._writers_waiting -= 1
                    self._cond.notify_all()
                self._writer = True

    async def _release(self, kind: str) -> None:
        async with self._cond:
            if kind == "read":
                self._readers -= 1
            else:
                self._writer = False
            self._cond.notify_all()


class _Guard:
    def __init__(self, lock: CountedRwLock, kind: str, label_extra: str):
        self._lock = lock
        self._kind = kind
        self._label = lock._label + (f":{label_extra}" if label_extra else "")
        self._meta: Optional[LockMeta] = None

    async def __aenter__(self) -> "_Guard":
        self._meta = self._lock._registry.register(self._label, self._kind)
        try:
            await self._lock._acquire(self._kind)
        except BaseException:
            # cancelled while queued: drop the registry entry, don't leak
            self._lock._registry.release(self._meta)
            self._meta = None
            raise
        self._lock._registry.acquired(self._meta)
        return self

    async def __aexit__(self, *exc) -> None:
        await self._lock._release(self._kind)
        if self._meta is not None:
            self._lock._registry.release(self._meta)

"""Lightweight distributed tracing with W3C context propagation.

Counterpart of the reference's tracing stack (SURVEY §5): `tracing` spans
with OpenTelemetry OTLP export, and W3C `traceparent`/`tracestate`
propagated across the sync protocol inside `SyncTraceContextV1`
(`klukai-types/src/sync.rs:33-67`, injected `peer/mod.rs:1098-1101`,
extracted `peer/mod.rs:1494-1496`).

Spans are contextvar-scoped, duration-histogrammed into the metrics
registry, and logged at DEBUG. The wire format is real W3C traceparent,
so traces stitch across nodes. When an OTLP endpoint is configured
(`runtime/otel.py` — a dependency-free OTLP/HTTP JSON exporter, since the
image ships no OTel SDK), finished spans are batch-exported to it the way
the reference's BatchSpanProcessor does.
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from corrosion_tpu.runtime import otel
from corrosion_tpu.runtime import profiler as _profiler
from corrosion_tpu.runtime.metrics import METRICS

log = logging.getLogger(__name__)

_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True

    def traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"


def parse_traceparent(tp: Optional[str]) -> Optional[SpanContext]:
    if not tp:
        return None
    m = _TRACEPARENT.match(tp.strip())
    if m is None:
        return None
    _ver, trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, sampled=flags != "00")


_current: contextvars.ContextVar[Optional[SpanContext]] = contextvars.ContextVar(
    "corro_trace", default=None
)


def _rand_hex(n: int) -> str:
    return os.urandom(n // 2).hex()


# -- tail-sampling trace meta (the envelope ext v3 byte) --------------------
#
# One byte rides the broadcast/sync envelopes next to the traceparent
# (`types/codec.py` _ENVELOPE_EXT_V3): bit 0 carries the ORIGIN's head
# decision (lottery win → every node on the path keeps the trace without
# coordination), bits 2..7 the relay hop count (capped at 63) so a
# remote apply span can say how many re-broadcasts it is from the
# origin.  Bits 1 is reserved.

TRACE_META_FORCED = 0x01
_META_HOP_SHIFT = 2
_META_HOP_MAX = 63


def meta_forced(meta: Optional[int]) -> bool:
    return bool(meta) and bool(meta & TRACE_META_FORCED)


def meta_hop(meta: Optional[int]) -> int:
    return ((meta or 0) >> _META_HOP_SHIFT) & _META_HOP_MAX


def make_meta(forced: bool = False, hop: int = 0) -> int:
    return (TRACE_META_FORCED if forced else 0) | (
        min(_META_HOP_MAX, max(0, hop)) << _META_HOP_SHIFT
    )


def bump_hop(meta: Optional[int]) -> Optional[int]:
    """Relay path: same flags, hop + 1 (saturating)."""
    if meta is None:
        return None
    return (meta & ((1 << _META_HOP_SHIFT) - 1)) | (
        min(_META_HOP_MAX, meta_hop(meta) + 1) << _META_HOP_SHIFT
    )


def current_context() -> Optional[SpanContext]:
    return _current.get()


def current_traceparent() -> Optional[str]:
    ctx = _current.get()
    return ctx.traceparent() if ctx is not None else None


class Span:
    """Context manager: opens a child span of the ambient context (or a
    fresh trace), times it, histograms + logs the duration."""

    def __init__(
        self,
        name: str,
        parent: Optional[SpanContext] = None,
        attrs: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.attrs = attrs or {}
        ambient = parent if parent is not None else _current.get()
        self.ctx = SpanContext(
            trace_id=ambient.trace_id if ambient else _rand_hex(32),
            span_id=_rand_hex(16),
            sampled=ambient.sampled if ambient else True,
        )
        self.parent = ambient
        self._token: Optional[contextvars.Token] = None
        self._start = 0.0

    def __enter__(self) -> "Span":
        self._token = _current.set(self.ctx)
        self._start = time.monotonic()
        self._start_ns = time.time_ns()
        return self

    def __exit__(self, et, e, tb) -> None:
        elapsed = time.monotonic() - self._start
        if self._token is not None:
            _current.reset(self._token)
        METRICS.histogram("corro_span_seconds", span=self.name).observe(elapsed)
        if self.ctx.sampled:
            _finish_span(
                self.name,
                self.ctx,
                self.parent.span_id if self.parent is not None else None,
                self._start_ns,
                self._start_ns + int(elapsed * 1e9),
                self.attrs,
                error=et is not None,
            )
        log.debug(
            "span %s trace=%s span=%s %.6fs%s %s",
            self.name,
            self.ctx.trace_id,
            self.ctx.span_id,
            elapsed,
            " ERROR" if et is not None else "",
            self.attrs,
        )


def _finish_span(
    name: str,
    ctx: SpanContext,
    parent_span_id: Optional[str],
    start_ns: int,
    end_ns: int,
    attrs: Dict[str, str],
    error: bool = False,
    forced: bool = False,
) -> None:
    """Route one finished span: stage-tagged spans buffer in the tail
    sampler's per-trace ring (`runtime/tracestore.py`) when one is
    configured — exported only if the trace is KEPT — while untagged
    spans keep the r11 direct-export path.  The unconfigured hot path
    pays one global None-check (the cached head-decision discipline)."""
    stage = attrs.get("stage")
    if stage is not None:
        from corrosion_tpu.runtime import tracestore

        store = tracestore.store()
        if store is not None:
            store.add_span(
                {
                    "name": name,
                    "trace_id": ctx.trace_id,
                    "span_id": ctx.span_id,
                    "parent_span_id": parent_span_id,
                    "start_ns": start_ns,
                    "end_ns": end_ns,
                    "attrs": attrs,
                    "error": error,
                    "forced": forced,
                }
            )
            return
    if otel.exporter() is not None:
        otel.record_span(
            name, ctx.trace_id, ctx.span_id, parent_span_id,
            start_ns, end_ns, attrs, error=error,
        )


def span(name: str, **attrs: str) -> Span:
    return Span(name, attrs={k: str(v) for k, v in attrs.items()})


def stage_span(
    traceparent: Optional[str],
    name: str,
    stage: str,
    duration_s: float,
    error: bool = False,
    forced: bool = False,
    **attrs,
) -> Optional[SpanContext]:
    """Synthesize one finished STAGE span as a child of the wire
    context, covering the last `duration_s` seconds (hop stamps measure
    origin→here wall deltas; a contextvar-scoped Span cannot represent
    that interval).  The hot-path cost when no store/exporter is
    configured is one parse + two global None-checks; callers on
    per-sink walks stride-sample (pubsub/fanout.py)."""
    parent = parse_traceparent(traceparent)
    if parent is None:
        return None
    ctx = SpanContext(
        trace_id=parent.trace_id,
        span_id=_rand_hex(16),
        sampled=parent.sampled,
    )
    if not ctx.sampled:
        return ctx
    end_ns = time.time_ns()
    start_ns = end_ns - int(max(0.0, duration_s) * 1e9)
    a = {"stage": stage}
    a.update({k: str(v) for k, v in attrs.items()})
    METRICS.histogram("corro_span_seconds", span=name).observe(
        max(0.0, duration_s)
    )
    _finish_span(
        name, ctx, parent.span_id, start_ns, end_ns, a,
        error=error, forced=forced,
    )
    return ctx


def continue_from(traceparent: Optional[str], name: str, **attrs: str) -> Span:
    """Server-side: adopt the peer's trace id from the wire
    (peer/mod.rs:1494-1496 extract)."""
    return Span(
        name, parent=parse_traceparent(traceparent),
        attrs={k: str(v) for k, v in attrs.items()},
    )


# -- slow-query logging ----------------------------------------------------

SLOW_QUERY_S = 1.0


class timed_query:
    """Logs any wrapped block slower than 1 s with its SQL — the analog of
    the reference's sqlite trace_v2 slow-query hook
    (`klukai-types/src/sqlite.rs:55-65`).

    r23: this IS the statement profiler's tap.  A caller that knows its
    statement's shape (the r15 capture-shape key on the write path,
    class labels like "apply:batch" / "match:batch" / "query:api"
    elsewhere) passes `shape=`, and when the continuous profiler is
    installed every exit feeds `corro.store.stmt.seconds{shape=}` plus
    the /v1/profile statement table — uninstalled, the hook is one
    module-global read."""

    def __init__(self, sql: str, shape: Optional[str] = None):
        self.sql = sql
        self.shape = shape
        self._start = 0.0

    def __enter__(self) -> "timed_query":
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        elapsed = time.monotonic() - self._start
        if self.shape is not None:
            _profiler.record_stmt(self.shape, elapsed)
        if elapsed >= SLOW_QUERY_S:
            METRICS.counter("corro_slow_queries_total").inc()
            log.warning("slow query (%.3fs): %s", elapsed, self.sql[:500])

"""Robust JAX backend selection when a TPU PJRT plugin may hang or fail.

The driver environment ships a tunnel-backed TPU plugin on PYTHONPATH
(`.axon_site`) whose backend init can hang indefinitely (tunnel down) or
fail fast (UNAVAILABLE).  Empirical behavior matrix in this image:

- default env (``JAX_PLATFORMS=axon``): interpreter startup is fine;
  ``jax.devices()`` hangs or raises when the tunnel is down.
- ``JAX_PLATFORMS=cpu`` with the plugin still on PYTHONPATH: fresh
  ``import jax`` can hang inside plugin discovery.
- plugin stripped from PYTHONPATH + ``JAX_PLATFORMS=cpu``: always works.
- in a process where jax is already imported but backends are NOT yet
  initialized: ``jax.config.update('jax_platforms', 'cpu')`` (plus
  ``XLA_FLAGS`` for a virtual device count) reliably selects CPU.

Rules implemented here:
1. Probe candidate backends only in subprocesses, bounded by timeouts.
2. CPU subprocesses always use :func:`stripped_env`.
3. In-process fallback uses :func:`force_cpu_inprocess` and is only safe
   before the first backend init (checked via :func:`backends_initialized`).

Nothing in this module imports jax at module scope.
"""

from __future__ import annotations

import os
import subprocess
import sys

# Substring identifying PYTHONPATH entries that carry the hazardous
# TPU-plugin site dir (and its sitecustomize auto-registration).
PLUGIN_PATH_MARKER = ".axon_site"

_PROBE_CODE = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"


def stripped_env(
    n_devices: int | None = None, base: dict[str, str] | None = None
) -> dict[str, str]:
    """A subprocess env with the TPU plugin removed and CPU forced.

    This is the only configuration that reliably initializes JAX in this
    image regardless of tunnel state.
    """
    env = dict(os.environ if base is None else base)
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and PLUGIN_PATH_MARKER not in p
    ]
    if parts:
        env["PYTHONPATH"] = os.pathsep.join(parts)
    else:
        env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        # A requested device count overrides any inherited flag value —
        # a stale --xla_force_host_platform_device_count=1 from the outer
        # env would otherwise break the dryrun's device-count assert.
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def probe(env: dict[str, str] | None, timeout: float) -> str | None:
    """Platform name if ``jax.devices()`` succeeds under ``env``, else None.

    Runs in a subprocess so a hung backend init can never block the caller.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-u", "-c", _PROBE_CODE],
            env=os.environ.copy() if env is None else env,
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if out.returncode != 0:
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


def backends_initialized() -> bool:
    """True if this process's jax has already created backend clients."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(xb._backends)
    except Exception:
        return False


def initialized_platform() -> str | None:
    """Platform of the already-initialized default backend, if any."""
    if not backends_initialized():
        return None
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return None


def force_cpu_inprocess(n_devices: int | None = None) -> None:
    """Flip this process's jax to CPU before its first backend init.

    Safe whether or not jax is already imported, as long as no backend has
    been initialized yet.  With ``n_devices`` also forces a virtual host
    device count (must happen before CPU client creation).
    """
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def ensure_usable_backend(
    probe_timeout: float = 120.0, n_devices: int | None = None
) -> str:
    """Make sure this process's first jax backend init will not hang.

    Returns the platform that will be (or already is) in use.  If backends
    are already initialized, reports what exists.  Otherwise probes the
    inherited env in a subprocess; on failure flips this process to CPU.
    """
    existing = initialized_platform()
    if existing is not None:
        return existing
    if os.environ.get("JAX_PLATFORMS", "") in ("cpu",):
        force_cpu_inprocess(n_devices)
        return "cpu"
    platform = probe(None, probe_timeout)
    if platform is None or platform == "cpu":
        force_cpu_inprocess(n_devices)
        return "cpu"
    return platform


def reexec_under_cpu(
    child_flag: str,
    n_devices: int | None = None,
    timeout: float | None = None,
    prefer_inherited_probe_s: float | None = None,
) -> None:
    """Measurement-script preamble: re-exec this script as a child under
    a known-good env, then `sys.exit` with its return code. No-op (returns)
    when ``child_flag`` is already set in the environment.

    By default the child gets :func:`stripped_env` (plugin removed, CPU
    forced, optional virtual device count) — `JAX_PLATFORMS=cpu` alone is
    NOT safe with the TPU plugin on PYTHONPATH (import can hang in plugin
    discovery). With ``prefer_inherited_probe_s``, the inherited env is
    probed first and kept when it exposes a live non-CPU backend (the
    scale-ladder policy: run on the real chip when the tunnel is up).
    """
    if os.environ.get(child_flag) == "1":
        return
    env = None
    if prefer_inherited_probe_s is not None:
        if probe(None, prefer_inherited_probe_s) not in (None, "cpu"):
            env = os.environ.copy()
    if env is None:
        env = stripped_env(n_devices=n_devices)
    env[child_flag] = "1"
    proc = subprocess.run(
        [sys.executable, "-u", os.path.abspath(sys.argv[0])] + sys.argv[1:],
        env=env,
        timeout=timeout,
    )
    sys.exit(proc.returncode)


def run_python(
    code: str,
    env: dict[str, str],
    timeout: float,
    cwd: str | None = None,
) -> subprocess.CompletedProcess | None:
    """Run ``python -c code`` under ``env``; None on timeout."""
    try:
        return subprocess.run(
            [sys.executable, "-u", "-c", code],
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
            cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        return None


def default_tpu_compile_env() -> None:
    """Defaults the TPU topology env vars the tunnel's chipless AOT
    compile helper needs but the terminal does not always provide.

    Programs whose compilation consults accelerator "host bounds" (seen
    first on the 1M-member pview init, an 8.6 GiB-output program) fail
    with `remote_compile: HTTP 500, tpu_compile_helper exit 1` and
    "Failed to find host bounds for accelerator type: WARNING: could
    not determine TPU accelerator type" when TPU_ACCELERATOR_TYPE is
    unset; setting it client-side propagates through to the helper and
    was verified to fix that exact compile (PROFILE.md r5). Applied
    ONLY when the axon tunnel plugin is the selected backend — a real
    multi-chip pod (JAX_PLATFORMS=tpu or unset) auto-detects its
    topology and must not be pinned to a single-chip type — and
    setdefault only, so an environment that knows its topology wins."""
    if "axon" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    os.environ.setdefault("TPU_WORKER_ID", "0")


def enable_compilation_cache(path: str | None = None) -> str:
    """Point jax at a persistent on-disk compilation cache.

    VERDICT r3 weak #7: sharded compiles measured 268 s (n=262k) and
    522 s (n=1M) on the virtual CPU mesh, and every measurement script
    paid them again. The XLA compilation cache persists compiled
    executables keyed by HLO fingerprint, so a re-run of the same config
    (the common case for the scale ladders and the bench) skips straight
    to execution. Safe to call before or after jax import, but must run
    before the first compilation. Returns the cache dir.
    """
    cache = path or os.environ.get(
        "CORRO_JAX_CACHE", "/tmp/corrosion_jax_cache"
    )
    os.makedirs(cache, exist_ok=True)
    # every TPU-touching entry point routes through here before its
    # first compile — the natural seam for the helper-env defaults
    default_tpu_compile_env()
    import jax

    jax.config.update("jax_compilation_cache_dir", cache)
    # cache everything that took noticeable time, not only >1s programs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache

"""Declarative anomaly rules over the metrics TSDB: the alerting plane
(r20).

The repo measures everything (218+ documented series, /v1/slo,
/v1/cluster, /v1/traces) but until now nothing WATCHED the signals —
an operator (or the chaos matrix) had to poll and eyeball.  This module
turns the `[alerts]` config into typed, lifecycle-tracked alerts:

- RULES — threshold / rate / absent expressions over the TSDB fields
  (`runtime/tsdb.py`): a `threshold` rule compares the latest
  aggregated level of a gauge-like field, a `rate` rule the windowed
  per-second rate of a counter, an `absent` rule fires when a series
  that existed goes silent.  Every rule carries a for-duration and a
  severity; the default pack (`DEFAULT_RULES`) covers what the chaos
  matrix already proved can break: SLO burn, loop lag, shed/refusal
  rates, open sync circuits, view divergence, store faults.

- LIFECYCLE — OK → pending (condition true) → firing (held for the
  effective for-duration) → resolved (condition false), with a bounded
  transition history.  A PAGE-severity firing trips ONE FlightRecorder
  incident dump per episode (warn/info never dump — a flapping warn on
  a loaded host must not write frame histories); every firing
  attaches the tail sampler's slowest kept trace ids
  (runtime/tracestore.py — the jump from "paged" to "this write,
  through these nodes"), and an alert raised while the chaos CENSUS
  shows an active injection carries the scenario as a ``drill`` mark —
  the drill-vs-outage discriminator (chaos/faults.py).

- LOCAL HEALTH (Lifeguard, arXiv:1707.00788) — a node whose own event
  loop lags or whose store is faulting must distrust its own timers
  instead of flooding false positives: the health score (loop lag,
  store fault rate, membership LHM) WIDENS every rule's for-duration
  by up to `health_widen_max`×.  A sick node still pages — later, on
  stronger evidence (the LHA-Probe discipline applied to alerting).

Prime CCL bar (arXiv:2505.14065): a fault must surface as a typed
degradation signal, never a silent stall — the rules are how the
signals come TO the operator as pages instead of waiting in gauges.

Thread contract: `evaluate()` runs via `asyncio.to_thread` from
`alerts_loop` (incident dumps do file I/O) while HTTP handlers and the
digest builder read summaries from the loop/worker threads — all
shared state under ``self._lock``, reads return copies.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.tsdb import MetricsTSDB

log = logging.getLogger(__name__)

SEVERITIES = ("info", "warn", "page")
KINDS = ("threshold", "rate", "absent")
OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

# the default rule pack: one typed alert per failure class the chaos
# matrix / SLO plane already surfaced.  `series` names a TSDB field —
# rate rules name the COUNTER (the engine reads its `:rate` field).
DEFAULT_RULES = (
    {
        "name": "slo-burn",
        "kind": "threshold",
        "series": "corro.slo.burn.rate",
        "op": ">", "value": 1.0, "for_secs": 6.0,
        "agg": "max", "severity": "page",
        "summary": "error-budget burn > 1 on a write→event stage",
    },
    {
        "name": "loop-lag",
        "kind": "threshold",
        "series": "corro.runtime.loop.lag.max.seconds",
        "op": ">", "value": 0.5, "for_secs": 10.0,
        "agg": "max", "severity": "warn",
        "summary": "event-loop scheduling lag sustained above 500 ms",
    },
    {
        "name": "shed-rate",
        "kind": "rate",
        "series": "corro.subs.shed.total",
        "op": ">", "value": 1.0, "for_secs": 6.0,
        "severity": "warn",
        "summary": "subscription streams being shed as laggards",
    },
    {
        "name": "refusal-rate",
        "kind": "rate",
        "series": "corro.api.requests",
        "labels": {"status": "503"},
        "op": ">", "value": 5.0, "for_secs": 6.0,
        "severity": "warn",
        "summary": "API load-shedding 503s sustained",
    },
    {
        "name": "sync-circuit-open",
        "kind": "rate",
        "series": "corro.sync.circuit.opened.total",
        "op": ">", "value": 0.0, "for_secs": 4.0,
        "severity": "warn",
        "summary": "per-peer sync circuit breakers opening",
    },
    {
        "name": "view-divergence",
        "kind": "threshold",
        "series": "corro.cluster.divergence.active",
        "op": ">=", "value": 1.0, "for_secs": 4.0,
        "agg": "max", "severity": "page",
        "summary": "membership view divergence episode open "
                   "(partition / split-brain / silent node)",
    },
    {
        "name": "store-faults",
        "kind": "rate",
        "series": "corro.store.write.errors.total",
        "op": ">", "value": 0.5, "for_secs": 4.0,
        "severity": "page",
        "summary": "local write transactions failing at the store "
                   "(sick disk)",
    },
    {
        "name": "commit-stall",
        "kind": "rate",
        "series": "corro.store.commit.stall.total",
        "op": ">", "value": 0.5, "for_secs": 4.0,
        "severity": "page",
        "summary": "sqlite COMMIT walls stalling past the flush budget "
                   "(slow disk)",
    },
)


# rule → actuator bindings for the r22 remediation plane
# (agent/remediation.py): which registered actuator a FIRING rule
# drives.  Kept here beside DEFAULT_RULES so adding a rule forces the
# "should the cluster act on this?" question in the same diff; rules
# absent from the map page a human and nothing else.
DEFAULT_ACTIONS = {
    "view-divergence": "targeted-sync",
    "store-faults": "drain-refuse-bulk",
    "slo-burn": "shed-laggards",
}


@dataclass
class AlertRule:
    name: str
    kind: str  # threshold | rate | absent
    series: str
    op: str = ">"
    value: float = 0.0
    for_secs: float = 4.0
    window_secs: float = 10.0
    severity: str = "warn"
    agg: str = "sum"  # across-label-set aggregation
    labels: Dict[str, str] = field(default_factory=dict)
    summary: str = ""

    @classmethod
    def from_dict(cls, d: dict, for_scale: float = 1.0) -> "AlertRule":
        d = dict(d)
        unknown = set(d) - {f.name for f in cls.__dataclass_fields__.values()}  # type: ignore[attr-defined]
        if unknown:
            raise ValueError(
                f"alert rule {d.get('name', '?')!r}: unknown key(s) "
                f"{sorted(unknown)}"
            )
        r = cls(**d)
        if not r.name:
            raise ValueError("alert rule without a name")
        if r.kind not in KINDS:
            raise ValueError(f"alert rule {r.name!r}: kind {r.kind!r}")
        if r.op not in OPS:
            raise ValueError(f"alert rule {r.name!r}: op {r.op!r}")
        if r.severity not in SEVERITIES:
            raise ValueError(
                f"alert rule {r.name!r}: severity {r.severity!r}"
            )
        r.for_secs = float(r.for_secs) * for_scale
        r.window_secs = float(r.window_secs) * for_scale
        r.labels = dict(r.labels or {})
        return r

    @property
    def tsdb_field(self) -> str:
        return f"{self.series}:rate" if self.kind == "rate" else self.series


class _RuleState:
    __slots__ = ("state", "since_mono", "since_wall", "value", "drill",
                 "trace_ids", "incident", "profile")

    def __init__(self):
        self.state = "ok"  # ok | pending | firing
        self.since_mono = 0.0
        self.since_wall = 0.0
        self.value: Optional[float] = None
        self.drill: Optional[str] = None
        self.trace_ids: List[str] = []
        self.incident: Optional[str] = None
        self.profile: Optional[dict] = None


class AlertEngine:
    """One node's rule evaluator over the (process-global) TSDB."""

    def __init__(
        self,
        tsdb: MetricsTSDB,
        cfg=None,
        agent=None,
        registry=METRICS,
        clock=time.monotonic,
        wall=time.time,
    ):
        from corrosion_tpu.runtime.config import AlertsConfig

        self.tsdb = tsdb
        self.cfg = cfg if cfg is not None else AlertsConfig()
        self.agent = agent
        self.registry = registry
        self._clock = clock
        self._wall = wall
        scale = max(1e-6, float(self.cfg.for_scale))
        packs: List[dict] = []
        if self.cfg.default_pack:
            packs.extend(DEFAULT_RULES)
        packs.extend(self.cfg.rules)
        self.rules: List[AlertRule] = []
        seen = set()
        for d in packs:
            r = AlertRule.from_dict(d, for_scale=scale)
            if r.name in seen:  # operator rule overrides the pack's
                self.rules = [x for x in self.rules if x.name != r.name]
            seen.add(r.name)
            self.rules.append(r)
        self._lock = threading.Lock()
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }
        self._history: deque = deque(maxlen=int(self.cfg.history_max))

    # -- local health (Lifeguard) -------------------------------------------

    def health_score(self) -> float:
        """0 = healthy; each sick local signal adds up to 1.  Read from
        the TSDB so the score judges the same evidence the rules do."""
        cfg = self.cfg
        score = 0.0
        lag = self.tsdb.aggregate(
            "corro.runtime.loop.lag.max.seconds",
            window_secs=max(30.0, 3 * self.tsdb.sample_interval_secs),
            across="max", over="last",
        )
        if lag is not None and cfg.health_lag_secs > 0:
            score += min(1.0, lag / cfg.health_lag_secs)
        faults = self.tsdb.aggregate(
            "corro.store.write.errors.total:rate",
            window_secs=max(30.0, 3 * self.tsdb.sample_interval_secs),
            across="sum", over="avg",
        )
        if faults is not None and cfg.health_fault_rate > 0:
            score += min(1.0, faults / cfg.health_fault_rate)
        if self.agent is not None:
            try:
                lhm = self.agent.membership.lhm
                lhm_max = max(1, self.agent.membership.config.lhm_max)
                score += min(1.0, lhm / lhm_max)
            except AttributeError:
                pass
        return score

    def _widen(self) -> float:
        """For-duration multiplier: 1 (healthy) … health_widen_max
        (sick) — the node distrusts its own timers, it does not
        silence them."""
        return min(
            float(self.cfg.health_widen_max), 1.0 + self.health_score()
        )

    # -- evaluation (worker thread via alerts_loop) -------------------------

    def _eval_condition(self, rule: AlertRule):
        if rule.kind == "absent":
            return (
                self.tsdb.absent(
                    rule.tsdb_field, rule.labels or None,
                    window_secs=rule.window_secs,
                ),
                None,
            )
        over = "last" if rule.kind == "threshold" else "avg"
        across = rule.agg if rule.kind == "threshold" else "sum"
        v = self.tsdb.aggregate(
            rule.tsdb_field, rule.labels or None,
            window_secs=rule.window_secs, across=across, over=over,
        )
        if v is None:
            return False, None
        return OPS[rule.op](v, rule.value), v

    def evaluate(self) -> dict:
        """One pass over every rule; returns {fired: [...], resolved:
        [...]} for callers that react (tests, obs_report)."""
        now = self._clock()
        wall = self._wall()
        widen = self._widen()
        self.registry.gauge("corro.alerts.health.score").set(
            round(self.health_score(), 4)
        )
        fired: List[str] = []
        resolved: List[str] = []
        for rule in self.rules:
            cond, value = self._eval_condition(rule)
            with self._lock:
                st = self._states[rule.name]
                st.value = value
                if cond:
                    if st.state == "ok":
                        st.state = "pending"
                        st.since_mono = now
                        st.since_wall = wall
                    if (
                        st.state == "pending"
                        and now - st.since_mono >= rule.for_secs * widen
                    ):
                        st.state = "firing"
                        fired.append(rule.name)
                elif st.state != "ok":
                    if st.state == "firing":
                        resolved.append(rule.name)
                    st.state = "ok"
                    st.drill = None
                    st.trace_ids = []
                    st.incident = None
                    st.profile = None
        for name in fired:
            self._on_fire(name, wall)
        for name in resolved:
            self._on_resolve(name, wall)
        with self._lock:
            firing = sum(
                1 for s in self._states.values() if s.state == "firing"
            )
            pending = sum(
                1 for s in self._states.values() if s.state == "pending"
            )
        self.registry.counter("corro.alerts.evals.total").inc()
        self.registry.gauge("corro.alerts.firing").set(firing)
        self.registry.gauge("corro.alerts.pending").set(pending)
        return {"fired": fired, "resolved": resolved}

    def _on_fire(self, name: str, wall: float) -> None:
        from corrosion_tpu.chaos.faults import CENSUS
        from corrosion_tpu.runtime import profiler
        from corrosion_tpu.runtime import tracestore
        from corrosion_tpu.runtime.records import FLIGHT

        rule = next(r for r in self.rules if r.name == name)
        chaos = CENSUS.snapshot()
        drill = (
            (chaos.get("scenario") or "injection")
            if chaos.get("active") else None
        )
        st_store = tracestore.store()
        trace_ids = (
            [t["trace_id"] for t in st_store.kept(n=3)]
            if st_store is not None else []
        )
        # black-box dump for PAGES only: a warn-level alert flapping on
        # a loaded host (loop-lag on a busy 1-core box) must not write
        # a multi-MB frame history per episode per node.  A page also
        # grabs the continuous profiler's hot window (r23) — the
        # incident answers "WHERE was the time going when this fired",
        # not just "what were the lanes doing".
        profile = None
        if rule.severity == "page":
            prof = profiler.get()
            if prof is not None:
                try:
                    profile = prof.capture(f"alert_{name}")
                except Exception:
                    log.exception("profile capture failed for %s", name)
        incident = (
            FLIGHT.snapshot_incident(
                f"alert_{name}", registry=self.registry,
                extra={"profile": profile} if profile else None,
            )
            if rule.severity == "page" else None
        )
        with self._lock:
            st = self._states[name]
            st.drill = drill
            st.trace_ids = trace_ids
            st.incident = incident
            st.profile = profile
            value = st.value
            self._history.append({
                "rule": name, "event": "fired", "wall": wall,
                "severity": rule.severity, "value": value,
                "drill": drill,
            })
        self.registry.counter(
            "corro.alerts.fired.total", rule=name
        ).inc()
        log.warning(
            "ALERT firing: %s (%s)%s value=%s", name, rule.severity,
            f" [drill: {drill}]" if drill else "", value,
        )

    def _on_resolve(self, name: str, wall: float) -> None:
        rule = next(r for r in self.rules if r.name == name)
        with self._lock:
            fired_wall = next(
                (h["wall"] for h in reversed(self._history)
                 if h["rule"] == name and h["event"] == "fired"),
                None,
            )
            self._history.append({
                "rule": name, "event": "resolved", "wall": wall,
                "severity": rule.severity,
                "duration_secs": (
                    round(wall - fired_wall, 3)
                    if fired_wall is not None else None
                ),
            })
        self.registry.counter(
            "corro.alerts.resolved.total", rule=name
        ).inc()
        log.info("alert resolved: %s", name)

    # -- read side (loop / digest builder; copies only) ---------------------

    def _state_row(self, rule: AlertRule, st: _RuleState) -> dict:
        return {
            "rule": rule.name,
            "severity": rule.severity,
            "kind": rule.kind,
            "series": rule.series,
            "state": st.state,
            "value": st.value,
            "since_wall": (
                st.since_wall if st.state != "ok" else None
            ),
            "drill": st.drill,
            "trace_ids": list(st.trace_ids),
            "incident": st.incident,
            "profile": dict(st.profile) if st.profile else None,
            "summary": rule.summary,
        }

    def report(self, history: bool = True) -> dict:
        with self._lock:
            rows = [
                self._state_row(r, self._states[r.name])
                for r in self.rules
            ]
            hist = list(self._history) if history else []
        out = {
            "enabled": True,
            "health_score": round(self.health_score(), 4),
            "rules": rows,
            "active": [r for r in rows if r["state"] != "ok"],
        }
        if history:
            out["history"] = hist
        return out

    def active_summaries(self, cap: int = 16) -> List[dict]:
        """Compact active-alert rows for the cluster digest
        (runtime/digest.py): firing first, bounded."""
        with self._lock:
            rows = [
                {
                    "rule": r.name,
                    "severity": r.severity,
                    "state": st.state,
                    "since": st.since_wall,
                    "value": st.value if st.value is not None else 0.0,
                    "drill": bool(st.drill),
                }
                for r in self.rules
                for st in (self._states[r.name],)
                if st.state != "ok"
            ]
        rows.sort(key=lambda a: (a["state"] != "firing", a["rule"]))
        return rows[:cap]

    def firing_snapshot(self) -> List[dict]:
        """The remediation supervisor's consumption point
        (agent/remediation.py): every FIRING rule with how long it has
        been firing — enough to gate sustain windows and cooldowns
        without re-deriving lifecycle state."""
        now = self._clock()
        rules = {r.name: r for r in self.rules}
        with self._lock:
            return [
                {
                    "rule": name,
                    "severity": rules[name].severity,
                    "firing_secs": max(0.0, now - st.since_mono),
                    "since_wall": st.since_wall,
                    "value": st.value,
                    "drill": st.drill,
                }
                for name, st in self._states.items()
                if st.state == "firing"
            ]

    def census(self) -> dict:
        """The /v1/status block."""
        with self._lock:
            firing = [
                n for n, s in self._states.items() if s.state == "firing"
            ]
            pending = [
                n for n, s in self._states.items() if s.state == "pending"
            ]
        return {
            "enabled": True,
            "rules": len(self.rules),
            "firing": sorted(firing),
            "pending": sorted(pending),
            "health_score": round(self.health_score(), 4),
        }


async def alerts_loop(agent) -> None:
    """Evaluate the agent's rules every `eval_interval_secs` until
    tripwire.  Evaluation runs via to_thread: a firing rule dumps a
    flight-recorder incident (file I/O) and every TSDB read takes
    locks — neither belongs on the event loop."""
    eng = agent.alerts
    if eng is None:
        return
    interval = agent.config.alerts.eval_interval_secs
    while not agent.tripwire.tripped:
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(agent.tripwire.wait(), interval)
        if agent.tripwire.tripped:
            return
        try:
            await asyncio.to_thread(eng.evaluate)
        except Exception:
            log.exception("alert evaluation failed")

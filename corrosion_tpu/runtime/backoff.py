"""Exponential backoff iterator with jitter.

Counterpart of `klukai-types/src/backoff.rs:7-149` (a vendored
exponential-backoff crate): an iterator yielding sleep durations that grow
by `factor` from `min_interval` up to `max_interval`, each multiplied by a
random jitter in [1-jitter, 1+jitter]. `retries=None` yields forever —
the reference's sync loop uses `.iter()` endlessly with 1–15 s bounds
(`klukai-agent/src/agent/util.rs:359-405`).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass
class Backoff:
    min_interval: float = 1.0
    max_interval: float = 15.0
    factor: float = 2.0
    jitter: float = 0.3
    retries: Optional[int] = None
    _rng: Optional[random.Random] = None

    def with_seed(self, seed: int) -> "Backoff":
        self._rng = random.Random(seed)
        return self

    def iter(self) -> Iterator[float]:
        rng = self._rng or random
        base = self.min_interval
        n = 0
        while self.retries is None or n < self.retries:
            jit = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield min(base * jit, self.max_interval)
            base = min(base * self.factor, self.max_interval)
            n += 1

    def __iter__(self) -> Iterator[float]:
        return self.iter()

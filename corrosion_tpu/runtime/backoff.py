"""Exponential backoff iterator with jitter.

Counterpart of `klukai-types/src/backoff.rs:7-149` (a vendored
exponential-backoff crate): an iterator yielding sleep durations that grow
by `factor` from `min_interval` up to `max_interval`, each multiplied by a
random jitter in [1-jitter, 1+jitter]. `retries=None` yields forever —
the reference's sync loop uses `.iter()` endlessly with 1–15 s bounds
(`klukai-agent/src/agent/util.rs:359-405`).

r9 adds `mode="full"` — AWS-style FULL jitter: each yield is uniform in
[0, min(base, max_interval)] while the base still grows exponentially.
Multiplicative jitter keeps retriers loosely synchronized (every client
sleeps ≈ the same base ± 30%); full jitter spreads them over the whole
window, which is what breaks the rejoin/announce storm after a partition
heal — every healed node's backoff otherwise fires in the same beat
(the thundering-herd analysis in the AWS architecture blog's
"Exponential Backoff And Jitter").
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass
class Backoff:
    min_interval: float = 1.0
    max_interval: float = 15.0
    factor: float = 2.0
    jitter: float = 0.3
    retries: Optional[int] = None
    mode: str = "equal"  # "equal" (multiplicative ±jitter) | "full"
    # (uniform in [0, base] — use for fleet-synchronized retry storms)
    _rng: Optional[random.Random] = None

    def with_seed(self, seed: int) -> "Backoff":
        self._rng = random.Random(seed)
        return self

    def iter(self) -> Iterator[float]:
        if self.mode not in ("equal", "full"):
            raise ValueError(f"unknown backoff mode {self.mode!r}")
        rng = self._rng or random
        base = self.min_interval
        n = 0
        while self.retries is None or n < self.retries:
            if self.mode == "full":
                yield rng.random() * min(base, self.max_interval)
            else:
                jit = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
                yield min(base * jit, self.max_interval)
            base = min(base * self.factor, self.max_interval)
            n += 1

    def __iter__(self) -> Iterator[float]:
        return self.iter()

"""Profile store: the bounded ring of per-window folded-stack maps the
continuous profiler (runtime/profiler.py, r23) aggregates into — the
TSDB ring discipline (r20) applied to stacks instead of scalars.

The sampler thread folds one stack per thread per tick into the OPEN
window's `{folded_stack: samples}` map; every `window_secs` the open
window is sealed into a deque bounded by `slots`, so memory is capped
twice — per window by `max_stacks` (excess distinct stacks collapse
into the `~overflow` key, typed, never dropped silently) and globally
by the ring depth.  Readers (`GET /v1/profile`, the alert-triggered
capture, the digest hotspot summary) merge the windows that intersect
their lookback and return copies.

The statement-shape half lives here too: `record_stmt` accumulates
per-shape wall totals for writer/finalize/apply/matcher statements
(keyed by the r15 capture-shape cache key, fed from the
`timed_query` sqlite trace-callback path in runtime/trace.py), bounded
the same way.

Thread contract — the r7 lock discipline with one extra, profiler-
specific rule (enforced by the `profiler-safety` static rule,
analysis/profiler_safety.py): everything the SAMPLER thread touches
per sample is guarded by ``_fold_lock`` ONLY, and the critical
sections are plain dict updates — no asyncio objects, no store locks,
no allocation beyond the fold-map update.  Sealing a window (a dict
swap) and every read path run under the same lock; reads copy.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# per-window distinct-stack cap: past it, new stacks fold into this key
# (bounded memory under pathological stack churn, accounted not hidden)
OVERFLOW_KEY = "~overflow"


class _Window:
    __slots__ = ("start_wall", "end_wall", "folded", "samples")

    def __init__(self, start_wall: float):
        self.start_wall = start_wall
        self.end_wall = 0.0
        self.folded: Dict[str, int] = {}
        self.samples = 0


class ProfStore:
    """Bounded folded-stack ring + statement-shape aggregation."""

    def __init__(
        self,
        window_secs: float = 5.0,
        slots: int = 24,
        max_stacks: int = 512,
        max_shapes: int = 128,
        wall=time.time,
    ):
        self.window_secs = float(window_secs)
        self.slots = int(slots)
        self.max_stacks = int(max_stacks)
        self.max_shapes = int(max_shapes)
        self._wall = wall
        self._fold_lock = threading.Lock()
        self._open = _Window(self._wall())
        self._ring: deque = deque(maxlen=self.slots)
        # shape key -> [count, total_secs] (cumulative; bounded)
        self._shapes: Dict[str, list] = {}
        self.sealed_total = 0

    # -- sampler-thread half (profiler-safety scoped) ----------------------

    def add_sample(self, key: str) -> None:
        """Fold one sampled stack into the open window.  THE per-sample
        mutation: one dict update under `_fold_lock`, nothing else."""
        with self._fold_lock:
            folded = self._open.folded
            n = folded.get(key)
            if n is None and len(folded) >= self.max_stacks:
                key = OVERFLOW_KEY
                n = folded.get(key)
            folded[key] = 1 if n is None else n + 1
            self._open.samples += 1

    def seal_coldpath(self) -> None:
        """Close the open window into the ring and open a fresh one.
        Cold path: runs once per `window_secs`, not per sample."""
        now = self._wall()
        with self._fold_lock:
            w = self._open
            w.end_wall = now
            self._open = _Window(now)
            if w.samples:
                self._ring.append(w)
                self.sealed_total += 1

    # -- statement shapes (worker threads via timed_query) -----------------

    def record_stmt(self, shape: str, secs: float) -> None:
        with self._fold_lock:
            row = self._shapes.get(shape)
            if row is None:
                if len(self._shapes) >= self.max_shapes:
                    shape = OVERFLOW_KEY
                    row = self._shapes.get(shape)
                if row is None:
                    row = self._shapes[shape] = [0, 0.0]
            row[0] += 1
            row[1] += secs

    # -- read side (loop / worker threads; copies) -------------------------

    def merged(self, window_secs: Optional[float] = None) -> Dict[str, int]:
        """Folded map merged over every window intersecting the
        lookback (open window included).  `None` → everything held."""
        lo = (
            self._wall() - float(window_secs)
            if window_secs is not None else float("-inf")
        )
        out: Dict[str, int] = {}
        with self._fold_lock:
            windows: List[_Window] = [
                w for w in self._ring if w.end_wall >= lo
            ]
            windows.append(self._open)
            for w in windows:
                for key, n in w.folded.items():
                    out[key] = out.get(key, 0) + n
        return out

    def stmt_rows(self) -> List[dict]:
        """Per-shape statement rows, heaviest total wall first."""
        with self._fold_lock:
            rows = [
                {
                    "shape": shape,
                    "count": row[0],
                    "total_secs": round(row[1], 6),
                }
                for shape, row in self._shapes.items()
            ]
        rows.sort(key=lambda r: -r["total_secs"])
        return rows

    def census(self) -> dict:
        with self._fold_lock:
            open_samples = self._open.samples
            ring_samples = sum(w.samples for w in self._ring)
            windows = len(self._ring)
            stacks = len(self._open.folded) + sum(
                len(w.folded) for w in self._ring
            )
            shapes = len(self._shapes)
        return {
            "window_secs": self.window_secs,
            "slots": self.slots,
            "windows_sealed": windows,
            "samples_held": open_samples + ring_samples,
            "distinct_stacks": stacks,
            "stmt_shapes": shapes,
        }


# -- folded map post-processing (serving side, never the sampler) ----------


def self_times(folded: Dict[str, int]) -> List[Tuple[str, int]]:
    """Per-frame SELF sample counts: each folded stack's sample count is
    charged to its LEAF frame — the flamegraph's 'who is actually on
    CPU' column.  Heaviest first."""
    acc: Dict[str, int] = {}
    for key, n in folded.items():
        leaf = key.rsplit(";", 1)[-1]
        acc[leaf] = acc.get(leaf, 0) + n
    return sorted(acc.items(), key=lambda kv: (-kv[1], kv[0]))


def to_folded_text(folded: Dict[str, int]) -> str:
    """The collapsed-stack text format every flamegraph tool ingests:
    one `stack count` line per distinct folded stack."""
    lines = [f"{key} {n}" for key, n in sorted(folded.items())]
    return "\n".join(lines) + ("\n" if lines else "")


def to_speedscope(folded: Dict[str, int], name: str = "corrosion") -> dict:
    """The speedscope file format (sampled profile): shared frame table
    + per-stack sample/weight arrays — importable straight into
    https://www.speedscope.app."""
    frame_index: Dict[str, int] = {}
    frames: List[dict] = []
    samples: List[List[int]] = []
    weights: List[int] = []
    for key in sorted(folded):
        stack = []
        for frame in key.split(";"):
            idx = frame_index.get(frame)
            if idx is None:
                idx = frame_index[frame] = len(frames)
                frames.append({"name": frame})
            stack.append(idx)
        samples.append(stack)
        weights.append(folded[key])
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": name,
                "unit": "none",
                "startValue": 0,
                "endValue": sum(weights),
                "samples": samples,
                "weights": weights,
            }
        ],
        "exporter": "corrosion-tpu-profiler",
    }

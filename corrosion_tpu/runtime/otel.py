"""OTLP/HTTP span export — closes the reference's OpenTelemetry gap.

The reference wires an OTLP exporter + batch span processor behind
`config.telemetry.open-telemetry` (`klukai/src/main.rs:68-118`: tonic
exporter, resource attrs service.name/service.version/host.name, batch
export).  This image ships no OTel SDK, but OTLP/HTTP has a stable JSON
protobuf mapping (opentelemetry-proto, `trace_service.proto` — trace and
span ids are HEX strings in the JSON encoding, nanosecond timestamps are
decimal strings), so the exporter here speaks it directly over
`urllib.request` with zero dependencies:

    POST {endpoint}/v1/traces   Content-Type: application/json
    {"resourceSpans": [{"resource": {...}, "scopeSpans":
        [{"scope": {"name": "corrosion-tpu"}, "spans": [...]}]}]}

Design (mirrors the reference's BatchSpanProcessor semantics):
- finished spans are enqueued onto a bounded deque (drop-oldest, the drop
  counted in `corro_otel_spans_dropped_total`) — tracing must never block
  or grow without bound when the collector is away;
- a daemon thread flushes every `flush_interval_s` or as soon as
  `batch_max` spans are queued, whichever first;
- export failures are counted (`corro_otel_export_failures_total`) and
  the batch is dropped, not retried forever — matching the SDK's
  fire-and-forget batch processor;
- `configure()` is opt-in via config/env; when unconfigured, the hot-path
  hook (`record_span`) is a single global None-check.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import urllib.request
from collections import deque
from typing import Dict, List, Optional

from corrosion_tpu.runtime.metrics import METRICS

log = logging.getLogger(__name__)

_VERSION = "0.4.0"


def _attr(key: str, value) -> dict:
    """One OTLP KeyValue in JSON encoding (anyValue by python type)."""
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


class OtlpExporter:
    """Batched OTLP/HTTP JSON span exporter (BatchSpanProcessor analog)."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "corrosion-tpu",
        resource_attrs: Optional[Dict[str, str]] = None,
        queue_max: int = 4096,
        batch_max: int = 512,
        flush_interval_s: float = 5.0,
        timeout_s: float = 10.0,
    ):
        ep = endpoint.rstrip("/")
        # OTEL_EXPORTER_OTLP_ENDPOINT is a base URL; the traces signal
        # path is appended unless the caller already gave the full path
        self.url = ep if ep.endswith("/v1/traces") else ep + "/v1/traces"
        self.timeout_s = timeout_s
        self.batch_max = batch_max
        self.flush_interval_s = flush_interval_s
        attrs = {
            "service.name": service_name,
            "service.version": _VERSION,
            "host.name": socket.gethostname(),
        }
        attrs.update(resource_attrs or {})
        self._resource = {"attributes": [_attr(k, v) for k, v in attrs.items()]}
        self._queue: deque = deque(maxlen=queue_max)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="otlp-export", daemon=True
        )
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def record(self, span: dict) -> None:
        """Enqueue one finished span (already in OTLP JSON span form)."""
        with self._lock:
            if len(self._queue) == self._queue.maxlen:
                METRICS.counter("corro_otel_spans_dropped_total").inc()
            self._queue.append(span)
            full = len(self._queue) >= self.batch_max
        if full:
            self._wake.set()

    # -- consumer side -----------------------------------------------------

    def _drain(self) -> List[dict]:
        with self._lock:
            batch = list(self._queue)
            self._queue.clear()
        return batch

    def _export(self, spans: List[dict]) -> bool:
        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": self._resource,
                        "scopeSpans": [
                            {
                                "scope": {
                                    "name": "corrosion-tpu",
                                    "version": _VERSION,
                                },
                                "spans": spans,
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                ok = 200 <= resp.status < 300
        except Exception as e:  # noqa: BLE001 — any transport error counts
            log.debug("otlp export failed: %s", e)
            ok = False
        if ok:
            METRICS.counter("corro_otel_spans_exported_total").inc(len(spans))
        else:
            METRICS.counter("corro_otel_export_failures_total").inc()
        return ok

    def _export_chunked(self, batch: List[dict]) -> None:
        # batch_max bounds the REQUEST size, not just the wake trigger: a
        # backlog drained after a collector outage must not become one
        # oversized POST a size-limited collector rejects wholesale
        for i in range(0, len(batch), self.batch_max):
            self._export(batch[i : i + self.batch_max])

    def _run(self) -> None:
        while True:
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            batch = self._drain()
            if batch:
                self._export_chunked(batch)
            if self._closed:
                return

    # -- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        """Synchronously export everything queued (tests / shutdown)."""
        batch = self._drain()
        if batch:
            self._export_chunked(batch)

    def shutdown(self) -> None:
        self._closed = True
        self._wake.set()
        self._thread.join(timeout=self.timeout_s + 1.0)
        self.flush()


_EXPORTER: Optional[OtlpExporter] = None


def configure(
    endpoint: Optional[str],
    service_name: str = "corrosion-tpu",
    resource_attrs: Optional[Dict[str, str]] = None,
    **kw,
) -> Optional[OtlpExporter]:
    """Install (or, with endpoint=None, uninstall) the global exporter.

    Call sites pass `config.telemetry.open_telemetry_endpoint`; the CLI
    agent entrypoint (`cli.py:_cmd_agent`) additionally falls back to the
    standard OTEL_EXPORTER_OTLP_ENDPOINT env var so deployments can turn
    tracing on without editing TOML (the reference gates identically on
    `config.telemetry.open-telemetry`, `main.rs:68-76`).
    """
    global _EXPORTER
    if _EXPORTER is not None:
        _EXPORTER.shutdown()
        _EXPORTER = None
    if endpoint:
        _EXPORTER = OtlpExporter(
            endpoint, service_name=service_name, resource_attrs=resource_attrs,
            **kw,
        )
    return _EXPORTER


def exporter() -> Optional[OtlpExporter]:
    return _EXPORTER


def record_span(
    name: str,
    trace_id: str,
    span_id: str,
    parent_span_id: Optional[str],
    start_ns: int,
    end_ns: int,
    attrs: Dict[str, str],
    error: bool = False,
) -> None:
    """Hot-path hook called by trace.Span.__exit__; no-op when unconfigured."""
    exp = _EXPORTER
    if exp is None:
        return
    span: dict = {
        "traceId": trace_id,
        "spanId": span_id,
        "name": name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [_attr(k, v) for k, v in attrs.items()],
        "status": {"code": 2} if error else {},  # STATUS_CODE_ERROR
    }
    if parent_span_id:
        span["parentSpanId"] = parent_span_id
    exp.record(span)

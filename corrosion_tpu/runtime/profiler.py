"""Continuous profiling plane (r23): an always-on wall-clock stack
sampler + the statement-shape profiler hook, Prime CCL discipline
(arXiv:2505.14065) — bounded overhead, and when the budget is exceeded
the plane DEGRADES (sheds its own sample rate), never the serving path.

The sampler is a daemon thread (`prof-sample`, the tsdb/tracestore
pattern) that walks ``sys._current_frames()`` at an adaptive rate:
`hz` (default 67) while its own measured duty cycle stays under
`max_overhead_pct`, auto-shedding to `shed_hz` (default 11) past it —
every shed counted by `corro.profile.shed.total`, the live overhead
published as `corro.profile.overhead.pct`, and the rate restored once
the projected full-rate overhead falls back under half the budget.

Each sampled thread is CLASSIFIED into a subsystem tag (event loop /
store worker / fanout / observability / the sampler itself) from its
thread name plus one stack-derived refinement (a worker thread with a
`store/` frame on its stack is the store worker); for a registered
event-loop thread the running asyncio task's name is resolved (the
lock-free ``asyncio.tasks._current_tasks`` dict read — the py-spy
trick, no asyncio API call on the sample path), so folded stacks carry
a ``subsystem;task;frames…`` prefix.  Samples aggregate into the
bounded `ProfStore` ring (runtime/profstore.py) and serve
``GET /v1/profile?window=…&format=folded|speedscope``.

Sampler-thread safety is a STATIC contract, not just a convention: the
`profiler-safety` rule (analysis/profiler_safety.py) walks the call
graph reachable from `_sample_once` across this module and profstore.py
and rejects asyncio calls, any lock but the sanctioned `_fold_lock`,
`agent`/`.store` object traversal, and per-sample allocation beyond the
fold-map update (comprehensions, f-strings, sorting, json, logging,
per-sample registry calls).  Cache-miss fills and once-per-window work
are explicitly cold paths — functions suffixed ``_coldpath`` are
bounded by cache size or window cadence, not by the sample rate.

Process-global install (`configure`/`ensure`/`get`, the tsdb.py
contract): the first agent's `[profile]` knobs win; tests drive
`Profiler.sample_once()` directly with the thread stopped.
"""

from __future__ import annotations

import sys
import threading
import time
from asyncio.tasks import _current_tasks  # lock-free dict, read-only
from typing import Dict, Optional

from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.runtime.profstore import (
    ProfStore,
    self_times,
    to_folded_text,
    to_speedscope,
)

# how many samples between overhead-accounting / adaptation passes —
# metrics flush and shed decisions are per-BLOCK, never per-sample
ADAPT_EVERY = 32

# deepest stack folded per sample: beyond it the stack is truncated at
# the leaf end (the hot frames), bounded key size under deep recursion
MAX_DEPTH = 48

# thread-name prefix -> subsystem tag (the add-a-subsystem-tag table:
# extend it when a new named thread family appears — COMPONENTS.md
# "Continuous profiling" documents the procedure)
_NAME_TAGS = (
    ("corro-committer", "committer"),
    ("corro-subs-diff", "fanout"),
    ("asyncio_", "worker"),
    ("ThreadPoolExecutor", "worker"),
    ("crdt-interrupt-watchdog", "store"),
    ("tsdb-sample", "obs"),
    ("trace-sweep", "obs"),
    ("otlp-export", "obs"),
    ("prof-sample", "sampler"),
)


class Profiler:
    """The adaptive wall-clock sampler + its serving/read side."""

    def __init__(
        self,
        hz: float = 67.0,
        shed_hz: float = 11.0,
        max_overhead_pct: float = 1.0,
        window_secs: float = 5.0,
        slots: int = 24,
        max_stacks: int = 512,
        registry=METRICS,
    ):
        self.hz = float(hz)
        self.shed_hz = float(shed_hz)
        self.max_overhead_pct = float(max_overhead_pct)
        self.registry = registry
        self.ring = ProfStore(
            window_secs=window_secs, slots=slots, max_stacks=max_stacks
        )
        self.shed = False
        self.sheds_total = 0
        self.captures_total = 0
        self.overhead_pct = 0.0
        self.samples_total = 0
        # monotone sample-path wall accumulator: never reset by the
        # per-block flush, so an external reader (bench_ingest
        # --profile) can difference it across any span for an exact
        # duty measurement independent of block boundaries
        self.busy_secs_total = 0.0
        self._interval = 1.0 / self.hz
        self._own_tid = 0
        # tid -> subsystem tag (bounded: cleared past 512 entries);
        # loop-thread tids additionally map to their loop object so the
        # running task name can be resolved per sample
        self._tids: Dict[int, str] = {}
        self._loops: Dict[int, object] = {}
        # guards _tids/_loops MUTATION only (register_loop_coldpath on
        # the loop thread vs _classify_coldpath on the sampler thread);
        # the hot path reads both dicts lock-free — a stale read is
        # harmless, the next sample reclassifies
        self._reg_lock = threading.Lock()
        # code object -> (display frame string, is_store_frame) — the
        # per-frame intern table; filled on miss (cold path), read hot
        self._codes: Dict[object, tuple] = {}
        self._keybuf: list = []  # reused per-sample frame buffer
        # per-block overhead accounting (flushed by _adapt_coldpath)
        self._busy = 0.0
        self._block_started = time.monotonic()
        self._block_samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="prof-sample", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        self._own_tid = threading.get_ident()
        # a (re)started thread opens a FRESH accounting block: busy
        # carried across a stop() gap would divide by an elapsed that
        # excludes the gap and read as phantom duty
        self._busy = 0.0
        self._block_samples = 0
        self._block_started = time.monotonic()
        while not self._stop.wait(self._interval):
            try:
                self.sample_once()
            except Exception:  # pragma: no cover - defensive
                # the profiler must never take the process down; one
                # bad sample is dropped, the plane keeps running
                pass

    def register_loop_coldpath(self, loop=None, tid: int = 0) -> None:
        """Map an event-loop thread (caller's thread by default) to its
        loop so the sampler resolves running task names.  Called once
        per agent boot from the loop thread — never on the sample path."""
        import asyncio

        if loop is None:
            loop = asyncio.get_running_loop()
        tid = tid or threading.get_ident()
        with self._reg_lock:
            self._loops[tid] = loop
            self._tids[tid] = "loop"

    # -- the sample path (profiler-safety scoped) ---------------------------

    def sample_once(self) -> None:
        """One pass over every live thread's current stack.  Runs on
        the sampler thread (or a test driver).  Everything here and
        below is inside the `profiler-safety` static contract."""
        t0 = time.monotonic()
        if self._own_tid == 0:
            self._own_tid = threading.get_ident()
        self._sample_once(t0)
        spent = time.monotonic() - t0
        self._busy += spent
        self.busy_secs_total += spent
        self._block_samples += 1
        if self._block_samples >= ADAPT_EVERY:
            self._adapt_coldpath(t0)

    def _sample_once(self, t0: float) -> None:
        frames = sys._current_frames()
        tids = self._tids
        codes = self._codes
        buf = self._keybuf
        add = self.ring.add_sample
        for tid, frame in frames.items():
            if tid == self._own_tid:
                add("sampler;-;prof-sample")
                continue
            sub = tids.get(tid)
            if sub is None:
                sub = self._classify_coldpath(tid)
            del buf[:]
            store_hit = False
            f = frame
            depth = 0
            while f is not None and depth < MAX_DEPTH:
                code = f.f_code
                info = codes.get(code)
                if info is None:
                    info = self._code_info_coldpath(code)
                buf.append(info[0])
                if info[1]:
                    store_hit = True
                f = f.f_back
                depth += 1
            buf.reverse()
            if store_hit and sub == "worker":
                sub = "store"
            task_name = "-"
            if sub == "loop":
                loop = self._loops.get(tid)
                if loop is not None:
                    task = _current_tasks.get(loop)
                    if task is not None:
                        task_name = task.get_name()
            key = sub + ";" + task_name + ";" + ";".join(buf)
            add(key)
        # window roll check: `_open` is swapped only by this thread, so
        # the unlocked read of its start stamp is single-writer-safe
        if time.time() - self.ring._open.start_wall >= self.ring.window_secs:
            self.ring.seal_coldpath()

    def _classify_coldpath(self, tid: int) -> str:
        """Thread-name classification on tid-cache miss — bounded by
        the number of live threads, not the sample rate."""
        name = ""
        th = threading._active.get(tid)
        if th is not None:
            name = th.name or ""
        sub = "other"
        if tid in self._loops:
            sub = "loop"
        else:
            for prefix, tag in _NAME_TAGS:
                if name.startswith(prefix):
                    sub = tag
                    break
        with self._reg_lock:
            if len(self._tids) > 512:
                self._tids.clear()  # dead-tid churn must not pin memory
            self._tids[tid] = sub
        return sub

    def _code_info_coldpath(self, code) -> tuple:
        """Frame intern-table fill on code-object miss — bounded by the
        number of distinct code objects, not the sample rate."""
        fname = code.co_filename
        short = fname.rsplit("/", 2)
        short = "/".join(short[1:]) if len(short) > 2 else fname
        info = (
            "%s:%s" % (short, code.co_name),
            "/store/" in fname,
        )
        if len(self._codes) > 8192:
            self._codes.clear()
        self._codes[code] = info
        return info

    def _adapt_coldpath(self, now: float) -> None:
        """Per-block overhead accounting + the adaptive shed: runs once
        per ADAPT_EVERY samples.  Metrics flush lives here so the
        sample path never takes a registry lock."""
        elapsed = max(1e-9, now - self._block_started)
        duty = self._busy / elapsed
        self.overhead_pct = round(100.0 * duty, 4)
        reg = self.registry
        reg.counter("corro.profile.samples.total").inc(self._block_samples)
        self.samples_total += self._block_samples
        reg.gauge("corro.profile.overhead.pct").set(self.overhead_pct)
        if not self.shed and self.overhead_pct > self.max_overhead_pct:
            self.shed = True
            self.sheds_total += 1
            self._interval = 1.0 / self.shed_hz
            reg.counter("corro.profile.shed.total").inc()
        elif self.shed:
            # projected duty at FULL rate from the per-sample cost; the
            # plane recovers only once full rate would fit half the
            # budget (hysteresis against shed/restore flapping)
            per_sample = self._busy / max(1, self._block_samples)
            projected = 100.0 * per_sample * self.hz
            if projected < 0.5 * self.max_overhead_pct:
                self.shed = False
                self._interval = 1.0 / self.hz
        self._busy = 0.0
        self._block_samples = 0
        self._block_started = time.monotonic()

    # -- statement shapes ---------------------------------------------------

    def stmt(self, shape: str, secs: float) -> None:
        """One statement-shape observation (timed_query's exit hook,
        worker threads): the registry histogram + the profile payload's
        cumulative per-shape rows."""
        self.registry.histogram(
            "corro.store.stmt.seconds", shape=shape
        ).observe(secs)
        self.ring.record_stmt(shape, secs)

    # -- read side ----------------------------------------------------------

    def folded(self, window_secs: Optional[float] = None) -> Dict[str, int]:
        return self.ring.merged(window_secs)

    def capture(self, reason: str, window_secs: float = 30.0, top: int = 10) -> dict:
        """The alert-triggered hot-window grab (pinned to flight-
        recorder incidents): top folded stacks + self-time frames +
        statement shapes, bounded and JSON-ready."""
        folded = self.ring.merged(window_secs)
        stacks = sorted(folded.items(), key=lambda kv: -kv[1])[: 4 * top]
        tops = self_times(folded)[:top]
        self.captures_total += 1
        self.registry.counter("corro.profile.captures.total").inc()
        return {
            "reason": reason,
            "window_secs": window_secs,
            "samples": sum(folded.values()),
            "folded": dict(stacks),
            "top_self": [
                {"frame": fr, "samples": n} for fr, n in tops
            ],
            "stmt": self.ring.stmt_rows()[:top],
            "overhead_pct": self.overhead_pct,
            "shed": self.shed,
        }

    def hotspots(self, window_secs: float = 60.0, top: int = 3) -> list:
        """Digest-plane summary: top-N self-time frames as compact
        (frame, samples) pairs — what rides the gossiped NodeDigest."""
        return [
            {"frame": fr, "samples": n}
            for fr, n in self_times(self.ring.merged(window_secs))[:top]
        ]

    def export(
        self, window_secs: Optional[float] = None, fmt: str = "json"
    ):
        """The /v1/profile serving surface: 'folded' → collapsed-stack
        text, 'speedscope' → the speedscope JSON document, anything
        else → the census+tops JSON summary."""
        folded = self.ring.merged(window_secs)
        if fmt == "folded":
            return to_folded_text(folded)
        if fmt == "speedscope":
            return to_speedscope(folded)
        return {
            "enabled": True,
            "window_secs": window_secs,
            "samples": sum(folded.values()),
            "hz": self.hz if not self.shed else self.shed_hz,
            "shed": self.shed,
            "overhead_pct": self.overhead_pct,
            "top_self": [
                {"frame": fr, "samples": n}
                for fr, n in self_times(folded)[:20]
            ],
            "stmt": self.ring.stmt_rows()[:20],
            "census": self.census(),
        }

    def census(self) -> dict:
        out = {
            "enabled": True,
            "hz": self.hz,
            "shed_hz": self.shed_hz,
            "shed": self.shed,
            "sheds_total": self.sheds_total,
            "max_overhead_pct": self.max_overhead_pct,
            "overhead_pct": self.overhead_pct,
            "samples_total": self.samples_total,
            "busy_secs_total": round(self.busy_secs_total, 6),
            "captures_total": self.captures_total,
        }
        out.update(self.ring.census())
        return out


# -- process-global install (the tsdb.py configure/ensure/get contract) ----

_PROFILER: Optional[Profiler] = None


def configure(auto_start: bool = True, **kw) -> Optional[Profiler]:
    """(Re)install the process profiler.  No kwargs = uninstall."""
    global _PROFILER
    if _PROFILER is not None:
        _PROFILER.stop()
        _PROFILER = None
    if not kw:
        return None
    _PROFILER = Profiler(**kw)
    if auto_start:
        _PROFILER.start()
    return _PROFILER


def ensure(auto_start: bool = True, **kw) -> Profiler:
    """Install if absent (first agent's [profile] config wins)."""
    global _PROFILER
    if _PROFILER is None:
        _PROFILER = Profiler(**kw)
        if auto_start:
            _PROFILER.start()
    return _PROFILER


def get() -> Optional[Profiler]:
    return _PROFILER


def installed() -> bool:
    return _PROFILER is not None


def record_stmt(shape: str, secs: float) -> None:
    """The timed_query exit hook (runtime/trace.py): a no-op until a
    profiler is installed — one global read on the uninstalled path."""
    p = _PROFILER
    if p is not None:
        p.stmt(shape, secs)


# the five-bucket write-path attribution (WRITE_PROFILE.json / ROADMAP
# write-path round 4): agent/run.py stamps the commit pipeline and
# calls this per settled tx when a profiler is installed.  The buckets
# PARTITION the submit→resolve wall: `sqlite_flush` is the worker-
# thread wall minus finalize (statement exec + COMMIT fsync +
# bookkeeping — the in-sqlite residual), `asyncio_dispatch` the
# loop-side scheduling on both ends.  r24 renamed `to_thread_hop` →
# `handoff`: the gate_acq→thread_start span is now the committer
# thread's deque pickup latency (on CORRO_COMMITTER=to_thread it is
# the old executor hop again), same partition arithmetic either way.
WRITE_BUCKETS = (
    "asyncio_dispatch",
    "write_gate",
    "handoff",
    "finalize",
    "sqlite_flush",
)


def record_write_buckets(
    enq: float,
    gate_start: float,
    gate_acq: float,
    dispatch: float,
    thread_start: float,
    thread_done: float,
    resolved: float,
    finalize_secs: float,
) -> None:
    p = _PROFILER
    if p is None:
        return
    if not (enq <= gate_start <= gate_acq <= dispatch
            <= thread_start <= thread_done <= resolved):
        return  # a stamp is missing/reordered; don't bank garbage
    reg = p.registry
    hist = reg.histogram
    wall = resolved - enq
    thread_wall = thread_done - thread_start
    finalize_secs = min(finalize_secs, thread_wall)
    # first call stays unaliased: metrics-doc matches dotted
    # registry-method call sites textually, and the series must not
    # vanish from the inventory behind the local alias
    reg.histogram("corro.write.profile.seconds", bucket="wall").observe(wall)
    hist("corro.write.profile.seconds", bucket="asyncio_dispatch").observe(
        (gate_start - enq) + (resolved - thread_done)
    )
    hist("corro.write.profile.seconds", bucket="write_gate").observe(
        gate_acq - gate_start
    )
    hist("corro.write.profile.seconds", bucket="handoff").observe(
        thread_start - dispatch + (dispatch - gate_acq)
    )
    hist("corro.write.profile.seconds", bucket="finalize").observe(
        finalize_secs
    )
    hist("corro.write.profile.seconds", bucket="sqlite_flush").observe(
        thread_wall - finalize_secs
    )

"""Local metrics TSDB: a bounded ring-buffer time-series store over the
process `Registry` (r20 — the alerting plane's substrate).

Every observability plane so far serves the registry's CURRENT state
(/v1/status, /metrics, the digests); nothing remembers how a series
MOVED, so a rule like "store faults > 0.5/s for 4 s" had nothing to
evaluate against.  This module samples the registry every few seconds
and keeps, per series, a bounded ring of points:

  counters    -> windowed per-second RATES (delta of the cumulative
                 value between consecutive samples / elapsed; clamped
                 at 0 across resets), field ``<name>:rate``
  gauges      -> levels, field ``<name>`` (this is how loopmon lag and
                 the write-gate depth gauges enter the TSDB — they are
                 already gauges)
  histograms  -> count rates, field ``<name>:rate``
  latencies   -> windowed p50/p99 (``<name>:p50`` / ``<name>:p99``)
                 plus the count rate ``<name>:rate``

The sampler runs on a DAEMON THREAD (`_Sampler`, the tracestore
flusher pattern), never the event loop: one `Registry.snapshot()` +
quantile pass per tick, O(series).  Memory is capped twice — per
series by the ring depth (`slots`) and globally by `max_series`
(excess series are dropped TYPED: `corro.tsdb.series.dropped.total`)
— and accounted (`corro.tsdb.series` / `corro.tsdb.points` /
`corro.tsdb.bytes.est`).

Thread contract (the r7 lock-discipline rule): `sample_once` mutates
the store from the sampler thread while the alert engine and HTTP
handlers read from worker threads and the event loop — every shared
structure is touched under ``self._lock`` and reads return copies.
The registry locks are never held together with the TSDB lock (the
snapshot is taken first, appended second).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.runtime.metrics import METRICS, Registry

LabelKey = Tuple[Tuple[str, str], ...]

# rough per-point cost for the bytes estimate: a (wall, value) float
# pair in a deque plus container overhead
_POINT_BYTES = 48
_SERIES_BYTES = 160

# window the latency quantile fields are computed over at sample time
# (the /v1/slo default: "p99 right now" means the last minute)
QUANTILE_WINDOW_SECS = 60.0


class _Series:
    __slots__ = ("points",)

    def __init__(self, slots: int):
        self.points: deque = deque(maxlen=slots)  # (wall, value)


class MetricsTSDB:
    def __init__(
        self,
        registry: Registry = METRICS,
        sample_interval_secs: float = 2.0,
        slots: int = 240,
        max_series: int = 4096,
        clock=time.monotonic,
        wall=time.time,
    ):
        self.registry = registry
        self.sample_interval_secs = float(sample_interval_secs)
        self.slots = int(slots)
        self.max_series = int(max_series)
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, LabelKey], _Series] = {}
        # counter-rate state: (field, labels) -> (mono, cumulative)
        self._prev: Dict[Tuple[str, LabelKey], Tuple[float, float]] = {}
        self.samples_total = 0

    # -- sampling (sampler thread) ------------------------------------------

    def sample_once(self) -> int:
        """One full registry pass; returns points appended.  Runs on
        the sampler thread (or a test driver) — never the event loop."""
        t0 = self._clock()
        wall = self._wall()
        rows: List[Tuple[str, LabelKey, float, bool]] = []
        # (field, labels, value, is_cumulative)
        for kind, name, labels, value in self.registry.snapshot():
            lk = tuple(sorted(labels.items()))
            if kind == "gauge":
                rows.append((name, lk, value, False))
            elif kind == "counter":
                rows.append((f"{name}:rate", lk, value, True))
            elif kind in ("histogram", "latency") and name.endswith("_count"):
                base = name[: -len("_count")]
                rows.append((f"{base}:rate", lk, value, True))
        for name, labels, inst in self.registry.latency_items():
            qs = inst.quantiles(window_secs=QUANTILE_WINDOW_SECS)
            lk = tuple(sorted(labels.items()))
            for q in ("p50", "p99"):
                if qs.get(q) is not None:
                    rows.append((f"{name}:{q}", lk, qs[q], False))

        added = dropped = 0
        with self._lock:
            for field, lk, value, cumulative in rows:
                key = (field, lk)
                if cumulative:
                    prev = self._prev.get(key)
                    self._prev[key] = (t0, value)
                    if prev is None:
                        continue  # first sight: no interval yet
                    dt = t0 - prev[0]
                    if dt <= 0:
                        continue
                    value = max(0.0, value - prev[1]) / dt
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= self.max_series:
                        dropped += 1
                        continue
                    s = self._series[key] = _Series(self.slots)
                s.points.append((wall, value))
                added += 1
            self.samples_total += 1
            n_series = len(self._series)
            n_points = sum(len(s.points) for s in self._series.values())
        reg = self.registry
        reg.counter("corro.tsdb.samples.total").inc()
        if dropped:
            reg.counter("corro.tsdb.series.dropped.total").inc(dropped)
        reg.gauge("corro.tsdb.series").set(n_series)
        reg.gauge("corro.tsdb.points").set(n_points)
        reg.gauge("corro.tsdb.bytes.est").set(
            n_series * _SERIES_BYTES + n_points * _POINT_BYTES
        )
        reg.histogram("corro.tsdb.sample.seconds").observe(
            self._clock() - t0
        )
        return added

    # -- queries (any thread; copies under the lock) ------------------------

    def _matching(
        self, field: str, labels: Optional[Dict[str, str]]
    ) -> List[Tuple[LabelKey, List[Tuple[float, float]]]]:
        want = set((labels or {}).items())
        with self._lock:
            return [
                (lk, list(s.points))
                for (f, lk), s in self._series.items()
                if f == field and want <= set(lk)
            ]

    def window(
        self,
        field: str,
        labels: Optional[Dict[str, str]] = None,
        window_secs: float = 60.0,
    ) -> List[Tuple[float, float]]:
        """Raw (wall, value) points of every matching label set within
        the window, oldest first."""
        lo = self._wall() - window_secs
        out: List[Tuple[float, float]] = []
        for _lk, pts in self._matching(field, labels):
            out.extend(p for p in pts if p[0] >= lo)
        out.sort(key=lambda p: p[0])
        return out

    def aggregate(
        self,
        field: str,
        labels: Optional[Dict[str, str]] = None,
        window_secs: float = 60.0,
        across: str = "sum",
        over: str = "last",
    ) -> Optional[float]:
        """One scalar: per-tick aggregation ACROSS matching label sets
        (sum/max/avg — points from one `sample_once` pass share a wall
        stamp), then OVER the window's ticks (last/avg/max/min).
        None when no point is inside the window."""
        lo = self._wall() - window_secs
        by_tick: Dict[float, List[float]] = {}
        for _lk, pts in self._matching(field, labels):
            for w, v in pts:
                if w >= lo:
                    by_tick.setdefault(w, []).append(v)
        if not by_tick:
            return None
        fns = {"sum": sum, "max": max, "min": min,
               "avg": lambda vs: sum(vs) / len(vs)}
        fa = fns[across]
        ticks = sorted(by_tick)
        vals = [fa(by_tick[w]) for w in ticks]
        if over == "last":
            return vals[-1]
        return fns[over](vals)

    def absent(
        self,
        field: str,
        labels: Optional[Dict[str, str]] = None,
        window_secs: float = 60.0,
    ) -> bool:
        """True when the series was seen before but produced NO point
        inside the window — a vanished series, not a never-born one
        (an absent-rule must not fire on a plane that never started)."""
        matching = self._matching(field, labels)
        if not matching:
            return False
        lo = self._wall() - window_secs
        return not any(
            p[0] >= lo for _lk, pts in matching for p in pts
        )

    def fields(self) -> List[str]:
        with self._lock:
            return sorted({f for f, _lk in self._series})

    def census(self) -> dict:
        with self._lock:
            n_series = len(self._series)
            n_points = sum(len(s.points) for s in self._series.values())
            samples = self.samples_total
        return {
            "enabled": True,
            "series": n_series,
            "points": n_points,
            "samples": samples,
            "sample_interval_secs": self.sample_interval_secs,
            "slots": self.slots,
            "max_series": self.max_series,
        }


# -- process-global installation (mirrors runtime/tracestore.py) ------------

_TSDB: Optional[MetricsTSDB] = None
_SAMPLER: Optional["_Sampler"] = None


class _Sampler:
    """Daemon thread driving `sample_once` — the whole sampling plane
    runs off the event loop by construction."""

    def __init__(self, db: MetricsTSDB):
        self.db = db
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tsdb-sample", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        period = max(0.05, self.db.sample_interval_secs)
        while not self._stop.wait(period):
            self.db.sample_once()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def configure(auto_sample: bool = True, **kw) -> Optional[MetricsTSDB]:
    """Install (or, with no kwargs, uninstall) the global TSDB.  Agent
    setup passes the [tsdb] knobs; tests drive `sample_once` by hand
    with auto_sample=False."""
    global _TSDB, _SAMPLER
    if _SAMPLER is not None:
        _SAMPLER.stop()
        _SAMPLER = None
    if not kw:
        _TSDB = None
        return None
    _TSDB = MetricsTSDB(**kw)
    if auto_sample:
        _SAMPLER = _Sampler(_TSDB)
    return _TSDB


def ensure(**kw) -> MetricsTSDB:
    """Install the global TSDB if absent (idempotent agent-setup hook —
    the FIRST agent's config wins in multi-agent processes, the
    tracestore rule)."""
    global _TSDB
    if _TSDB is None:
        return configure(**kw)
    return _TSDB


def get() -> Optional[MetricsTSDB]:
    return _TSDB

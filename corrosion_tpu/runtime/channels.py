"""Bounded channels with metrics, the runtime's nervous system.

Counterpart of `klukai-types/src/channel.rs` (mpsc wrappers emitting
send/recv/failed counters, capacity gauges, and send-delay histograms per
named channel) over asyncio queues. The same names flow into the metrics
registry so dashboards match the reference's series.
"""

from __future__ import annotations

import asyncio
import time
from typing import Generic, Optional, Tuple, TypeVar

from corrosion_tpu.runtime.metrics import METRICS

T = TypeVar("T")


class ChannelClosed(Exception):
    pass


class Sender(Generic[T]):
    def __init__(self, ch: "_Chan[T]"):
        self._ch = ch

    async def send(self, item: T) -> None:
        if self._ch.closed:
            METRICS.counter(
                "corro.channel.message.send.failed", channel=self._ch.name
            ).inc()
            raise ChannelClosed(self._ch.name)
        start = time.monotonic()
        await self._ch.queue.put(item)
        METRICS.counter("corro.channel.message.sent", channel=self._ch.name).inc()
        METRICS.gauge(
            "corro.channel.queue.depth", channel=self._ch.name
        ).set(self._ch.queue.qsize())
        METRICS.histogram(
            "corro.channel.message.send.delay.seconds", channel=self._ch.name
        ).observe(time.monotonic() - start)

    async def send_many(self, items) -> None:
        """Enqueue a whole batch with ONE metrics round (r21 group
        fanout): the sent counter bumps by the batch size and the
        depth/delay series are touched once, instead of a counter inc +
        gauge set + histogram observe per item.  Queue puts still
        happen item-by-item so a bounded channel's backpressure keeps
        its per-item semantics."""
        items = list(items)
        if not items:
            return
        if self._ch.closed:
            METRICS.counter(
                "corro.channel.message.send.failed", channel=self._ch.name
            ).inc(len(items))
            raise ChannelClosed(self._ch.name)
        start = time.monotonic()
        put = self._ch.queue.put
        for item in items:
            await put(item)
        METRICS.counter(
            "corro.channel.message.sent", channel=self._ch.name
        ).inc(len(items))
        METRICS.gauge(
            "corro.channel.queue.depth", channel=self._ch.name
        ).set(self._ch.queue.qsize())
        METRICS.histogram(
            "corro.channel.message.send.delay.seconds", channel=self._ch.name
        ).observe(time.monotonic() - start)

    def try_send(self, item: T) -> bool:
        try:
            self._ch.queue.put_nowait(item)
            METRICS.counter(
                "corro.channel.message.sent", channel=self._ch.name
            ).inc()
            return True
        except asyncio.QueueFull:
            METRICS.counter(
                "corro.channel.message.send.failed", channel=self._ch.name
            ).inc()
            return False

    def close(self) -> None:
        self._ch.closed = True
        self._ch.closed_event.set()

    @property
    def capacity_left(self) -> int:
        return max(0, self._ch.queue.maxsize - self._ch.queue.qsize())


class Receiver(Generic[T]):
    def __init__(self, ch: "_Chan[T]"):
        self._ch = ch

    async def recv(self) -> T:
        """Receive the next item; raises ChannelClosed once the channel is
        closed AND drained (the Rust mpsc recv-returns-None contract)."""
        while True:
            if self._ch.closed and self._ch.queue.empty():
                raise ChannelClosed(self._ch.name)
            get = asyncio.ensure_future(self._ch.queue.get())
            closed = asyncio.ensure_future(self._ch.closed_event.wait())
            try:
                done, _ = await asyncio.wait(
                    {get, closed}, return_when=asyncio.FIRST_COMPLETED
                )
            except asyncio.CancelledError:
                # external cancellation (e.g. wait_for timeout): don't lose
                # an item the inner get may already have consumed
                closed.cancel()
                if get.done() and not get.cancelled():
                    q = self._ch.queue
                    q._queue.appendleft(get.result())
                    # appendleft bypasses put_nowait's getter wakeup —
                    # rouse any consumer parked inside queue.get()
                    q._wakeup_next(q._getters)
                else:
                    get.cancel()
                raise
            closed.cancel()
            if get in done:
                METRICS.counter(
                    "corro.channel.message.received", channel=self._ch.name
                ).inc()
                return get.result()
            get.cancel()
            try:
                await get
                # a race can complete the get during cancellation
                METRICS.counter(
                    "corro.channel.message.received", channel=self._ch.name
                ).inc()
                return get.result()
            except asyncio.CancelledError:
                pass

    def try_recv(self) -> Optional[T]:
        try:
            item = self._ch.queue.get_nowait()
            METRICS.counter(
                "corro.channel.message.received", channel=self._ch.name
            ).inc()
            return item
        except asyncio.QueueEmpty:
            return None

    async def recv_timeout(self, timeout: float) -> Optional[T]:
        try:
            return await asyncio.wait_for(self.recv(), timeout)
        except asyncio.TimeoutError:
            return None

    def qsize(self) -> int:
        return self._ch.queue.qsize()


class _Chan(Generic[T]):
    def __init__(self, size: int, name: str):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=size)
        self.name = name
        self.closed = False
        self.closed_event = asyncio.Event()


def bounded(size: int, name: str) -> Tuple[Sender[T], Receiver[T]]:
    ch: _Chan[T] = _Chan(size, name)
    METRICS.gauge("corro.channel.bounded.capacity", channel=name).set(size)
    return Sender(ch), Receiver(ch)

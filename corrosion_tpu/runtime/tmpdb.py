"""Fresh file-backed SQLite paths for ephemeral agents.

Shared-cache in-memory SQLite (what CrdtStore turns ":memory:" into) has
table-level reader/writer locks — no real WAL — which flakes concurrent
read+apply as "database is locked" under load. Ephemeral multi-agent
harnesses (DevCluster, the integration tests) should use file-backed dbs
on the production WAL path instead; this module is the single copy of
that workaround. The per-process directory is removed at interpreter
exit; callers owning shorter lifetimes (DevCluster.stop) may also remove
individual files early.
"""

from __future__ import annotations

import atexit
import os
import shutil
import tempfile
import uuid
from typing import Optional

_dir: Optional[str] = None


def fresh_db_path(prefix: str = "agent") -> str:
    """A unique path for a new file-backed SQLite db in the per-process
    scratch directory (created lazily, removed at exit). The prefix is
    sanitized — node names can be bind addresses ('[::1]:8080') and must
    not leak glob/path metacharacters into filenames."""
    global _dir
    if _dir is None:
        _dir = tempfile.mkdtemp(prefix="corro-dbs-")
        atexit.register(_cleanup)
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in prefix)
    return os.path.join(_dir, f"{safe or 'agent'}-{uuid.uuid4().hex}.db")


def _cleanup() -> None:
    global _dir
    if _dir is not None:
        shutil.rmtree(_dir, ignore_errors=True)
        _dir = None

"""Event-loop instrumentation — the asyncio analog of tokio-metrics.

The reference samples its tokio runtime every 5 s and publishes worker/
scheduling gauges (`klukai/src/command/agent.rs:29-63`: park counts,
steal counts, queue depths, `corro.tokio.*`). asyncio has no worker pool,
so the translation keeps what is diagnosable on a single-threaded loop:

  corro.runtime.loop.lag.seconds       sampled scheduling lag histogram —
                                       sleep(dt) vs actual wakeup delta;
                                       the single most useful stall signal
  corro.runtime.loop.lag.max.seconds   gauge: worst lag in the last window
  corro.runtime.loop.tasks.alive       gauge: len(asyncio.all_tasks())
  corro.runtime.loop.ticks             counter: monitor wakeups

The thread-pool analogs of tokio's stealing/park metrics
(corro.tokio.total_steal_count etc.) have no asyncio counterpart and are
itemized as inapplicable in COMPONENTS.md §metrics.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from corrosion_tpu.runtime.metrics import METRICS

SAMPLE_INTERVAL = 0.5
REPORT_EVERY = 10  # samples per max-lag window (≈5 s, agent.rs:63 cadence)


async def loop_lag_monitor(
    tripwire=None,
    interval: float = None,
    report_every: int = None,
    registry=None,
    max_samples: int = None,
) -> None:
    """Run forever (until cancelled, tripped, or `max_samples` — the
    test hook), publishing loop health.  The r20 alerting plane rides
    on the gauges published here: the TSDB samples
    `corro.runtime.loop.lag.max.seconds` into its rings, the
    `loop-lag` default rule thresholds it, and the alert engine's
    Lifeguard health score reads it back to widen for-durations."""
    interval = SAMPLE_INTERVAL if interval is None else interval
    report_every = REPORT_EVERY if report_every is None else report_every
    registry = METRICS if registry is None else registry
    lag_hist = registry.histogram("corro.runtime.loop.lag.seconds")
    lag_max = registry.gauge("corro.runtime.loop.lag.max.seconds")
    tasks_g = registry.gauge("corro.runtime.loop.tasks.alive")
    ticks = registry.counter("corro.runtime.loop.ticks")
    window_max = 0.0
    i = 0
    while tripwire is None or not tripwire.tripped:
        t0 = time.monotonic()
        await asyncio.sleep(interval)
        lag = max(0.0, time.monotonic() - t0 - interval)
        lag_hist.observe(lag)
        window_max = max(window_max, lag)
        ticks.inc()
        i += 1
        if i % report_every == 0:
            lag_max.set(window_max)
            window_max = 0.0
            tasks_g.set(len(asyncio.all_tasks()))
        if max_samples is not None and i >= max_samples:
            return


def start(tracker, tripwire=None) -> Optional[asyncio.Task]:
    """Spawn the monitor on the agent's task tracker (spawn_counted)."""
    return tracker.spawn(loop_lag_monitor(tripwire))

"""Minimal metrics registry (counters/gauges/histograms) with a Prometheus
text exposition, standing in for the reference's `metrics` facade +
Prometheus exporter (`klukai/src/command/agent.rs:29-63`). ~Same series
names are emitted by the runtime so dashboards translate directly.

Thread model (r7): instruments are handed out by `Registry.counter/
gauge/histogram` under the registry lock, but the returned objects are
then mutated from arbitrary threads — the agent metrics loop runs
`collect_once` on a worker thread while the event loop serves requests,
and the simulation drivers publish from whatever thread steps them.
Each instrument therefore carries its OWN lock: `value += x` is a
read-modify-write that the GIL does not make atomic (bytecode
interleaving between LOAD and STORE drops increments), and a histogram
observe mutates three fields that must stay consistent with each other.
The per-instrument lock is never held together with the registry lock
except inside `render_prometheus`/`snapshot` (registry → instrument
order, the only nesting direction used anywhere).
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Dict, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def add(self, v: float) -> None:
        with self._lock:
            self.value += v


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class Histogram:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets=_DEFAULT_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.counts[bisect_right(self.buckets, v)] += 1
            self.total += v
            self.count += 1


def _escape_label_value(v: str) -> str:
    """Prometheus text format 0.0.4 label-value escaping: backslash,
    double quote, and line feed must be escaped or a hostile value (a
    table name, an endpoint path) corrupts the whole exposition."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Registry:
    # r20 cardinality guard: distinct label sets one series NAME may
    # mint before further label sets are dropped typed.  A runaway
    # label value (a pk in a label, an unescaped path) used to grow
    # the registry without bound; the largest legitimate family today
    # (corro.api.requests endpoint×status) is well under this.
    max_label_sets = 512

    def __init__(self):
        self._counters: Dict[Tuple[str, LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelKey], Gauge] = {}
        self._histograms: Dict[Tuple[str, LabelKey], Histogram] = {}
        # r11: windowed log-bucketed percentile histograms
        # (runtime/latency.py WindowedLatency) — the write→event SLO
        # substrate; carries its own internal lock like the others
        self._latencies: Dict[Tuple[str, LabelKey], object] = {}
        self._lock = threading.Lock()
        # per-name label-set counts (all kinds pooled) + the shared
        # detached instruments capped mint attempts are handed: callers
        # keep a working object, the writes just land nowhere
        self._name_counts: Dict[str, int] = {}
        self._null_counter = Counter()
        self._null_gauge = Gauge()
        self._null_histogram = Histogram()
        self._null_latency = None

    def _admit(self, name: str) -> bool:
        """Under self._lock: account one NEW label set for `name`;
        False when the per-name cap is hit (the caller then drops
        typed and returns the detached instrument)."""
        n = self._name_counts.get(name, 0)
        if n >= self.max_label_sets:
            return False
        self._name_counts[name] = n + 1
        return True

    def _series_total_locked(self) -> int:
        return (
            len(self._counters) + len(self._gauges)
            + len(self._histograms) + len(self._latencies)
        )

    def _note_mint(self) -> None:
        """Publish the registry's own size after a mint (outside the
        lock; bounded re-entry — minting corro.metrics.series itself
        lands in the existing-instrument fast path on the inner call).
        The total is recomputed AFTER the gauge is resolved so the
        first mint's recursive gauge mint is counted too."""
        g = self.gauge("corro.metrics.series")
        with self._lock:
            total = self._series_total_locked()
        g.set(total)

    def _note_drop(self, kind: str) -> None:
        """Typed drop count for a label set refused by the cardinality
        cap (outside the lock; this family has one label set per kind,
        so it can never trip the cap it reports on)."""
        self.counter(
            "corro.metrics.cardinality.dropped.total", kind=kind
        ).inc()

    def _get(self, table, kind, factory, name, labels):
        """Shared guarded mint: existing instruments return on the fast
        path; a NEW label set is admitted against the per-name cap or
        refused (typed drop + the shared detached instrument)."""
        key = (name, _labels_key(labels))
        minted = False
        with self._lock:
            inst = table.get(key)
            if inst is None and self._admit(name):
                inst = table[key] = factory()
                minted = True
        if inst is None:
            self._note_drop(kind)
            return None
        if minted:
            self._note_mint()
        return inst

    def counter(self, name: str, **labels: str) -> Counter:
        c = self._get(self._counters, "counter", Counter, name, labels)
        return c if c is not None else self._null_counter

    def gauge(self, name: str, **labels: str) -> Gauge:
        g = self._get(self._gauges, "gauge", Gauge, name, labels)
        return g if g is not None else self._null_gauge

    def histogram(self, name: str, **labels: str) -> Histogram:
        h = self._get(
            self._histograms, "histogram", Histogram, name, labels
        )
        return h if h is not None else self._null_histogram

    def latency(self, name: str, **labels: str):
        """Windowed percentile histogram (runtime/latency.py): log
        buckets at ~5 % resolution, p50…p999 over the sliding window
        and cumulative.  Use for every latency an SLO is judged on."""
        from corrosion_tpu.runtime.latency import WindowedLatency

        w = self._get(
            self._latencies, "latency", WindowedLatency, name, labels
        )
        if w is None:
            with self._lock:
                if self._null_latency is None:
                    self._null_latency = WindowedLatency()
            return self._null_latency
        return w

    def latency_items(self):
        """Every latency instrument as (name, labels, instrument) rows
        — what the TSDB's quantile sampling pass iterates
        (runtime/tsdb.py) without minting series by looking."""
        with self._lock:
            items = list(self._latencies.items())
        return [(n, dict(labels), w) for (n, labels), w in items]

    def latency_family(self, name: str):
        """All label sets of one latency series, as (name, labels,
        instrument) rows — what cross-label aggregation (the SLO plane)
        iterates without minting series."""
        with self._lock:
            items = list(self._latencies.items())
        return [
            (n, dict(labels), w) for (n, labels), w in items if n == name
        ]

    def snapshot(self) -> List[Tuple[str, str, Dict[str, str], float]]:
        """Point-in-time read of every series as (kind, name, labels,
        value) rows — the non-mutating peek the status plane renders
        (`api/http.py` GET /v1/status, `scripts/obs_report.py`).
        Histograms surface as two rows (`<name>_count`, `<name>_sum`);
        reading through `counter()`/`gauge()` instead would MINT empty
        series as a side effect of looking."""
        out: List[Tuple[str, str, Dict[str, str], float]] = []
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        for (name, labels), c in counters:
            out.append(("counter", name, dict(labels), c.value))
        for (name, labels), g in gauges:
            out.append(("gauge", name, dict(labels), g.value))
        for (name, labels), h in hists:
            with h._lock:
                cnt, tot = h.count, h.total
            out.append(("histogram", name + "_count", dict(labels), cnt))
            out.append(("histogram", name + "_sum", dict(labels), tot))
        with self._lock:
            lats = list(self._latencies.items())
        for (name, labels), w in lats:
            c = w.snapshot_cumulative()
            out.append(("latency", name + "_count", dict(labels), c.count))
            out.append(("latency", name + "_sum", dict(labels), c.total))
        return out

    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4."""
        out: List[str] = []

        def fmt(
            name: str, labels: LabelKey,
            extra: Optional[Dict[str, str]] = None,
        ) -> str:
            norm = name.replace(".", "_").replace("-", "_")
            items = list(labels) + (list(extra.items()) if extra else [])
            if items:
                lbl = ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in items
                )
                return f"{norm}{{{lbl}}}"
            return norm

        with self._lock:
            for (name, labels), c in sorted(self._counters.items()):
                out.append(f"{fmt(name, labels)} {c.value}")
            for (name, labels), g in sorted(self._gauges.items()):
                out.append(f"{fmt(name, labels)} {g.value}")
            for (name, labels), h in sorted(self._histograms.items()):
                with h._lock:
                    counts = list(h.counts)
                    total, count = h.total, h.count
                cum = 0
                for i, b in enumerate(h.buckets):
                    cum += counts[i]
                    out.append(
                        f"{fmt(name + '_bucket', labels, {'le': str(b)})} {cum}"
                    )
                out.append(
                    f"{fmt(name + '_bucket', labels, {'le': '+Inf'})} {count}"
                )
                out.append(f"{fmt(name + '_sum', labels)} {total}")
                out.append(f"{fmt(name + '_count', labels)} {count}")
            for (name, labels), w in sorted(self._latencies.items()):
                # cumulative log buckets (sparse: only occupied edges —
                # cumulative counts at the emitted le values stay exact)
                # + summary-style windowed quantile gauges
                from corrosion_tpu.runtime import latency as _lat

                c = w.snapshot_cumulative()
                cum = 0
                for i, n in c.nonzero_buckets():
                    cum += n
                    out.append(
                        f"{fmt(name + '_bucket', labels, {'le': format(_lat.bucket_upper(i), '.6g')})} {cum}"
                    )
                out.append(
                    f"{fmt(name + '_bucket', labels, {'le': '+Inf'})} {c.count}"
                )
                out.append(f"{fmt(name + '_sum', labels)} {c.total}")
                out.append(f"{fmt(name + '_count', labels)} {c.count}")
                qs = w.quantiles(window_secs=_lat.DEFAULT_WINDOW_SECS)
                for q in _lat.QUANTILES:
                    v = qs[_lat._qname(q)]
                    if v is not None:
                        out.append(
                            f"{fmt(name, labels, {'quantile': format(q, 'g'), 'window': format(_lat.DEFAULT_WINDOW_SECS, 'g')})} {v}"
                        )
        return "\n".join(out) + "\n"


METRICS = Registry()

# Kernel phase-timing series (r6): the SWIM kernel profilers and the
# simulation drivers publish per-phase device seconds under one family,
#     corro.kernel.phase.seconds{kernel="pview"|"dense", phase="..."}
# so a dashboard shows where the tick goes the same way PROFILE.md's
# phase tables do.  Canonical pview phase names (the profiler's rows):
PVIEW_PHASES = (
    "pick",       # probe/feed partner selection gathers
    "inbox",      # gossip delivery (grouped sort or shift row-gather)
    "feed",       # feed/seed window pulls
    "merge",      # the merge scatter chain (+ own-entry pin, re-encode)
    "bufmrg",     # gossip buffer merge sorts
    "stats",      # blocked stats pass + readback
    "tick",       # whole fused tick (scanned, per tick)
)

# Kernel event-telemetry series (r7): what happened ON DEVICE, counted
# inside the jitted tick and drained in one readback alongside the
# existing stats —
#     corro.kernel.events.total{kernel="dense"|"pview"|"crdt_merge",
#                               event="..."}
# This tuple is the single source of truth for the SWIM kernels' lane
# layout: `SwimState.events` / `PViewState.events` is an int32 vector
# indexed in THIS order (ops/swim.py builds it via `_event_vector`),
# the simulation drivers zip deltas against it, and `scripts/
# obs_report.py` renders it.  Reordering is a wire-format change for
# any state snapshot that carries the lane.
KERNEL_EVENTS = (
    "gossip_emitted",     # gossip messages sent (sender+receiver up,
    #                       same partition; includes anti-entropy lanes)
    "gossip_lost",        # of those, dropped by iid loss injection
    "inbox_delivered",    # messages that landed in a bounded inbox
    "inbox_overflowed",   # messages dropped at the inbox cap
    "merge_won",          # inbox/own-update entries that improved the
    #                       receiver's view (feed merges count as pulls)
    "feed_pulls",         # successful feed-window partner exchanges
    "seed_pulls",         # bootstrap-seed window exchanges
    "suspect_raised",     # failed indirect probes → new suspicions
    "down_declared",      # suspicion timers fired un-refuted
    "refuted",            # members that refuted by bumping incarnation
    "self_announced",     # periodic self-announces entering gossip
    # r9 Lifeguard lanes (appended — lane order is a wire format):
    "suspicion_confirmations",  # independent confirming suspect messages
    #                       applied to OPEN suspicion timers (LHA-S:
    #                       each confirmation shrinks that timer's
    #                       deadline toward the floor; 0 with lhm off)
    "suspect_fp",         # of suspect_raised, subjects that are ground-
    #                       truth ALIVE — the false-accusation rate the
    #                       Lifeguard A/B is judged on (the kernel owns
    #                       ground truth, so the lane is exact, not an
    #                       estimate)
    "down_fp",            # of down_declared, subjects ground-truth
    #                       ALIVE — wrongful evictions
)

# Flight-recorder census lanes (r8): the per-tick snapshot half of the
# device flight ring (`SwimState.ring` / `PViewState.ring` — ops/swim.py
# `_census_frame`).  Each ring row is [KERNEL_EVENTS deltas ‖ census]:
# the event lanes hold THIS tick's delta of the cumulative vector above;
# the census lanes hold point-in-time levels.  All are cheap [N]-shaped
# integer reductions over arrays the tick already carries — never a
# whole-view/table pass:
FLIGHT_CENSUS = (
    "census_alive",       # ground-truth live processes (sum alive)
    "census_suspect",     # open suspicion timers cluster-wide — the
    #                       per-protocol-period "suspicion pressure"
    #                       SWIM pathologies show up in (Das et al.;
    #                       Lifeguard)
    "census_down",        # ground-truth dead processes (detected or
    #                       not) — churn injections appear as steps
    "inbox_highwater",    # max per-member valid inbox entries this tick
    "inc_max",            # max incarnation — refute storms ramp it
    "lhm_max",            # r9: max Local Health Multiplier score across
    #                       members (Lifeguard LHA-Probe; 0 = every
    #                       member healthy or lifeguard disabled) — a
    #                       degraded node shows up as a sustained step
)

# One ring row = event deltas then census, in this order.  Reordering
# is a wire-format change for every drained ring snapshot.
FLIGHT_LANES = KERNEL_EVENTS + FLIGHT_CENSUS

# Subscription serving-plane series (r10): the live-query perf round's
# observable contract, emitted from pubsub/{manager,executor,matcher}.py
# and agent/handle.py —
#   corro.subs.router.tables          gauge      indexed source tables
#   corro.subs.router.changes.total   counter    changes seen by the
#                                                inverted routing index
#   corro.subs.router.matched.total   counter    changes that hit >= 1
#                                                matcher's (table,cid)
#   corro.subs.router.fanout.total    counter    change x matcher pairs
#                                                routed (the old hook
#                                                cost was subs x changes
#                                                REGARDLESS of matches)
#   corro.subs.executor.depth         gauge      diff jobs submitted but
#                                                unfinished; > workers
#                                                means matchers queue
#   corro.subs.executor.submitted.total counter
#   corro.subs.executor.wait.seconds  histogram  queue wait before a
#                                                diff starts
#   corro.agent.changes.hooks.seconds histogram  per committed batch:
#                                                total change-hook time
#                                                on the write path
# Canonical rows live in the COMPONENTS.md observability table
# (lint_metrics.py enforces both directions).

# The CRDT merge kernel's lane (ops/crdt_merge.py `_merge_kernel`):
# per-batch decision outcomes, drained by the host wrapper in the same
# readback as the decision outputs.
CRDT_MERGE_EVENTS = (
    "decide_won",         # changes that won their cell/row decision
    "decide_transition",  # causal-length transitions among the wins
    "decide_stale",       # changes beaten by local state or the batch
    "decide_ambiguous",   # undecidable digest ties (host-engine fallback)
)

EVENTS_BY_KERNEL = {
    "dense": KERNEL_EVENTS,
    "pview": KERNEL_EVENTS,
    "crdt_merge": CRDT_MERGE_EVENTS,
}


def record_phase_seconds(
    kernel: str, phase: str, seconds: float, registry: Registry = METRICS
) -> None:
    """Publish one phase timing into the shared registry (gauge: latest
    measurement wins — phase profiles are point-in-time tables, not
    accumulating counters)."""
    registry.gauge(
        "corro.kernel.phase.seconds", kernel=kernel, phase=phase
    ).set(seconds)


def record_kernel_events(
    kernel: str, deltas, registry: Registry = METRICS
) -> None:
    """Publish one drained batch of device event counts: `deltas` is a
    sequence aligned with `EVENTS_BY_KERNEL[kernel]`.  Zero deltas are
    skipped so idle kernels do not mint series."""
    names = EVENTS_BY_KERNEL[kernel]
    for name, d in zip(names, deltas):
        d = float(d)
        if d:
            registry.counter(
                "corro.kernel.events.total", kernel=kernel, event=name
            ).inc(d)


def kernel_event_totals(
    registry: Registry = METRICS,
) -> Dict[str, Dict[str, float]]:
    """{kernel: {event: total}} view of the event-counter family — the
    shape `/v1/status` and `obs_report.py` serve."""
    out: Dict[str, Dict[str, float]] = {}
    for kind, name, labels, value in registry.snapshot():
        if kind == "counter" and name == "corro.kernel.events.total":
            out.setdefault(labels.get("kernel", "?"), {})[
                labels.get("event", "?")
            ] = value
    return out


async def serve_prometheus(addr: str, registry: Registry = METRICS):
    """Serve the registry at GET /metrics on `addr` ("host:port").

    Counterpart of `setup_prometheus` (`klukai/src/command/agent.rs:29-63`).
    Returns an aiohttp AppRunner; call `.cleanup()` to stop.
    """
    from aiohttp import web

    async def h_metrics(_request):
        return web.Response(
            text=registry.render_prometheus(),
            content_type="text/plain",
        )

    app = web.Application()
    app.router.add_get("/metrics", h_metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    host, port = addr.rsplit(":", 1)
    site = web.TCPSite(runner, host, int(port))
    await site.start()
    return runner

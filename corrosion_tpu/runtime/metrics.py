"""Minimal metrics registry (counters/gauges/histograms) with a Prometheus
text exposition, standing in for the reference's `metrics` facade +
Prometheus exporter (`klukai/src/command/agent.rs:29-63`). ~Same series
names are emitted by the runtime so dashboards translate directly.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Dict, List, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def add(self, v: float) -> None:
        self.value += v


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class Histogram:
    __slots__ = ("buckets", "counts", "total", "count")

    def __init__(self, buckets=_DEFAULT_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_right(self.buckets, v)] += 1
        self.total += v
        self.count += 1


class Registry:
    def __init__(self):
        self._counters: Dict[Tuple[str, LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelKey], Gauge] = {}
        self._histograms: Dict[Tuple[str, LabelKey], Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, **labels: str) -> Counter:
        key = (name, _labels_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
            return c

    def gauge(self, name: str, **labels: str) -> Gauge:
        key = (name, _labels_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge()
            return g

    def histogram(self, name: str, **labels: str) -> Histogram:
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram()
            return h

    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4."""
        out: List[str] = []

        def fmt(name: str, labels: LabelKey, extra: Dict[str, str] = ()) -> str:
            norm = name.replace(".", "_").replace("-", "_")
            items = list(labels) + list(dict(extra).items() if extra else [])
            if items:
                lbl = ",".join(f'{k}="{v}"' for k, v in items)
                return f"{norm}{{{lbl}}}"
            return norm

        with self._lock:
            for (name, labels), c in sorted(self._counters.items()):
                out.append(f"{fmt(name, labels)} {c.value}")
            for (name, labels), g in sorted(self._gauges.items()):
                out.append(f"{fmt(name, labels)} {g.value}")
            for (name, labels), h in sorted(self._histograms.items()):
                cum = 0
                for i, b in enumerate(h.buckets):
                    cum += h.counts[i]
                    out.append(
                        f"{fmt(name + '_bucket', labels, {'le': str(b)})} {cum}"
                    )
                out.append(
                    f"{fmt(name + '_bucket', labels, {'le': '+Inf'})} {h.count}"
                )
                out.append(f"{fmt(name + '_sum', labels)} {h.total}")
                out.append(f"{fmt(name + '_count', labels)} {h.count}")
        return "\n".join(out) + "\n"


METRICS = Registry()

# Kernel phase-timing series (r6): the SWIM kernel profilers and the
# simulation drivers publish per-phase device seconds under one family,
#     corro.kernel.phase.seconds{kernel="pview"|"dense", phase="..."}
# so a dashboard shows where the tick goes the same way PROFILE.md's
# phase tables do.  Canonical pview phase names (the profiler's rows):
PVIEW_PHASES = (
    "pick",       # probe/feed partner selection gathers
    "inbox",      # gossip delivery (grouped sort or shift row-gather)
    "feed",       # feed/seed window pulls
    "merge",      # the merge scatter chain (+ own-entry pin, re-encode)
    "bufmrg",     # gossip buffer merge sorts
    "stats",      # blocked stats pass + readback
    "tick",       # whole fused tick (scanned, per tick)
)


def record_phase_seconds(
    kernel: str, phase: str, seconds: float, registry: Registry = METRICS
) -> None:
    """Publish one phase timing into the shared registry (gauge: latest
    measurement wins — phase profiles are point-in-time tables, not
    accumulating counters)."""
    registry.gauge(
        "corro.kernel.phase.seconds", kernel=kernel, phase=phase
    ).set(seconds)


async def serve_prometheus(addr: str, registry: Registry = METRICS):
    """Serve the registry at GET /metrics on `addr` ("host:port").

    Counterpart of `setup_prometheus` (`klukai/src/command/agent.rs:29-63`).
    Returns an aiohttp AppRunner; call `.cleanup()` to stop.
    """
    from aiohttp import web

    async def h_metrics(_request):
        return web.Response(
            text=registry.render_prometheus(),
            content_type="text/plain",
        )

    app = web.Application()
    app.router.add_get("/metrics", h_metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    host, port = addr.rsplit(":", 1)
    site = web.TCPSite(runner, host, int(port))
    await site.start()
    return runner

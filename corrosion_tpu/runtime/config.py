"""Configuration: TOML file + environment overrides + builder.

Counterpart of `klukai-types/src/config.rs:62-458`. Same sections (db, api,
gossip, perf, admin, telemetry, log, consul) and the same env-override
convention: `CORRO_DB__PATH=/x` overrides `db.path` (double underscore as
the section separator, config.rs:304-310). PerfConfig carries the channel
sizes and protocol knobs with the reference's defaults (config.rs:11-59,
179-235).
"""

from __future__ import annotations

import os

try:
    import tomllib
except ModuleNotFoundError:  # stdlib only on 3.11+; tomli is API-identical
    import tomli as tomllib
from dataclasses import dataclass, field, fields, is_dataclass, replace
from typing import List, Optional


@dataclass
class DbConfig:
    path: str = "./corrosion.db"
    schema_paths: List[str] = field(default_factory=list)
    subscriptions_path: Optional[str] = None


@dataclass
class ApiConfig:
    bind_addr: List[str] = field(default_factory=lambda: ["127.0.0.1:8080"])
    authz_bearer: Optional[str] = None


@dataclass
class GossipTlsConfig:
    """[gossip.tls] — mirrors the reference's rustls config
    (`api/peer/mod.rs:152-373`): server cert/key, CA pinning for peer
    verification, optional mTLS client-cert requirement, and an insecure
    mode that skips server verification (SkipServerVerification)."""

    cert_file: Optional[str] = None
    key_file: Optional[str] = None
    ca_file: Optional[str] = None  # verify peers against this CA
    insecure: bool = False  # client side: skip server verification
    # mTLS: server requires + verifies client certs against ca_file
    mtls: bool = False
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None


@dataclass
class GossipConfig:
    bind_addr: str = "0.0.0.0:8787"
    external_addr: Optional[str] = None
    bootstrap: List[str] = field(default_factory=list)
    cluster_id: int = 0
    # explicit plaintext mode, like the reference's quinn_plaintext crypto
    # session for trusted networks; set false + a [gossip.tls] section for
    # a TLS-secured gossip plane
    plaintext: bool = True
    # "tcp" = UDP datagrams + lane-tagged TCP/TLS streams (default);
    # "quic" = plaintext QUIC (RFC 9000 subset, net/quic.py), the
    # reference's native wire (quinn + quinn_plaintext.rs)
    transport: str = "tcp"
    tls: GossipTlsConfig = field(default_factory=GossipTlsConfig)
    max_mtu: Optional[int] = None
    idle_timeout_secs: int = 30
    # quic only: where outbound dials originate (config.rs:162-163,
    # default [::]:0). Port 0 -> 8 hashed dial-only sockets
    # (transport.rs:57-71 kernel-buffer dilution); a fixed port -> 1
    # socket bound there.
    client_addr: Optional[str] = None

    @property
    def tls_enabled(self) -> bool:
        return not self.plaintext and self.tls.cert_file is not None


@dataclass
class PerfConfig:
    # channel sizes (config.rs:179-235)
    changes_channel_len: int = 2048
    bcast_channel_len: int = 10_000
    apply_channel_len: int = 512
    foca_channel_len: int = 1024
    # ingestion (config.rs:15-47)
    processing_queue_len: int = 20_000
    apply_queue_len: int = 50
    apply_queue_timeout_ms: int = 10
    max_concurrent_applies: int = 5
    # sync (config.rs:11-13, 53-59)
    sync_interval_min_secs: float = 1.0
    sync_interval_max_secs: float = 15.0
    sync_peers_min: int = 3
    sync_peers_max: int = 10
    max_concurrent_inbound_syncs: int = 3
    # local-commit group coalescing (r14): concurrent local writers
    # batch into one BEGIN IMMEDIATE..COMMIT (one fsync, one store-lock
    # hold, consecutive db_versions; per-writer SAVEPOINT isolation).
    # The first writer commits immediately when nobody else is queued,
    # so solo p50 latency is unchanged; `group_commit_wait` > 0 adds an
    # opt-in extra coalescing window for bursty single writers, and the
    # writer/byte budgets bound one shared transaction's blast radius.
    group_commit: bool = True
    group_commit_wait: float = 0.0
    group_commit_max_writers: int = 64
    group_commit_max_bytes: int = 1 << 20
    # per-group fanout (r21): the group leader runs ONE post-commit
    # loop re-entry for the whole batch — one origin stamp, one hooks
    # flush, one chunk pass over the stamped wire cells, one channel
    # round — instead of each follower paying its own hooks+chunk+send
    # block after its future resolves — plus the leader's pre-gather
    # loop yield that lets just-settled writers join the next batch
    # (full occupancy instead of alternating full/size-1 batches).
    # false (or env CORRO_GROUP_FANOUT=0) restores the r15 per-tx
    # post-commit path and gathering behavior.
    group_fanout: bool = True
    # direct change capture (r15): WriteTx parses recognized INSERT/
    # UPDATE/DELETE statement shapes and records the written cells in
    # memory, bypassing the AFTER-trigger → __crdt_pending round-trip
    # (~60% of a 10-row commit in the r14 profile).  Triggers stay
    # installed and capture raw/unrecognized SQL; false (or env
    # CORRO_CAPTURE=trigger) restores the pure trigger path.
    direct_capture: bool = True
    # dedicated committer thread (r24): one long-lived thread per store
    # runs every group commit, fed by a lock-free handoff deque + a
    # single event-loop wakeup — the leader parks on a future instead of
    # paying an executor submit/teardown (`asyncio.to_thread`) per
    # batch.  Backpressure is unchanged: the leader still holds the
    # priority write gate across the commit, so a stuck committer
    # surfaces as the existing typed gate refusals, never a new hang.
    # false (or env CORRO_COMMITTER=to_thread) restores the r15–r23
    # per-batch to_thread hop (the ingest bench's r24 pre mode).
    committer_thread: bool = True
    # broadcast
    broadcast_interval_ms: int = 500
    broadcast_cutoff_bytes: int = 64 * 1024
    broadcast_rate_limit_bytes: int = 10 * 1024 * 1024
    max_inflight_broadcasts: int = 500
    # maintenance (handlers.rs:379-547)
    wal_threshold_gb: float = 5.0
    wal_check_interval_secs: float = 60.0
    vacuum_interval_secs: float = 300.0
    vacuum_min_freelist_pages: int = 10_000


@dataclass
class SloConfig:
    """[slo] — the write→event latency objectives served by GET /v1/slo
    (r11).  `targets` maps e2e stage → latency target in seconds at the
    `objective` quantile (a stage absent from the map is reported but
    never judged); burn rate is the violating fraction over the error
    budget `1 - objective`, and a burn > 1 sustained for
    `breach_checks` consecutive checks trips a flight-recorder incident
    dump.  The canary probe is opt-in: a background loop writing tiny
    synthetic rows to `canary_table` under a self-subscription,
    continuously measuring TRUE end-to-end write→event latency on a
    live cluster (remote rows measure cross-node latency from their
    embedded origin wall stamp)."""

    window_secs: float = 60.0
    objective: float = 0.99
    targets: dict = field(
        default_factory=lambda: {
            "broadcast": 0.75,
            "apply": 1.5,
            "match": 1.5,
            "deliver": 0.25,
            "total": 3.0,
        }
    )
    breach_checks: int = 3
    canary: bool = False
    canary_interval_secs: float = 1.0
    canary_table: str = "corro_canary"


@dataclass
class TraceConfig:
    """[trace] — the r19 end-to-end write-tracing plane (runtime/
    trace.py stage spans + runtime/tracestore.py tail sampler).

    When `enabled`, every traced write's stage spans
    (write→broadcast→apply→match→deliver, stitched cross-node by the
    W3C traceparent on the broadcast/sync envelope ext) buffer in a
    bounded per-trace ring and are KEPT only when the trace errors,
    breaches an [slo] per-stage target, was head-lottery-selected at
    the origin (1 in `lottery_n`, deterministic on the trace id so
    every node keeps the same traces), or wins the local lottery —
    everything else drops at close with O(1) cost.  Kept traces serve
    `GET /v1/traces` (slowest-N, per-stage breakdown), feed exemplar
    ids into /v1/slo stage rows, and export through the OTLP plane
    when a collector is configured.  `lottery_n=0` disables the
    lottery (keep only errors/breaches/forced)."""

    enabled: bool = True
    lottery_n: int = 64
    max_traces: int = 512
    max_spans_per_trace: int = 64
    keep_max: int = 256
    idle_close_secs: float = 1.0


@dataclass
class TsdbConfig:
    """[tsdb] — the local metrics time-series store (runtime/tsdb.py,
    r20).  A daemon thread samples the process registry every
    `sample_interval_secs` (counters→rates, gauges→levels, latency
    p50/p99), keeping `slots` points per series in a bounded ring —
    the substrate the `[alerts]` rules evaluate against.  Memory is
    capped by `slots × max_series` and accounted in `corro.tsdb.*`."""

    enabled: bool = True
    # Prometheus-scrape-like cadence: cheap enough to forget about
    # (one registry snapshot per tick), fine enough for for-durations
    # in the seconds; harnesses that need sub-second alerting
    # (scripts/traffic_sim.py) tune it per run
    sample_interval_secs: float = 5.0
    slots: int = 240  # ring depth per series (240 × 5 s = 20 min)
    max_series: int = 4096


@dataclass
class ProfileConfig:
    """[profile] — the continuous profiling plane (runtime/profiler.py
    + runtime/profstore.py, r23).  An always-on daemon-thread stack
    sampler walks every thread at `hz` (wall-clock sampling), folding
    classified stacks into a bounded ring of `slots` windows of
    `window_secs` each; when the sampler's own measured duty cycle
    exceeds `max_overhead_pct` it auto-sheds to `shed_hz`
    (`corro.profile.shed.total`) and recovers with hysteresis — Prime
    CCL discipline: the plane degrades itself, never the serving path.
    The statement-shape profiler (`corro.store.stmt.seconds{shape=}`)
    rides the same install.  Served as `GET /v1/profile?window=…&
    format=folded|speedscope`; alert firings pin the hot window to
    their flight-recorder incident.  Process-global: the first agent's
    knobs win (the tsdb/tracestore contract)."""

    enabled: bool = True
    hz: float = 67.0
    shed_hz: float = 11.0
    max_overhead_pct: float = 1.0
    window_secs: float = 5.0
    slots: int = 24  # ring depth (24 × 5 s = 2 min of hot windows)
    max_stacks: int = 512  # distinct folded stacks per window


@dataclass
class AlertsConfig:
    """[alerts] — declarative anomaly rules over the TSDB
    (runtime/alerts.py, r20).  `rules` is a list of
    `[[alerts.rules]]` tables ({name, kind=threshold|rate|absent,
    series, op, value, for_secs, window_secs, severity, agg, labels,
    summary}); `default_pack` prepends the built-in pack (SLO burn,
    loop lag, shed/refusal rates, open sync circuits, view
    divergence, store faults) — an operator rule with the same name
    overrides the pack's.  `for_scale` multiplies every rule's
    for/window durations (the chaos harness shrinks them to fit tiny
    scenario windows).  The health knobs feed the Lifeguard-style
    local-health score that WIDENS for-durations (up to
    `health_widen_max`×) when this node itself is sick — a lagging
    node distrusts its own timers instead of flooding false pages."""

    enabled: bool = True
    eval_interval_secs: float = 5.0
    history_max: int = 256
    default_pack: bool = True
    for_scale: float = 1.0
    rules: List[dict] = field(default_factory=list)
    health_lag_secs: float = 0.25
    health_fault_rate: float = 5.0
    health_widen_max: float = 4.0


@dataclass
class RemediationConfig:
    """[remediation] — the supervised remediation plane
    (agent/remediation.py, r22) that closes the observe→act loop: a
    supervisor tick consumes `[alerts]` firings and drives typed,
    cooldown-gated actuators (view-divergence → targeted anti-entropy
    sync, store-faults → matcher-home drain + refuse-bulk, sustained
    slo-burn → laggard-tier shed).

    `enabled=false` is the GLOBAL KILL-SWITCH and the default: the
    supervisor still runs, evaluates every gate, and records typed
    "would_act" events (flight-recorded, served by GET
    /v1/remediation) — observe-only mode, so operators audit exactly
    what the plane WOULD have done before arming it.  `defer_health`
    is the Lifeguard self-distrust bar (arXiv:1707.00788): when the
    local `AlertEngine.health_score()` is at/above it, local impulses
    defer to the digest-merged cluster-scope alert rollup — the node
    acts only when another node's digest confirms the same rule is
    firing.  `slo_sustain_secs` keeps the shed actuator off transient
    slo-burn blips (Prime CCL: shrink capacity, never convert requests
    into stalls); per-actuator cooldowns stop act storms; and
    `refuse_bulk_secs` bounds how long a store-faulting node refuses
    bulk snapshot serves + new stream admissions before the flag
    self-expires (revert clears it sooner on alert resolve)."""

    enabled: bool = False
    tick_secs: float = 2.0
    act_timeout_secs: float = 30.0
    history_max: int = 256
    defer_health: float = 0.5
    sync_cooldown_secs: float = 30.0
    drain_cooldown_secs: float = 60.0
    shed_cooldown_secs: float = 30.0
    slo_sustain_secs: float = 5.0
    refuse_bulk_secs: float = 60.0


@dataclass
class PubsubConfig:
    """[pubsub] — live-query matcher knobs.  `candidate_batch_wait` is
    the matcher's candidate-batching window in seconds: the PR-6 SLO
    plane attributed the old ~600 ms p50 write→event total to exactly
    this wait (the pubsub.rs:1069 parity value 0.6, kept as the
    matcher-module constant), and since the r10 matcher is ~6 ms/batch
    FLAT the wide window bought nothing — the default is now 0.1 s
    (write→event p50 ~0.6 s → ~0.15 s, SLO_BASELINE.json, with no
    events/s regression in PUBSUB_BENCH.json).  Operators can raise it
    back to trade match latency for fewer, larger diff batches under
    extreme write fan-in (surfaced in /v1/status)."""

    candidate_batch_wait: float = 0.1


@dataclass
class SubsConfig:
    """[subs] — serving-plane admission control + stream backpressure
    (r16).  One node is expected to host 10k–100k concurrent
    subscription streams: `max_streams` bounds how many the HTTP plane
    admits (excess subscribes get a typed 503, never a half-served
    stream), and the per-stream lag bounds govern the coalesced fan-out
    writer — a stream whose socket stops draining accumulates pending
    batch payloads until `max_lag_bytes`/`max_lag_batches`, then is
    SHED with a terminal `{"lagging": ...}` frame (Prime CCL
    discipline: a slow consumer degrades, it never stalls the
    DiffExecutor or its sibling streams).  `matcher_linger_secs` is the
    teardown grace after a deduped matcher's LAST stream detaches: a
    reconnect inside the window re-uses the warm matcher + changes log
    (the client resumes by change id), after it the sub db is reaped.
    `writer_tick_secs` paces retry flushes of clogged sinks;
    `diff_workers` sizes the shared DiffExecutor pool."""

    max_streams: int = 100_000
    max_lag_bytes: int = 4 * 1024 * 1024
    max_lag_batches: int = 1024
    matcher_linger_secs: float = 30.0
    writer_tick_secs: float = 0.05
    diff_workers: int = 4
    # "writer" = the r16 shared coalescing fan-out writer (sinks, lag
    # shedding); "queue" = the r10 per-stream drain-loop reference path
    # (one task + one queue per stream, no shedding) — the bench's A/B
    # axis and the rollback lever (env: CORRO_SUBS__FANOUT=queue)
    fanout: str = "writer"


@dataclass
class SyncConfig:
    """[sync] — the r17 cold-node catch-up plane (agent/catchup.py,
    store/snapshot.py).

    Snapshot bootstrap: a node whose estimated version gap against the
    freshest peer exceeds `snapshot_min_gap_versions` fetches the
    peer's cached compressed snapshot (staleness-bounded by
    `snapshot_max_age_secs` on the SERVING side), installs it through
    the locked-swap path, and tops up with delta sync from the embedded
    watermark — instead of replaying the whole gap change-by-change.
    `snapshot=false` disables both serving and bootstrapping (the pure-
    delta A/B lever `scripts/bench_sync.py` measures against).

    Resumable delta sync: a peer dropping mid-stream releases its
    unserved version ranges back to the shared claim ledger and the
    SAME sync round re-claims them from surviving peers, up to
    `max_waves` waves paced by `resume_backoff_{min,max}_secs` (Prime
    CCL discipline: a dead peer degrades the transfer, never restarts
    or deadlocks it).  A peer failing `circuit_failures` consecutive
    sessions opens its circuit for `circuit_reset_secs` (per-peer
    state on the Agent handle): peer choice DEPRIORITIZES it (never
    excludes — small clusters must keep probing through a flap) and
    the snapshot bootstrap refuses it as a bulk-transfer source.  The
    default 0 auto-scales the reset to 4 × `perf.sync_interval_max_
    secs` — a breaker horizon must track the retry cadence it guards,
    or fast-cadence deployments blank their sync plane for hundreds of
    rounds after one flap."""

    snapshot: bool = True
    snapshot_min_gap_versions: int = 10_000
    snapshot_max_age_secs: float = 60.0
    snapshot_chunk_bytes: int = 256 * 1024
    snapshot_timeout_secs: float = 300.0
    # after a successful install the bootstrap stands down and the
    # delta plane owns the residual gap: under sustained write fire
    # every small transaction is a fresh version, so the version-gap
    # heuristic alone would re-trigger bootstrap each round and reset
    # the node to the (stale) watermark forever
    snapshot_cooldown_secs: float = 300.0
    max_concurrent_snapshot_serves: int = 2
    max_waves: int = 3
    resume_backoff_min_secs: float = 0.1
    resume_backoff_max_secs: float = 2.0
    circuit_failures: int = 3
    circuit_reset_secs: float = 0.0  # 0 → 4 × perf.sync_interval_max_secs


@dataclass
class ClusterObsConfig:
    """[cluster] — the r12 cluster observatory (agent/observatory.py).
    Each node builds a telemetry digest every `digest_interval_secs`
    and piggybacks it on the gossip/broadcast planes; aggregation is
    freshest-per-node with digests older than `stale_after_secs`
    excluded from /v1/cluster merges.  The view-divergence detector
    opens an episode (one flight-recorder incident dump) after
    `divergence_checks` consecutive divergent checks; an ACTIVE member
    whose digests stop arriving for `silent_after_secs` (default:
    `silent_after_mult × digest_interval_secs`) counts as divergent,
    and remembered view hashes are compared for
    `divergence_memory_secs` after the last digest."""

    digests: bool = True
    digest_interval_secs: float = 2.0
    # r22: hard ceiling on the ENCODED digest.  The digest is cumulative
    # (histograms only grow), and the gossip plane offers pick_ext only
    # the bytes a SWIM frame has left (~1135 quiet, less with piggyback)
    # — so a digest that outgrows the quiet frame is skipped by EVERY
    # datagram and the view/census core (the split-brain signal) starves
    # cluster-wide.  Worse, an open divergence episode ADDS an alert
    # block to every digest, so the starvation is self-sustaining.
    # build_and_store degrades an over-ceiling digest (drop non-total
    # stage histograms, then stages/events/alert tail) instead: shed
    # telemetry richness, never liveness.
    max_wire_bytes: int = 896
    stale_after_secs: float = 20.0
    silent_after_secs: float = 0.0  # 0 → silent_after_mult × interval
    # the silence threshold must undercut the SWIM suspicion window
    # (~9 s at n=3 defaults) or the membership downs a partitioned peer
    # before the observatory can flag the divergence: 2.5 × 2 s = 5 s
    # silence + 2 divergent checks ≈ 9 s worst-case detection
    silent_after_mult: float = 2.5
    divergence_checks: int = 2
    divergence_memory_secs: float = 120.0


@dataclass
class AdminConfig:
    uds_path: str = "./admin.sock"


@dataclass
class ConsulConfig:
    enabled: bool = False
    address: str = "127.0.0.1:8500"  # consul agent HTTP address
    # Reverse TTL sync: each entry is a `[[consul.ttl_checks]]` TOML table
    # {"id": <consul check id>, "query": <SQL run against the store>}.
    # The sync loop evaluates the query each tick and PUTs the derived
    # pass/warn/fail status to /v1/agent/check/update/<id>, hash-gated on
    # (status, output) with a forced refresh every ttl_refresh_seconds so
    # the Consul-side TTL never lapses while we're healthy.
    ttl_checks: List[dict] = field(default_factory=list)
    ttl_refresh_seconds: float = 30.0


@dataclass
class TelemetryConfig:
    prometheus_bind_addr: Optional[str] = None
    # OTLP/HTTP collector base URL (e.g. "http://127.0.0.1:4318") — the
    # reference's `telemetry.open-telemetry` exporter config
    # (`klukai/src/main.rs:68-76`).  Env fallback: the standard
    # OTEL_EXPORTER_OTLP_ENDPOINT, honored at agent startup (cli.py).
    open_telemetry_endpoint: Optional[str] = None


@dataclass
class LogConfig:
    format: str = "plaintext"  # or "json"
    colors: bool = True
    level: str = "info"


@dataclass
class Config:
    db: DbConfig = field(default_factory=DbConfig)
    api: ApiConfig = field(default_factory=ApiConfig)
    gossip: GossipConfig = field(default_factory=GossipConfig)
    perf: PerfConfig = field(default_factory=PerfConfig)
    admin: AdminConfig = field(default_factory=AdminConfig)
    consul: ConsulConfig = field(default_factory=ConsulConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    log: LogConfig = field(default_factory=LogConfig)
    slo: SloConfig = field(default_factory=SloConfig)
    pubsub: PubsubConfig = field(default_factory=PubsubConfig)
    subs: SubsConfig = field(default_factory=SubsConfig)
    cluster: ClusterObsConfig = field(default_factory=ClusterObsConfig)
    sync: SyncConfig = field(default_factory=SyncConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    tsdb: TsdbConfig = field(default_factory=TsdbConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    alerts: AlertsConfig = field(default_factory=AlertsConfig)
    remediation: RemediationConfig = field(default_factory=RemediationConfig)


_ENV_PREFIX = "CORRO_"


def _coerce(value: str, target_type):
    if target_type is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if target_type is int:
        return int(value)
    if target_type is float:
        return float(value)
    if target_type in (List[str], list):
        return [v.strip() for v in value.split(",") if v.strip()]
    return value


def _apply_dict(cfg, data: dict):
    for f in fields(cfg):
        if f.name in data:
            v = data[f.name]
            cur = getattr(cfg, f.name)
            if is_dataclass(cur) and isinstance(v, dict):
                _apply_dict(cur, v)
            else:
                setattr(cfg, f.name, v)


def load_config(path: Optional[str] = None, env: Optional[dict] = None) -> Config:
    """TOML file (optional) overlaid with CORRO_SECTION__FIELD env vars."""
    cfg = Config()
    if path:
        with open(path, "rb") as f:
            _apply_dict(cfg, tomllib.load(f))
    env = env if env is not None else os.environ
    for key, value in env.items():
        if not key.startswith(_ENV_PREFIX):
            continue
        parts = key[len(_ENV_PREFIX):].lower().split("__")
        if len(parts) != 2:
            continue
        section, name = parts
        sec = getattr(cfg, section, None)
        if sec is None or not hasattr(sec, name):
            continue
        ftype = str({f.name: f.type for f in fields(sec)}.get(name))
        # with `from __future__ import annotations` field types are strings
        # like 'Optional[int]'; match on the contained scalar type
        target = str
        if "List" in ftype or "list" in ftype:
            target = list
        elif "bool" in ftype:
            target = bool
        elif "int" in ftype:
            target = int
        elif "float" in ftype:
            target = float
        setattr(sec, name, _coerce(value, target))
    return cfg

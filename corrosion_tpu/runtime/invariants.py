"""Always/sometimes invariant hooks woven through production code.

Counterpart of the reference's Antithesis SDK usage (SURVEY §4): the
reference sprinkles `assert_always!` (invariants that must hold on every
evaluation), `assert_sometimes!` (coverage markers that must fire at
least once under a thorough workload) and `assert_unreachable!` through
production paths — e.g. gap deletion effective (`agent.rs:1144`),
contiguous seq ranges (`util.rs:1170`), locks held < 60 s
(`setup.rs:231`), "Corrosion syncs with other nodes"
(`handlers.rs:840`). They are inert in CI and evaluated under the
deterministic-hypervisor environment.

Here the same three primitives are driven by `CORRO_INVARIANTS`:

  off     — zero work beyond a truthiness check (production default)
  log     — violations log + count via METRICS (the CI default: the
            test suite runs with invariants observable)
  strict  — violations raise InvariantViolation (chaos/soak harnesses)

`sometimes_registry()` exposes which coverage markers have fired, so a
soak test can assert the workload actually exercised the paths it
claims to (the Antithesis "sometimes" contract).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

from corrosion_tpu.runtime.metrics import METRICS

logger = logging.getLogger(__name__)

_MODE_ENV = "CORRO_INVARIANTS"


class InvariantViolation(AssertionError):
    pass


_lock = threading.Lock()
_sometimes: Dict[str, int] = {}


def _mode() -> str:
    return os.environ.get(_MODE_ENV, "off")


def enabled() -> bool:
    """Fast-path guard for call sites whose CONDITION is expensive to
    compute: `if invariants.enabled(): assert_always(costly(), ...)`.
    Off mode (the production default) then costs one env lookup."""
    return _mode() != "off"


def assert_always(
    condition: bool, name: str, details: Optional[dict] = None
) -> bool:
    """The property must hold on EVERY evaluation (assert_always!)."""
    if condition:
        return True
    mode = _mode()
    if mode == "off":
        return False
    METRICS.counter("corro.invariant.violated", invariant=name).inc()
    logger.error("invariant violated: %s %s", name, details or {})
    if mode == "strict":
        # chaos trip: before the violation kills the harness, dump the
        # flight recorder's per-tick history — the black box a post-
        # mortem replays the churn/suspicion timeline from (best-effort,
        # a second failure must not mask the invariant itself)
        try:
            from corrosion_tpu.runtime.records import FLIGHT

            FLIGHT.snapshot_incident(f"invariant:{name}")
        except Exception:  # pragma: no cover - diagnostics never mask
            pass
        raise InvariantViolation(f"{name}: {details or {}}")
    return False


def assert_sometimes(name: str, condition: bool = True) -> None:
    """Coverage marker: a thorough workload must reach this at least
    once (assert_sometimes!). Cheap enough to leave on everywhere."""
    if not condition:
        return
    with _lock:
        _sometimes[name] = _sometimes.get(name, 0) + 1
    if _mode() != "off":
        METRICS.counter("corro.invariant.sometimes", invariant=name).inc()


def assert_unreachable(name: str, details: Optional[dict] = None) -> None:
    """This line must never execute (assert_unreachable!)."""
    assert_always(False, f"unreachable:{name}", details)


def sometimes_registry() -> Dict[str, int]:
    """Snapshot of fired coverage markers (name → count)."""
    with _lock:
        return dict(_sometimes)


def reset_sometimes() -> None:
    with _lock:
        _sometimes.clear()

"""Three-class priority gate for the single write path.

Counterpart of the reference's SplitPool write queues
(`klukai-types/src/agent.rs:478-519`): one writable connection, three
FIFO queues in front of it — `priority` (local client writes,
`/v1/transactions`), `normal` (remote change applies), `low`
(background work) — so a burst of sync-applied remote changes can never
starve local write latency.

The gate is an asyncio-level single permit. Work that takes the store's
thread lock (WriteTx, apply_changes) must acquire a lane first; the
store lock then never has more than one waiter, making the asyncio
queue the ONLY ordering that matters.
"""

from __future__ import annotations

import asyncio
import enum
from collections import deque
from typing import Deque, Optional

from corrosion_tpu.runtime.metrics import METRICS


class WritePriority(enum.IntEnum):
    PRIORITY = 0  # local client writes (write_priority, agent.rs:586)
    NORMAL = 1  # remote change applies (write_normal)
    LOW = 2  # background maintenance (write_low)


class PriorityWriteGate:
    """Single-permit async gate with three strict-priority FIFO lanes.

    Release always wakes the highest non-empty lane; within a lane,
    arrival order (FIFO) is preserved. `async with gate:` acquires the
    NORMAL lane; `gate.priority()` / `gate.normal()` / `gate.low()`
    return context managers for explicit lanes.
    """

    def __init__(self):
        self._held = False
        self._waiters: tuple[Deque[asyncio.Future], ...] = (
            deque(),
            deque(),
            deque(),
        )

    def _gauge(self) -> None:
        for lane in WritePriority:
            METRICS.gauge(
                f"corro.write_gate.waiting.{lane.name.lower()}"
            ).set(len(self._waiters[lane]))
        # SplitPool write-side parity (agent.rs:478): 1 permit total
        METRICS.gauge("corro.sqlite.write.permits.available").set(
            0 if self._held else 1
        )
        METRICS.gauge("corro.sqlite.pool.write.connections.waiting").set(
            sum(len(q) for q in self._waiters)
        )

    async def acquire(self, lane: WritePriority = WritePriority.NORMAL) -> None:
        if not self._held and not any(self._waiters):
            self._held = True
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters[lane].append(fut)
        self._gauge()
        try:
            await fut
        except asyncio.CancelledError:
            if not fut.cancelled() and fut.done() and fut.result() is None:
                # woken AND cancelled: pass the permit on
                self.release()
            else:
                try:
                    self._waiters[lane].remove(fut)
                except ValueError:
                    pass
            raise
        finally:
            self._gauge()

    def release(self) -> None:
        for lane_q in self._waiters:
            while lane_q:
                fut = lane_q.popleft()
                if not fut.done():
                    fut.set_result(None)
                    return
        self._held = False

    def locked(self) -> bool:
        return self._held

    def lane(self, lane: WritePriority) -> "_LaneCM":
        return _LaneCM(self, lane)

    def priority(self) -> "_LaneCM":
        return self.lane(WritePriority.PRIORITY)

    def normal(self) -> "_LaneCM":
        return self.lane(WritePriority.NORMAL)

    def low(self) -> "_LaneCM":
        return self.lane(WritePriority.LOW)

    async def __aenter__(self):
        await self.acquire(WritePriority.NORMAL)
        return self

    async def __aexit__(self, *exc):
        self.release()


class _LaneCM:
    def __init__(self, gate: PriorityWriteGate, lane: WritePriority):
        self._gate = gate
        self._lane = lane

    async def __aenter__(self):
        await self._gate.acquire(self._lane)
        return self._gate

    async def __aexit__(self, *exc):
        self._gate.release()

"""Tripwire: cooperative shutdown signal threaded through every loop.

Counterpart of `klukai-types/src/tripwire/` (watch-channel future completed
on SIGTERM/SIGINT or programmatic trip, plus the `preemptible` combinator
every loop wraps its awaits in) and `spawn.rs`'s counted-task graceful
shutdown (`wait_for_all_pending_handles`, ≤60 s drain).
"""

from __future__ import annotations

import asyncio
import contextlib
import signal
from enum import Enum
from typing import Awaitable, Optional, Set, TypeVar

T = TypeVar("T")


class Outcome(Enum):
    COMPLETED = "completed"
    PREEMPTED = "preempted"


class Tripwire:
    def __init__(self):
        self._event = asyncio.Event()

    @classmethod
    def from_signals(cls) -> "Tripwire":
        tw = cls()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(
                    sig, tw.trip, f"signal:{signal.Signals(sig).name}"
                )
        return tw

    def trip(self, incident: Optional[str] = None) -> None:
        """Fire the tripwire.  `incident` names an ABNORMAL trip (a
        SIGTERM/SIGINT, an operator kill): the flight recorder's frame
        history is then dumped to a black-box file before the loops
        start draining — exactly the moment an operator later asks
        "what was the cluster doing when it died".  Graceful shutdown
        (agent/run.py `shutdown`) trips with no incident and dumps
        nothing."""
        if incident and not self._event.is_set():
            with contextlib.suppress(Exception):  # best-effort black box
                from corrosion_tpu.runtime.records import FLIGHT

                FLIGHT.snapshot_incident(incident)
        self._event.set()

    @property
    def tripped(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        await self._event.wait()

    async def preemptible(self, aw: Awaitable[T]):
        """Run `aw` unless the tripwire fires first.

        Returns (Outcome.COMPLETED, result) or (Outcome.PREEMPTED, None);
        the inner awaitable is cancelled on preemption.
        """
        task = asyncio.ensure_future(aw)
        trip_task = asyncio.ensure_future(self._event.wait())
        try:
            done, _ = await asyncio.wait(
                {task, trip_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if task in done:
                return Outcome.COMPLETED, task.result()
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await task
            return Outcome.PREEMPTED, None
        finally:
            trip_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await trip_task


class TaskTracker:
    """Counted critical tasks: shutdown waits for them (spawn.rs:17-134)."""

    def __init__(self):
        self._tasks: Set[asyncio.Task] = set()

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    @property
    def pending(self) -> int:
        return len(self._tasks)

    async def wait_all(self, timeout: float = 60.0) -> bool:
        """Wait ≤timeout for all tracked tasks; returns True if drained."""
        if not self._tasks:
            return True
        done, pending = await asyncio.wait(
            set(self._tasks), timeout=timeout
        )
        for t in pending:
            t.cancel()
        return not pending

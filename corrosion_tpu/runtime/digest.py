"""Telemetry digest: the compact per-node snapshot the cluster
observatory gossips (r12).

Each node periodically summarizes its own observability planes into one
`NodeDigest` — membership census + a canonical membership-VIEW hash,
the five cumulative `corro.e2e.*` stage histograms (sparse wire form of
`runtime/latency.py::LatencyHistogram`), kernel event-counter totals,
per-peer sync backlog, and a small health roll-up (LHM, loop lag) — and
disseminates it on the planes the cluster already runs: a version-gated
trailing ext on SWIM datagrams (`net/gossip_codec.py`) and on broadcast
envelopes (`types/codec.py` ext v2).  `agent/observatory.py` is the
anti-entropy layer on top (freshest-per-node wins, bounded staleness,
relay); this module is the pure data + wire codec half.

Wire discipline:
  - one leading version byte (`DIGEST_V1`); decoders reject newer
    majors instead of misparsing,
  - LEB128 uvarints everywhere a small integer travels,
  - histograms ride SPARSE and DELTA-ENCODED: occupied log-bucket
    indices as gaps (first index absolute, then index deltas ≥ 1), each
    with its uvarint count — a 5-stage digest of a quiet node is tens
    of bytes, and decode(encode(h)) reproduces the histogram
    bucket-for-bucket, so cross-node aggregation by
    `LatencyHistogram.merge` is EXACT (merge-of-decoded ≡
    decode-of-merged).

The digest is cumulative (not an inter-digest delta): with
freshest-per-node-wins aggregation a lost packet costs staleness, never
correctness — the property the /v1/cluster percentile pins rely on.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from corrosion_tpu.runtime.latency import E2E_STAGES, LatencyHistogram
from corrosion_tpu.types.codec import Reader, Writer

DIGEST_V1 = 1

# r20 alert-summary enum codes (wire form of the trailing alert block)
_SEV_CODE = {"info": 0, "warn": 1, "page": 2}
_SEV_NAME = {v: k for k, v in _SEV_CODE.items()}
_STATE_CODE = {"pending": 1, "firing": 2}
_STATE_NAME = {v: k for k, v in _STATE_CODE.items()}


def view_hash(ids: Iterable[bytes]) -> int:
    """Canonical u64 hash of a membership view: the sorted 16-byte actor
    ids of every ACTIVE member (self included).  Two nodes report the
    same hash iff they agree on who is in the cluster — the divergence
    (split-brain) detector's whole signal, so the canonicalization
    (sort, raw id bytes only, no states) must never drift."""
    h = hashlib.blake2b(digest_size=8)
    for b in sorted(ids):
        if len(b) != 16:
            raise ValueError(f"actor id must be 16 bytes, got {len(b)}")
        h.update(b)
    return int.from_bytes(h.digest(), "big")


@dataclass
class NodeDigest:
    """One node's gossiped telemetry snapshot.  `wall` is the ORIGIN
    node's clock at build time — freshness comparisons are always
    per-node (same clock), so cross-node skew cannot reorder them."""

    actor_id: bytes  # 16 raw bytes
    seq: int  # per-boot monotone build counter
    wall: float  # origin wall clock at snapshot
    view_hash: int  # canonical membership-view hash (u64)
    view_size: int  # active members incl. self
    alive: int = 0
    suspect: int = 0
    downed: int = 0  # remembered DOWN ids (Membership.downed)
    lhm: int = 0  # Lifeguard local-health score
    loop_lag: float = 0.0  # max event-loop lag seconds
    # per-peer sync backlog: origin actor id -> versions still needed
    sync_backlog: Dict[bytes, int] = field(default_factory=dict)
    # r17: total versions this node HOLDS across all origin actors
    # (heads minus gaps minus incomplete partials) — the catch-up
    # plane's freshness signal: peer choice biases toward the highest
    # advertiser and the snapshot-bootstrap gap heuristic compares
    # against it.  Rides as a TRAILING field (old decoders stop before
    # it, new decoders default 0 on eof — the envelope-ext tolerance).
    heads_total: int = 0
    # r20: this node's ACTIVE alerts (runtime/alerts.py
    # `active_summaries`: rule, severity, state pending|firing, since
    # wall, drill flag, trigger value) — how `GET /v1/alerts?scope=
    # cluster` answers from ANY node.  Rides as a second TRAILING
    # block after `heads_total` with the same eof tolerance: old
    # decoders stop before it, new decoders default to [] on eof.
    alerts: List[dict] = field(default_factory=list)
    # r23: this node's top self-time profile frames
    # (runtime/profiler.py `hotspots`: frame display name + sample
    # count) — how `GET /v1/profile?scope=cluster` serves a
    # cluster-scope hotspot table from ANY node.  Third TRAILING block
    # after `alerts`, same eof tolerance; under the wire-budget ladder
    # it is the FIRST tier shed (agent/observatory.py) — profile color
    # yields to view/census core.
    hotspots: List[dict] = field(default_factory=list)
    # device kernel event totals (corro.kernel.events.total), summed
    # across kernels — empty on agents that host no kernel sim
    events: Dict[str, int] = field(default_factory=dict)
    # cumulative corro.e2e.* stage histograms (merged across label sets)
    stages: Dict[str, LatencyHistogram] = field(default_factory=dict)

    def fresher_than(self, other: Optional["NodeDigest"]) -> bool:
        if other is None:
            return True
        return (self.wall, self.seq) > (other.wall, other.seq)


def write_hist(w: Writer, h: LatencyHistogram) -> None:
    pairs, total = h.to_sparse()
    w.uvarint(len(pairs))
    prev = 0
    for i, (idx, count) in enumerate(pairs):
        w.uvarint(idx if i == 0 else idx - prev)  # gap ≥ 1 after first
        w.uvarint(count)
        prev = idx
    w.f64(total)


def read_hist(r: Reader) -> LatencyHistogram:
    n = r.uvarint()
    pairs: List[Tuple[int, int]] = []
    idx = 0
    for i in range(n):
        gap = r.uvarint()
        idx = gap if i == 0 else idx + gap
        pairs.append((idx, r.uvarint()))
    return LatencyHistogram.from_sparse(pairs, r.f64())


def encode_digest(d: NodeDigest) -> bytes:
    w = Writer()
    w.u8(DIGEST_V1)
    w.raw(d.actor_id)
    w.uvarint(d.seq)
    w.f64(d.wall)
    w.u64(d.view_hash)
    w.uvarint(d.view_size)
    w.uvarint(d.alive)
    w.uvarint(d.suspect)
    w.uvarint(d.downed)
    w.uvarint(d.lhm)
    w.f64(d.loop_lag)
    w.uvarint(len(d.sync_backlog))
    for aid, n in sorted(d.sync_backlog.items()):
        w.raw(aid)
        w.uvarint(n)
    w.uvarint(len(d.events))
    for name, v in sorted(d.events.items()):
        w.string(name)
        w.uvarint(v)
    # stages: only non-empty histograms travel, keyed by name so the
    # stage list can grow without a wire break
    present = [
        (s, h) for s, h in sorted(d.stages.items()) if h.count > 0
    ]
    w.uvarint(len(present))
    for stage, h in present:
        w.string(stage)
        write_hist(w, h)
    w.uvarint(d.heads_total)  # r17 trailing field (default_on_eof)
    # r20 trailing alert block (default_on_eof like heads_total): the
    # severity/state string<->code maps live beside the codec so the
    # wire never carries free-form strings for enum fields
    w.uvarint(len(d.alerts))
    for a in d.alerts:
        w.string(a["rule"])
        w.u8(_SEV_CODE.get(a.get("severity", "warn"), 1))
        w.u8(
            _STATE_CODE.get(a.get("state", "firing"), 2)
            | (0x80 if a.get("drill") else 0)
        )
        w.f64(float(a.get("since") or 0.0))
        w.f64(float(a.get("value") or 0.0))
    # r23 trailing hotspot block (default_on_eof like the two above)
    w.uvarint(len(d.hotspots))
    for h in d.hotspots:
        w.string(h["frame"])
        w.uvarint(int(h.get("samples") or 0))
    return w.bytes()


def decode_digest(data: bytes) -> NodeDigest:
    r = Reader(data)
    ver = r.u8()
    if ver != DIGEST_V1:
        raise ValueError(f"unknown digest version {ver}")
    d = NodeDigest(
        actor_id=r.raw(16),
        seq=r.uvarint(),
        wall=r.f64(),
        view_hash=r.u64(),
        view_size=r.uvarint(),
        alive=r.uvarint(),
        suspect=r.uvarint(),
        downed=r.uvarint(),
        lhm=r.uvarint(),
        loop_lag=r.f64(),
    )
    for _ in range(r.uvarint()):
        aid = r.raw(16)
        d.sync_backlog[aid] = r.uvarint()
    for _ in range(r.uvarint()):
        name = r.string()
        d.events[name] = r.uvarint()
    for _ in range(r.uvarint()):
        stage = r.string()
        d.stages[stage] = read_hist(r)
    d.heads_total = r.uvarint() if not r.eof() else 0
    if not r.eof():
        for _ in range(r.uvarint()):
            rule = r.string()
            sev = r.u8()
            state = r.u8()
            d.alerts.append({
                "rule": rule,
                "severity": _SEV_NAME.get(sev, "warn"),
                "state": _STATE_NAME.get(state & 0x7F, "firing"),
                "drill": bool(state & 0x80),
                "since": r.f64(),
                "value": r.f64(),
            })
    if not r.eof():
        for _ in range(r.uvarint()):
            d.hotspots.append({
                "frame": r.string(),
                "samples": r.uvarint(),
            })
    return d


def merge_stage_hists(
    digests: Iterable[NodeDigest],
) -> Dict[str, LatencyHistogram]:
    """Exact cluster-wide per-stage histograms: aligned-bucket merge of
    each node's cumulative stage histograms (the mergeability that makes
    any-node aggregation exact, runtime/latency.py)."""
    out: Dict[str, LatencyHistogram] = {s: LatencyHistogram() for s in E2E_STAGES}
    for d in digests:
        for stage, h in d.stages.items():
            out.setdefault(stage, LatencyHistogram()).merge(h)
    return out

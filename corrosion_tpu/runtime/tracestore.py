"""Tail-based trace capture: per-trace span buffering, the keep/drop
decision at trace close, and the kept-trace store behind GET /v1/traces.

The r11 trace plane exports EVERY sampled span the moment it finishes —
fine for a debug session pointed at a collector, wrong for production:
the spans worth money are the slow, broken and representative ones, and
head sampling cannot know which a trace will be.  This module adds the
tail discipline (the Prime CCL cost rule, arXiv:2505.14065 — near-zero
overhead on the healthy fast path):

- stage spans (attrs carry ``stage=``) buffer in a bounded per-trace
  ring (`add_span`, O(1) under one lock; the oldest TRACE is evicted
  whole when the buffer is full — never a partial trace);
- a trace is CLOSED when no span has arrived for `idle_close_secs`
  (cross-node traces have no in-band end marker; idleness is the local
  evidence — the Lifeguard discipline of judging the path with evidence
  from the path, arXiv:1707.00788).  Closing happens on the flusher
  THREAD (`sweep`), never on the event loop: exports and eviction are
  off the hot path by construction;
- the keep decision, in precedence order: any span errored; any span
  carried the origin's forced-keep bit (envelope trace meta — the head
  lottery decision every node honors without coordination); any stage
  span exceeded the SLO observatory's per-stage target
  (`runtime/latency.py` supplies the thresholds via config.slo.targets);
  a deterministic 1-in-`lottery_n` lottery on the trace id (the same
  verdict on every node, no wire bytes needed).  Everything else is
  dropped at close with O(1) cost;
- kept traces land in a bounded ring of summaries (slowest-N served by
  GET /v1/traces, exemplar ids for /v1/slo) and their spans are
  forwarded to the OTLP exporter (runtime/otel.py) if one is
  configured.  Traces captured while a chaos injection is active are
  marked with the scenario (the drill-vs-outage discriminator).

Thread contract: `add_span` is called from write-path worker threads
AND the event loop; `sweep` runs on the flusher thread; HTTP handlers
read summaries from the loop.  Every shared structure is mutated under
``self._lock`` and reads return copies.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from corrosion_tpu.runtime.metrics import METRICS


class _TraceBuf:
    """One in-flight trace's buffered spans + rollup flags."""

    __slots__ = ("spans", "last_mono", "error", "forced", "chaos",
                 "spans_dropped")

    def __init__(self, chaos: Optional[str]):
        self.spans: List[dict] = []
        self.last_mono = 0.0
        self.error = False
        self.forced = False
        self.chaos = chaos
        self.spans_dropped = 0


class TraceStore:
    def __init__(
        self,
        targets: Optional[Dict[str, float]] = None,
        lottery_n: int = 64,
        max_traces: int = 512,
        max_spans_per_trace: int = 64,
        keep_max: int = 256,
        idle_close_secs: float = 1.0,
        clock=time.monotonic,
    ):
        self.targets = dict(targets or {})
        self.lottery_n = int(lottery_n)
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.keep_max = int(keep_max)
        self.idle_close_secs = float(idle_close_secs)
        self._clock = clock
        self._lock = threading.Lock()
        self._buf: "OrderedDict[str, _TraceBuf]" = OrderedDict()
        self._kept: List[dict] = []  # bounded summary ring, newest last
        self.kept_total = 0
        self.dropped_total = 0

    # -- head decision (hot path, origin side) ------------------------------

    def head_forced(self, trace_id: str) -> bool:
        """The origin's cached head decision: did this trace win the
        deterministic keep lottery?  Pure arithmetic on the id — the
        same verdict on every node, stamped into the envelope trace
        meta so even differently-configured peers keep the same
        traces."""
        return self._lottery(trace_id)

    def _lottery(self, trace_id: str) -> bool:
        if self.lottery_n <= 0:
            return False
        try:
            return int(trace_id[:8], 16) % self.lottery_n == 0
        except ValueError:
            return False

    # -- producer side (any thread) -----------------------------------------

    def add_span(self, rec: dict) -> None:
        """Buffer one finished stage span; O(1), one lock hold."""
        tid = rec["trace_id"]
        now = self._clock()
        with self._lock:
            buf = self._buf.get(tid)
            if buf is None:
                buf = _TraceBuf(chaos=_active_chaos())
                self._buf[tid] = buf
                if len(self._buf) > self.max_traces:
                    # evict the OLDEST in-flight trace whole: bounded
                    # memory beats a torn newest trace
                    self._buf.popitem(last=False)
                    METRICS.counter("corro.trace.evicted.total").inc()
            buf.last_mono = now
            if rec.get("error"):
                buf.error = True
            if rec.get("forced"):
                buf.forced = True
            if len(buf.spans) < self.max_spans_per_trace:
                buf.spans.append(rec)
            else:
                buf.spans_dropped += 1
            occupancy = len(self._buf)
        METRICS.gauge("corro.trace.buffer.traces").set(occupancy)

    # -- the tail decision (flusher thread) ----------------------------------

    def sweep(self, now: Optional[float] = None) -> int:
        """Close every trace idle past `idle_close_secs`, decide
        keep/drop, export kept spans.  Returns traces closed."""
        now = self._clock() if now is None else now
        with self._lock:
            closed = [
                (tid, self._buf.pop(tid))
                for tid in [
                    t for t, b in self._buf.items()
                    if now - b.last_mono >= self.idle_close_secs
                ]
            ]
            occupancy = len(self._buf)
        METRICS.gauge("corro.trace.buffer.traces").set(occupancy)
        for tid, buf in closed:
            keep, reason = self._decide(tid, buf)
            if not keep:
                with self._lock:
                    self.dropped_total += 1
                METRICS.counter("corro.trace.dropped.total").inc()
                continue
            summary = self._summarize(tid, buf, reason)
            with self._lock:
                self._kept.append(summary)
                if len(self._kept) > self.keep_max:
                    del self._kept[0]
                self.kept_total += 1
            METRICS.counter("corro.trace.kept.total", reason=reason).inc()
            self._export(buf)
        return len(closed)

    def _decide(self, tid: str, buf: _TraceBuf):
        if buf.error:
            return True, "error"
        if buf.forced:
            return True, "forced"
        for rec in buf.spans:
            target = self.targets.get(rec["attrs"].get("stage"))
            if target is not None and _dur_s(rec) > target:
                return True, f"slo:{rec['attrs']['stage']}"
        if self._lottery(tid):
            return True, "lottery"
        return False, "dropped"

    def _summarize(self, tid: str, buf: _TraceBuf, reason: str) -> dict:
        spans = buf.spans
        start = min(r["start_ns"] for r in spans)
        end = max(r["end_ns"] for r in spans)
        stages: Dict[str, dict] = {}
        actors = set()
        tables = set()
        hops = 0
        rows = []
        for r in sorted(spans, key=lambda r: r["start_ns"]):
            a = r["attrs"]
            stage = a.get("stage", "?")
            d = _dur_s(r)
            st = stages.setdefault(
                stage, {"count": 0, "seconds": 0.0, "max_secs": 0.0}
            )
            st["count"] += 1
            st["seconds"] = round(st["seconds"] + d, 6)
            st["max_secs"] = round(max(st["max_secs"], d), 6)
            if "actor" in a:
                actors.add(a["actor"])
            if "table" in a:
                tables.add(a["table"])
            hops = max(hops, int(a.get("hop", 0) or 0))
            rows.append(
                {
                    "name": r["name"],
                    "stage": stage,
                    "actor": a.get("actor"),
                    "start_offset_secs": round((r["start_ns"] - start) / 1e9, 6),
                    "duration_secs": round(d, 6),
                    "error": bool(r.get("error")),
                    "hop": int(a.get("hop", 0) or 0),
                }
            )
        return {
            "trace_id": tid,
            "reason": reason,
            "start_wall": round(start / 1e9, 6),
            "duration_secs": round((end - start) / 1e9, 6),
            "n_spans": len(spans),
            "spans_dropped": buf.spans_dropped,
            "actors": sorted(actors),
            "tables": sorted(tables),
            "hops": hops,
            "chaos": buf.chaos,
            "stages": stages,
            "spans": rows,
        }

    def _export(self, buf: _TraceBuf) -> None:
        from corrosion_tpu.runtime import otel

        if otel.exporter() is None:
            return
        for r in buf.spans:
            otel.record_span(
                r["name"], r["trace_id"], r["span_id"],
                r.get("parent_span_id"), r["start_ns"], r["end_ns"],
                r["attrs"], error=bool(r.get("error")),
            )
        METRICS.counter("corro.trace.exported.total").inc(len(buf.spans))

    # -- query side (loop thread; copies only) --------------------------------

    def kept(
        self,
        n: int = 20,
        stage: Optional[str] = None,
        actor: Optional[str] = None,
        table: Optional[str] = None,
    ) -> List[dict]:
        """Slowest-N kept traces, optionally filtered."""
        with self._lock:
            items = list(self._kept)
        if stage:
            items = [t for t in items if stage in t["stages"]]
        if actor:
            items = [t for t in items if actor in t["actors"]]
        if table:
            items = [t for t in items if table in t["tables"]]
        items.sort(key=lambda t: t["duration_secs"], reverse=True)
        return items[: max(1, n)]

    def slowest_ids(self, stage: str, n: int = 3) -> List[str]:
        """Exemplar trace ids for one stage, slowest-first by that
        stage's worst span (/v1/slo attaches these to stage rows)."""
        with self._lock:
            items = [t for t in self._kept if stage in t["stages"]]
        items.sort(key=lambda t: t["stages"][stage]["max_secs"], reverse=True)
        return [t["trace_id"] for t in items[: max(1, n)]]

    def census(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "buffered": len(self._buf),
                "kept_ring": len(self._kept),
                "kept_total": self.kept_total,
                "dropped_total": self.dropped_total,
                "lottery_n": self.lottery_n,
                "idle_close_secs": self.idle_close_secs,
            }


def _dur_s(rec: dict) -> float:
    return max(0, rec["end_ns"] - rec["start_ns"]) / 1e9


def _active_chaos() -> Optional[str]:
    """Scenario name when a chaos injection is live at capture time —
    the /v1/traces analog of /v1/status's chaos block."""
    try:
        from corrosion_tpu.chaos.faults import CENSUS

        snap = CENSUS.snapshot()
        if snap["active"]:
            return snap["scenario"] or "injection"
    except Exception:  # noqa: BLE001 — census must never fail capture
        pass
    return None


# -- process-global installation (mirrors runtime/otel.py) ------------------

_STORE: Optional[TraceStore] = None
_FLUSHER: Optional["_Flusher"] = None


class _Flusher:
    """Daemon thread sweeping the store — trace close, keep decisions
    and OTLP forwarding all run here, never on the event loop."""

    def __init__(self, store: TraceStore):
        self.store = store
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="trace-sweep", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        period = max(0.05, self.store.idle_close_secs / 2.0)
        while not self._stop.wait(period):
            self.store.sweep()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def configure(
    targets: Optional[Dict[str, float]] = None,
    auto_sweep: bool = True,
    **kw,
) -> Optional[TraceStore]:
    """Install (or, with targets=None and no kwargs, uninstall) the
    global tail sampler.  Agent setup passes config.slo.targets +
    [trace] knobs; tests drive `sweep()` by hand with
    auto_sweep=False."""
    global _STORE, _FLUSHER
    if _FLUSHER is not None:
        _FLUSHER.stop()
        _FLUSHER = None
    if targets is None and not kw:
        _STORE = None
        return None
    _STORE = TraceStore(targets=targets, **kw)
    if auto_sweep:
        _FLUSHER = _Flusher(_STORE)
    return _STORE


def ensure(targets: Optional[Dict[str, float]] = None, **kw) -> TraceStore:
    """Install the global store if absent (idempotent agent-setup hook:
    the FIRST agent's config wins in multi-agent processes — tests that
    need different knobs call configure() explicitly)."""
    global _STORE
    if _STORE is None:
        return configure(targets=targets or {}, **kw)
    return _STORE


def store() -> Optional[TraceStore]:
    return _STORE

"""Host runtime: shutdown tripwire, instrumented channels, config, agent."""

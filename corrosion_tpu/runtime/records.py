"""Measurement-record JSON files with merge-by-key writes.

The pview scale scripts (`scripts/pview_scale.py`, `scripts/pview_1m.py`)
record rungs into the shared PVIEW_SCALE.json; each must replace only the
rungs it re-measured, never clobber another script's records. This is the
single copy of that merge. (`scripts/scale_ladder.py` keeps its own
composite-key last-wins merge over BASELINE_MEASURED.json — a different
contract, deliberately not unified.)
"""

from __future__ import annotations

import fcntl
import json
import os
from typing import List, Sequence


def merge_records(
    path: str, records: Sequence[dict], key: str = "rung"
) -> List[dict]:
    """Replace-by-``key`` merge of ``records`` into the JSON list at
    ``path`` (existing records whose key value is re-measured are
    dropped; everything else is preserved). Returns the merged list.

    Every new record must carry ``key`` — a keyless record would
    otherwise silently match (and delete) unrelated keyless entries in
    the shared artifact, so it raises instead."""
    missing = [r for r in records if key not in r]
    if missing:
        raise KeyError(
            f"record(s) missing merge key {key!r}: {missing[:2]!r}"
        )
    # The read-merge-write below must be atomic across processes: two
    # scripts recording concurrently would otherwise each read the same
    # base list and the second write would drop the first's rungs.  A
    # sidecar .lock file (flock does not survive os.replace of the
    # locked file) serializes the whole cycle.
    lock_path = path + ".lock"
    with open(lock_path, "a") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = []
        mine = {r[key] for r in records}
        merged = [
            r for r in existing if not (key in r and r[key] in mine)
        ] + list(records)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=2)
        os.replace(tmp, path)
    return merged

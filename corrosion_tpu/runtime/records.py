"""Measurement records: merge-by-key JSON files + the flight recorder.

Two record planes live here:

1. `merge_records` — the pview scale scripts (`scripts/pview_scale.py`,
   `scripts/pview_1m.py`) record rungs into the shared PVIEW_SCALE.json;
   each must replace only the rungs it re-measured, never clobber
   another script's records. This is the single copy of that merge.
   (`scripts/scale_ladder.py` keeps its own composite-key last-wins
   merge over BASELINE_MEASURED.json — a different contract,
   deliberately not unified.)

2. `FlightRecorder` (r8) — the host half of the device flight ring
   (`ops/swim.py` ring note): drained `[ring_ticks, N_FLIGHT_LANES]`
   ring snapshots are stitched into a bounded wall-clock-stamped frame
   history, served by `GET /v1/flight` (api/http.py), rendered by
   `scripts/obs_report.py`, and dumped to a black-box incident file on
   tripwire signal-trips / strict invariant violations.  The process
   global `FLIGHT` is the one every sim, kernel wrapper and endpoint
   shares — the flight analog of `runtime.metrics.METRICS`.
"""

from __future__ import annotations

import fcntl
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from corrosion_tpu.runtime.metrics import (
    CRDT_MERGE_EVENTS,
    FLIGHT_CENSUS,
    FLIGHT_LANES,
    KERNEL_EVENTS,
    METRICS,
    Registry,
)


def merge_records(
    path: str, records: Sequence[dict], key: str = "rung"
) -> List[dict]:
    """Replace-by-``key`` merge of ``records`` into the JSON list at
    ``path`` (existing records whose key value is re-measured are
    dropped; everything else is preserved). Returns the merged list.

    Every new record must carry ``key`` — a keyless record would
    otherwise silently match (and delete) unrelated keyless entries in
    the shared artifact, so it raises instead."""
    missing = [r for r in records if key not in r]
    if missing:
        raise KeyError(
            f"record(s) missing merge key {key!r}: {missing[:2]!r}"
        )
    # The read-merge-write below must be atomic across processes: two
    # scripts recording concurrently would otherwise each read the same
    # base list and the second write would drop the first's rungs.  A
    # sidecar .lock file (flock does not survive os.replace of the
    # locked file) serializes the whole cycle.
    lock_path = path + ".lock"
    with open(lock_path, "a") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = []
        mine = {r[key] for r in records}
        merged = [
            r for r in existing if not (key in r and r[key] in mine)
        ] + list(records)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=2)
        os.replace(tmp, path)
    return merged


def cleanup_record_locks(*paths: str) -> None:
    """Remove the flock sidecars (``<path>.lock``) `merge_records`
    leaves behind.  The sidecar is only a cross-process mutex while a
    merge cycle is in flight — it carries no state — but it used to
    strand in the working tree whenever a bench/sim entry point exited
    (normally OR abnormally).  Entry points call this from a
    ``finally`` over the record files they merge; a lock currently
    held by a concurrent merger is safe to unlink (flock follows the
    open file description, not the name)."""
    for p in paths:
        try:
            os.remove(p + ".lock")
        except OSError:
            pass


# ---------------------------------------------------------------------------
# flight recorder (r8): the host timeline plane over the device ring


def frames_from_ring(ring, t: int):
    """Yield (tick, row) for the live rows of a drained device ring.

    Row j of a [R, L] ring holds the frame of the LARGEST tick < t that
    is ≡ j (mod R) — i.e. ticks [max(0, t - R), t) are live, older ones
    were overwritten in place.  Single copy of that arithmetic, shared
    by the recorder, the scripts and the wrap-around tests."""
    r = ring.shape[0] if hasattr(ring, "shape") else len(ring)
    for tick in range(max(0, int(t) - r), int(t)):
        yield tick, ring[tick % r]


def _frame_dict(kernel: str, tick: int, wall: float, row) -> dict:
    """One JSON-ready frame: event-delta lanes + census lanes by name
    (FLIGHT_LANES order — the ring's wire format)."""
    vals = [int(v) for v in row]
    n_ev = len(KERNEL_EVENTS)
    return {
        "kernel": kernel,
        "tick": tick,
        "wall": wall,
        "events": dict(zip(KERNEL_EVENTS, vals[:n_ev])),
        "census": dict(zip(FLIGHT_CENSUS, vals[n_ev:])),
    }


class FlightRecorder:
    """Bounded wall-clock-stamped history of per-tick flight frames.

    Sims drain the device ring beside their stats readback and hand the
    raw snapshot here (`record_ring`); host-side kernels without a scan
    carry (the CRDT merge wrapper) append per-batch frames directly
    (`record_host_frame`).  Thread model: mutated from whatever thread
    steps a simulation while the API event loop serves `window()` —
    every method takes the instance lock (same rule as the metrics
    instruments, runtime/metrics.py).

    Frames are stamped with the DRAIN wall clock: within one drained
    window all frames share a stamp, which is exactly the resolution an
    OTLP span around the drain has (runtime/trace.py) — the two
    timelines line up by construction.
    """

    def __init__(self, capacity: int = 4096):
        self._frames: deque = deque(maxlen=capacity)
        self._host_tick: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._incident_seq = 0

    def record_ring(
        self,
        kernel: str,
        drain,
        since: int = 0,
        registry: Registry = METRICS,
    ) -> int:
        """Stitch the NEW frames of a drained device ring (a
        `swim.FlightDrain`) into the history; returns how many were
        appended.  `since` is the CALLER's cursor — the tick up to which
        it already recorded (each sim owns one; the recorder itself
        keeps none, so independent sims of the same kernel can share the
        process-global plane without clobbering each other's stitching).
        Re-draining without stepping appends nothing; ticks overwritten
        on device before any drain saw them are counted as
        `corro.flight.frames.dropped` (the bounded-ring contract, not an
        error)."""
        ring, t = drain.ring, int(drain.t)
        wall = time.time()
        since = max(0, int(since))
        if t <= since:
            return 0
        r = ring.shape[0] if hasattr(ring, "shape") else len(ring)
        lo = max(since, t - r)
        dropped = lo - since
        added = 0
        with self._lock:
            for tick, row in frames_from_ring(ring, t):
                if tick < lo:
                    continue
                self._frames.append(_frame_dict(kernel, tick, wall, row))
                added += 1
        if added:
            registry.counter(
                "corro.flight.frames.total", kernel=kernel
            ).inc(added)
        if dropped:
            registry.counter(
                "corro.flight.frames.dropped", kernel=kernel
            ).inc(dropped)
        return added

    def record_host_frame(
        self,
        kernel: str,
        events: Dict[str, int],
        registry: Registry = METRICS,
    ) -> None:
        """Append one host-side frame (e.g. a CRDT merge batch: `events`
        keyed by CRDT_MERGE_EVENTS).  `tick` is a per-kernel batch
        counter — host kernels have no protocol period."""
        wall = time.time()
        with self._lock:
            tick = self._host_tick.get(kernel, 0)
            self._host_tick[kernel] = tick + 1
            self._frames.append(
                {
                    "kernel": kernel,
                    "tick": tick,
                    "wall": wall,
                    "events": {k: int(v) for k, v in events.items()},
                    "census": {},
                }
            )
        registry.counter(
            "corro.flight.frames.total", kernel=kernel
        ).inc()

    def window(
        self, k: int, kernel: Optional[str] = None
    ) -> List[dict]:
        """The last `k` frames in record order (optionally one kernel's)."""
        with self._lock:
            frames = list(self._frames)
        if kernel is not None:
            frames = [f for f in frames if f["kernel"] == kernel]
        return frames[-max(0, int(k)):]

    def snapshot_incident(
        self,
        reason: str,
        path: Optional[str] = None,
        registry: Registry = METRICS,
        extra: Optional[dict] = None,
    ) -> Optional[str]:
        """Black-box dump: write the whole frame history to a JSON file
        and return its path (None when there is nothing to dump).

        Callers are abnormal-exit paths (tripwire signal-trips, strict
        invariant violations — NOT graceful shutdown, which also trips
        the tripwire but is not an incident), so this must never raise.
        Files go to $CORRO_FLIGHT_DIR (default: a `corrosion_flight/`
        dir under the system tempdir) and the sequence wraps at 16 per
        process — a bounded black box, like the real instrument.

        `extra` merges caller-supplied JSON-safe keys into the record —
        the alert engine pins the continuous profiler's hot-window
        capture here (r23), so an incident carries WHERE the time went,
        not just what the lanes recorded."""
        with self._lock:
            frames = list(self._frames)
            seq = self._incident_seq
            self._incident_seq += 1
        if not frames:
            return None
        record = {
            "reason": reason,
            "wall": time.time(),
            "pid": os.getpid(),
            "lanes": list(FLIGHT_LANES),
            "crdt_lanes": list(CRDT_MERGE_EVENTS),
            "frames": frames,
        }
        if extra:
            record.update(extra)
        try:
            d = os.environ.get("CORRO_FLIGHT_DIR") or os.path.join(
                tempfile.gettempdir(), "corrosion_flight"
            )
            os.makedirs(d, exist_ok=True)
            if path is None:
                safe = "".join(
                    c if c.isalnum() or c in "-_" else "_" for c in reason
                )[:48]
                path = os.path.join(
                    d,
                    f"flight_incident_{os.getpid()}_{seq % 16:02d}_{safe}.json",
                )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, path)
        except OSError:
            return None
        registry.counter("corro.flight.incidents.total").inc()
        return path


FLIGHT = FlightRecorder()

"""Measurement-record JSON files with merge-by-key writes.

Several scale scripts (`scripts/pview_scale.py`, `scripts/pview_1m.py`,
`scripts/scale_ladder.py`) record rungs into shared JSON artifacts; each
must replace only the rungs it re-measured, never clobber another
script's records. This is the single copy of that merge.
"""

from __future__ import annotations

import json
from typing import List, Sequence


def merge_records(
    path: str, records: Sequence[dict], key: str = "rung"
) -> List[dict]:
    """Replace-by-``key`` merge of ``records`` into the JSON list at
    ``path`` (existing records whose key value is re-measured are
    dropped; everything else is preserved). Returns the merged list."""
    try:
        with open(path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = []
    mine = {r.get(key) for r in records}
    merged = [r for r in existing if r.get(key) not in mine] + list(records)
    with open(path, "w") as f:
        json.dump(merged, f, indent=2)
    return merged

"""Windowed percentile latency plane: log-bucketed histograms, the
write→event hop stamps, and the SLO monitor.

The fixed-bucket `Histogram` in `runtime/metrics.py` answers "how many
requests were slower than 250 ms, ever"; it cannot answer the question
every perf round on the ROADMAP is judged by — "what is p99 write→event
latency RIGHT NOW".  This module supplies the missing substrate
(measure-before-amortize, the PCCL discipline of arXiv:2505.14065):

- `LatencyHistogram` — HDR-style log-bucketed counts (~5 % value
  resolution, 1 µs…1 h span).  Mergeable (aligned bucket arrays add) and
  subtractable (`diff`), so windows, cross-label aggregation and
  per-scenario isolation are all the same cheap arithmetic.
- `WindowedLatency` — a ring of slot sub-histograms + a cumulative one:
  p50/p90/p99/p999 over the last N seconds AND since boot, from one
  `observe()` per sample.  Registered in the metrics `Registry`
  (`Registry.latency`) and exported in the Prometheus text exposition as
  `_bucket`/`_sum`/`_count` plus windowed quantile gauges.
- Hop stamps — `e2e_observe` feeds the five `corro.e2e.*` stage
  histograms of the write→event path (broadcast, apply, match, deliver,
  total).  Cross-node deltas are wall-clock differences between two
  machines: negative values (clock skew) are clamped to 0 and counted
  in `corro.e2e.skew.clamped.total{stage=}` instead of poisoning the
  distribution.
- `SloMonitor` — per-stage SLO targets + error-budget burn; a breach
  sustained for `breach_checks` consecutive checks trips a PR-3
  `FlightRecorder` incident dump so the black box contains the latency
  timeline (each check also appends a `kernel="slo"` host frame with
  the per-stage p99s).

Import rule: this module must NOT import `runtime.metrics` at module
level (metrics imports the histogram classes from here); helpers that
need the process registry resolve it lazily.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# ~5 % resolution: bucket i covers (BASE*RATIO**i, BASE*RATIO**(i+1)].
# 1 µs … >1 h in 456 buckets; everything below BASE lands in bucket 0,
# everything above the span in the last bucket.
BASE = 1e-6
RATIO = 1.05
_LOG_RATIO = math.log(RATIO)
N_BUCKETS = 456  # BASE * RATIO**456 ≈ 4.6e3 s

# the five write→event stages, in path order (each attributable to one
# hop, so a p99 regression names its culprit):
#   broadcast  origin commit → payload handed to the gossip transport
#   apply      origin commit → remote apply committed (network + ingest
#              queue + write tx; labeled by change source)
#   match      apply commit → live-query diff produced the event
#              (includes the matcher's candidate batching window)
#   deliver    event produced → bytes written to the HTTP stream
#   total      origin commit → delivered (only when the origin stamp
#              traveled the whole way)
E2E_STAGES = ("broadcast", "apply", "match", "deliver", "total")

QUANTILES = (0.5, 0.9, 0.99, 0.999)

# default sliding window served by /v1/slo and the quantile gauges
DEFAULT_WINDOW_SECS = 60.0


def bucket_index(v: float) -> int:
    if v <= BASE:
        return 0
    return min(N_BUCKETS - 1, int(math.log(v / BASE) / _LOG_RATIO))


def bucket_upper(i: int) -> float:
    """Inclusive upper edge of bucket i — what quantiles report (within
    one RATIO of the true sample value)."""
    return BASE * RATIO ** (i + 1)


class LatencyHistogram:
    """Log-bucketed counts; NOT thread-safe on its own (WindowedLatency
    owns the lock; standalone users in scripts are single-threaded)."""

    __slots__ = ("counts", "count", "total")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0

    def observe(self, v: float) -> None:
        i = bucket_index(v)
        self.counts[i] = self.counts.get(i, 0) + 1
        self.count += 1
        self.total += v

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        self.count += other.count
        self.total += other.total
        return self

    def copy(self) -> "LatencyHistogram":
        out = LatencyHistogram()
        out.counts = dict(self.counts)
        out.count = self.count
        out.total = self.total
        return out

    def diff(self, earlier: "LatencyHistogram") -> "LatencyHistogram":
        """self − earlier (a later snapshot minus a prior one of the SAME
        instrument): exact per-interval isolation without window-slot
        blur — what the scenario banker uses."""
        out = LatencyHistogram()
        for i, c in self.counts.items():
            d = c - earlier.counts.get(i, 0)
            if d > 0:
                out.counts[i] = d
        out.count = sum(out.counts.values())
        out.total = max(0.0, self.total - earlier.total)
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile q (0..1], reported as the containing
        bucket's upper edge (≤ ~5 % above the true sample)."""
        if self.count <= 0:
            return None
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for i in sorted(self.counts):
            cum += self.counts[i]
            if cum >= rank:
                return bucket_upper(i)
        return bucket_upper(max(self.counts))  # pragma: no cover

    def count_le(self, threshold: float) -> int:
        """Samples ≤ threshold, at bucket resolution (a bucket straddling
        the threshold counts as within — the SLO check errs forgiving by
        at most one 5 % bucket)."""
        ti = bucket_index(threshold)
        return sum(c for i, c in self.counts.items() if i <= ti)

    def nonzero_buckets(self) -> List[Tuple[int, int]]:
        return sorted(self.counts.items())

    def to_sparse(self) -> Tuple[List[Tuple[int, int]], float]:
        """(sorted (bucket, count) pairs, total) — the exact state a
        digest wire codec needs (`runtime/digest.py`): `from_sparse`
        reconstructs an identical histogram, so merge-of-decoded ≡
        decode-of-merged holds bucket-for-bucket."""
        return self.nonzero_buckets(), self.total

    @classmethod
    def from_sparse(
        cls, pairs: Sequence[Tuple[int, int]], total: float
    ) -> "LatencyHistogram":
        out = cls()
        for i, c in pairs:
            if c > 0:
                out.counts[int(i)] = out.counts.get(int(i), 0) + int(c)
        out.count = sum(out.counts.values())
        out.total = float(total)
        return out


class WindowedLatency:
    """A cumulative LatencyHistogram + a ring of time-slot
    sub-histograms giving percentiles over the last N seconds.

    Thread model: observed from write-path worker threads while the API
    event loop reads quantiles — one instance lock, same rule as the
    metrics instruments (runtime/metrics.py)."""

    __slots__ = ("cumulative", "slot_secs", "_slots", "_epochs", "_clock",
                 "_lock")

    def __init__(
        self,
        slot_secs: float = 5.0,
        slots: int = 36,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cumulative = LatencyHistogram()
        self.slot_secs = float(slot_secs)
        self._slots = [LatencyHistogram() for _ in range(slots)]
        self._epochs = [-1] * slots
        self._clock = clock
        self._lock = threading.Lock()

    @property
    def coverage_secs(self) -> float:
        return self.slot_secs * len(self._slots)

    def observe(self, v: float) -> None:
        with self._lock:
            self.cumulative.observe(v)
            e = int(self._clock() // self.slot_secs)
            j = e % len(self._slots)
            if self._epochs[j] != e:
                self._slots[j] = LatencyHistogram()
                self._epochs[j] = e
            self._slots[j].observe(v)

    def window_hist(
        self, window_secs: float = DEFAULT_WINDOW_SECS
    ) -> LatencyHistogram:
        """Merged histogram of the slots inside the last `window_secs`
        (capped at ring coverage; expired slots never contribute)."""
        now = self._clock()
        lo = now - min(window_secs, self.coverage_secs)
        out = LatencyHistogram()
        with self._lock:
            for j, e in enumerate(self._epochs):
                if e < 0:
                    continue
                # slot e covers [e*slot, (e+1)*slot)
                if (e + 1) * self.slot_secs > lo and e * self.slot_secs <= now:
                    out.merge(self._slots[j])
        return out

    def snapshot_cumulative(self) -> LatencyHistogram:
        with self._lock:
            return self.cumulative.copy()

    def quantiles(
        self,
        qs: Sequence[float] = QUANTILES,
        window_secs: float = DEFAULT_WINDOW_SECS,
    ) -> Dict[str, Optional[float]]:
        h = self.window_hist(window_secs)
        out: Dict[str, Optional[float]] = {
            _qname(q): h.quantile(q) for q in qs
        }
        out["count"] = h.count
        return out


def _qname(q: float) -> str:
    return "p" + format(q * 100, "g").replace(".", "")


# -- the write→event hop stamps ---------------------------------------------


@dataclass
class BatchStamp:
    """Rides a committed batch from the change hooks through the matcher
    queue to the event fan-out.

    `origin` is the wall clock at the ORIGIN node's commit (None when no
    stamp traveled — e.g. pre-upgrade peers); `applied` is the wall
    clock at the LOCAL apply/commit that fed the hooks.  When candidate
    batches coalesce in the matcher, the OLDEST stamp wins — a batch's
    latency is its worst element's.

    r19: `traceparent`/`trace_meta` carry the origin's W3C trace
    context + tail-sampling meta alongside the wall stamps, so the
    match and deliver stage spans stitch to the same trace id the
    write opened.  Coalescing keeps the trace of whichever stamp wins
    the oldest-origin contest (the batch is attributed to its worst
    element in spans exactly as it is in histograms)."""

    origin: Optional[float]
    applied: float
    traceparent: Optional[str] = None
    trace_meta: Optional[int] = None

    def oldest(self, other: Optional["BatchStamp"]) -> "BatchStamp":
        if other is None:
            return self
        if self.origin is not None and other.origin is not None:
            older = self if self.origin <= other.origin else other
            origin = older.origin
        elif self.origin is not None:
            older, origin = self, self.origin
        elif other.origin is not None:
            older, origin = other, other.origin
        else:
            older, origin = (self if self.traceparent else other), None
        return BatchStamp(
            origin=origin,
            applied=min(self.applied, other.applied),
            traceparent=older.traceparent,
            trace_meta=older.trace_meta,
        )


def _registry(registry=None):
    if registry is not None:
        return registry
    from corrosion_tpu.runtime.metrics import METRICS

    return METRICS


def e2e_latency(stage: str, registry=None, **labels: str) -> WindowedLatency:
    return _registry(registry).latency(
        f"corro.e2e.{stage}.seconds", **labels
    )


def e2e_observe(
    stage: str, delta: float, registry=None, **labels: str
) -> float:
    """Observe one stage sample; negative deltas (cross-node clock skew)
    clamp to 0 and count, so skew shows up as its own series instead of
    as impossible latencies.  Returns the recorded value."""
    reg = _registry(registry)
    if delta < 0:
        reg.counter("corro.e2e.skew.clamped.total", stage=stage).inc()
        delta = 0.0
    e2e_latency(stage, registry=reg, **labels).observe(delta)
    return delta


def stage_hists(
    window_secs: Optional[float] = None, registry=None
) -> Dict[str, LatencyHistogram]:
    """Per-stage histogram, merged ACROSS label sets (the apply stage is
    labeled by change source) — windowed when `window_secs` is given,
    cumulative otherwise."""
    reg = _registry(registry)
    out: Dict[str, LatencyHistogram] = {}
    for stage in E2E_STAGES:
        merged = LatencyHistogram()
        for _name, _labels, inst in reg.latency_family(
            f"corro.e2e.{stage}.seconds"
        ):
            merged.merge(
                inst.window_hist(window_secs)
                if window_secs is not None
                else inst.snapshot_cumulative()
            )
        out[stage] = merged
    return out


def snapshot_stages(registry=None) -> Dict[str, LatencyHistogram]:
    """Cumulative per-stage snapshot for later `stage_report` diffing."""
    return stage_hists(window_secs=None, registry=registry)


def stage_report(
    before: Optional[Dict[str, LatencyHistogram]] = None,
    window_secs: Optional[float] = None,
    registry=None,
) -> Dict[str, dict]:
    """{stage: {count, p50, p90, p99, p999, mean}} — over the interval
    since `before` (snapshot diff: exact scenario isolation), else over
    the sliding window, else cumulative."""
    now = stage_hists(
        window_secs=None if before is not None else window_secs,
        registry=registry,
    )
    out: Dict[str, dict] = {}
    for stage, h in now.items():
        if before is not None:
            h = h.diff(before.get(stage, LatencyHistogram()))
        row = {_qname(q): h.quantile(q) for q in QUANTILES}
        row["count"] = h.count
        row["mean"] = (h.total / h.count) if h.count else None
        out[stage] = row
    return out


# -- SLO monitor ------------------------------------------------------------


class SloMonitor:
    """Per-stage SLO targets + error-budget burn over the sliding
    window.

    `targets` maps stage → p-`objective` latency target in seconds.  A
    stage's error budget is `1 - objective` (e.g. 1 % of samples may
    exceed the target); burn rate is the observed violating fraction
    over that budget — burn > 1 means the objective is being missed.  A
    burn sustained for `breach_checks` consecutive checks with samples
    present trips ONE FlightRecorder incident dump per breach episode
    (re-armed when the stage recovers), so the black box holds the
    latency timeline that preceded the page."""

    def __init__(
        self,
        targets: Dict[str, float],
        objective: float = 0.99,
        window_secs: float = DEFAULT_WINDOW_SECS,
        breach_checks: int = 3,
        registry=None,
    ):
        self.targets = dict(targets)
        self.objective = objective
        self.window_secs = window_secs
        self.breach_checks = max(1, int(breach_checks))
        self._registry = registry
        self._streak: Dict[str, int] = {}
        self._open: Dict[str, bool] = {}
        self._lock = threading.Lock()

    def check(self, window_secs: Optional[float] = None) -> Dict[str, dict]:
        """Evaluate every stage; returns the per-stage report the
        /v1/slo plane serves (and fires incident dumps as a side
        effect)."""
        from corrosion_tpu.runtime.records import FLIGHT

        reg = _registry(self._registry)
        window = window_secs if window_secs is not None else self.window_secs
        budget = max(1e-9, 1.0 - self.objective)
        hists = stage_hists(window_secs=window, registry=reg)
        cums = stage_hists(window_secs=None, registry=reg)
        report: Dict[str, dict] = {}
        frame: Dict[str, int] = {}
        for stage in E2E_STAGES:
            h = hists[stage]
            row = {_qname(q): h.quantile(q) for q in QUANTILES}
            row["window_count"] = h.count
            c = cums[stage]
            row["cumulative"] = {
                _qname(q): c.quantile(q) for q in QUANTILES
            }
            row["cumulative"]["count"] = c.count
            target = self.targets.get(stage)
            row["target"] = target
            breached = False
            if target is not None and h.count:
                viol = h.count - h.count_le(target)
                burn = (viol / h.count) / budget
                row["burn_rate"] = burn
                breached = burn > 1.0
                reg.gauge("corro.slo.burn.rate", stage=stage).set(burn)
                if breached:
                    reg.counter(
                        "corro.slo.breach.total", stage=stage
                    ).inc()
            else:
                row["burn_rate"] = None
            row["breached"] = breached
            report[stage] = row
            p99 = row.get("p99")
            frame[f"{stage}_p99_us"] = (
                int(p99 * 1e6) if p99 is not None else 0
            )
            frame[f"{stage}_n"] = h.count
        # the latency timeline the black box replays after a breach —
        # recorded BEFORE breach tracking so even a first-check incident
        # dump contains this check's percentiles
        FLIGHT.record_host_frame("slo", frame, registry=reg)
        for stage in E2E_STAGES:
            self._track(stage, report[stage]["breached"], reg, FLIGHT)
        return report

    def _track(self, stage: str, breached: bool, reg, flight) -> None:
        with self._lock:
            if not breached:
                self._streak[stage] = 0
                self._open[stage] = False
                return
            self._streak[stage] = self._streak.get(stage, 0) + 1
            fire = (
                self._streak[stage] >= self.breach_checks
                and not self._open.get(stage, False)
            )
            if fire:
                self._open[stage] = True
        if fire:
            reg.counter("corro.slo.incidents.total", stage=stage).inc()
            flight.snapshot_incident(f"slo_breach_{stage}", registry=reg)

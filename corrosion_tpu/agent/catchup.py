"""Cold-node catch-up: snapshot serve + bootstrap over the sync plane
(r17).

A node that falls far behind — a fresh replica, a long partition, a
restore from old state — used to replay its whole gap change-by-change
through delta sync.  This module adds the fast path on both sides of
the peer protocol's new `SnapshotReq` bi-stream op (`types/codec.py`,
version-gated beside SyncStart):

SERVE: the agent keeps ONE cached compressed snapshot beside its
database (`store/snapshot.py::SnapshotCache`, staleness-bounded by
`[sync] snapshot_max_age_secs`) and streams its frames verbatim to any
requester whose cluster and schema sha match — a burst of cold nodes
amortizes a single VACUUM+compress.  Serves hold their own permit pool
(`Agent.snapshot_serve_sem`), separate from the ≤3 sync serves.

BOOTSTRAP: `maybe_snapshot_bootstrap` runs at the top of every sync
round.  The gap heuristic compares versions we hold against the
freshest peer's digest-advertised `heads_total` (observatory store) —
or, on a cold boot before any digest arrives, against one cheap
state-probe handshake.  Past `[sync] snapshot_min_gap_versions`, the
node fetches the snapshot (chunks decompress to a scratch db as frames
arrive; a schema-sha mismatch aborts after the FIRST frame), quiesces
its write path, swaps the database in through the
`store/restore.py` byte-lock path (`CrdtStore.swapped_database`),
re-pins its own site id, rebuilds the bookie from the
installed bookkeeping, and lets the SAME sync round top up the delta
from the snapshot's watermark.  Every refusal is a counted, safe
fallback to pure delta sync — a peer that can't serve (old version,
busy, schema drift) degrades the transfer, never wedges it (Prime CCL
discipline, arXiv:2505.14065).

Local safety: installing a foreign snapshot DROPS local state, so the
bootstrap refuses unless every version this node ORIGINATED is covered
by the snapshot's watermark (own unsynchronized writes are the one
thing a swap cannot get back; remote-origin overhang is re-fetched by
the top-up).  The guard runs TWICE: once at header time (cheap abort
before the bulk transfer) and again under the write-gate priority
permit right before the swap — own writes can commit during the
multi-second transfer, and only the under-permit check is
race-free.  `corro.snapshot.install.refused.total{reason=
"local_ahead"}` is the witness that the guard fired instead of data
being lost.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import zlib
from typing import List, Optional, Tuple

from corrosion_tpu.agent.handle import Agent
from corrosion_tpu.net.transport import BiStream, TransportError
from corrosion_tpu.runtime.metrics import METRICS
from corrosion_tpu.store import snapshot as snap_mod
from corrosion_tpu.store.bookkeeping import BookedVersions
from corrosion_tpu.store.snapshot import (
    REJECT_BUSY,
    REJECT_CLUSTER,
    REJECT_DISABLED,
    REJECT_SCHEMA,
    SnapshotCache,
    SnapshotDone,
    SnapshotHeader,
    bookie_watermark,
    decode_snapshot_msg,
    encode_snapshot_msg_rejection,
    schema_sha,
)
from corrosion_tpu.sync import held_total, state_held_total
from corrosion_tpu.types.actor import Actor
from corrosion_tpu.types.codec import SnapshotReq, encode_bi_payload_snapshot_req
from corrosion_tpu.types.rangeset import RangeSet

log = logging.getLogger(__name__)

RECV_TIMEOUT = 30.0
SEND_TIMEOUT = 30.0
# decompressed bytes buffered in memory before one worker-thread write
_WRITE_BATCH_BYTES = 4 * 1024 * 1024
# digestless gap probes ride the sync schedule at most this often
_PROBE_MIN_INTERVAL_S = 15.0

_REJECT_NAMES = {
    REJECT_CLUSTER: "cluster",
    REJECT_SCHEMA: "schema",
    REJECT_BUSY: "busy",
    REJECT_DISABLED: "disabled",
}


def local_schema_sha(agent: Agent) -> bytes:
    """This agent's schema generation, runtime-owned canary excluded
    (only opted-in nodes host it; it must not fail the install gate)."""
    return schema_sha(
        agent.store.schema, exclude=(agent.config.slo.canary_table,)
    )


def ensure_snapshot_cache(agent: Agent) -> Optional[SnapshotCache]:
    if agent.store._is_memory:
        return None  # no file to VACUUM INTO / swap
    if agent.snapshots is None:
        agent.snapshots = SnapshotCache(agent.store.path)
    return agent.snapshots


# -- serve side ------------------------------------------------------------


async def serve_snapshot(agent: Agent, stream: BiStream, req: SnapshotReq) -> None:
    """Answer one SnapshotReq on an accepted bi-stream (dispatched from
    the sync serve path)."""

    async def reject(reason: int) -> None:
        METRICS.counter(
            "corro.snapshot.serve.rejected.total",
            reason=_REJECT_NAMES.get(reason, str(reason)),
        ).inc()
        await asyncio.wait_for(
            stream.send(encode_snapshot_msg_rejection(reason)), SEND_TIMEOUT
        )
        await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)

    from corrosion_tpu.runtime.trace import continue_from

    # adopt the cold node's bootstrap trace from the wire (r19): the
    # serve — rejection or full stream — is one span of THAT trace
    with continue_from(
        req.traceparent, "catchup.snapshot.serve",
        peer=str(req.actor_id), actor=str(agent.actor_id),
    ):
        await _serve_snapshot_inner(agent, stream, req, reject)


async def _serve_snapshot_inner(
    agent: Agent, stream: BiStream, req: SnapshotReq, reject
) -> None:
    if req.cluster_id != agent.cluster_id:
        await reject(REJECT_CLUSTER)
        return
    cache = ensure_snapshot_cache(agent)
    if not agent.config.sync.snapshot or cache is None:
        await reject(REJECT_DISABLED)
        return
    local_sha = local_schema_sha(agent)
    if req.schema_sha != local_sha:
        await reject(REJECT_SCHEMA)
        return
    if agent.bulk_refuse_until > time.monotonic():
        # r22 remediation refuse-bulk: this node's store is faulting —
        # a multi-second VACUUM+stream against a sick disk is the last
        # thing to add.  BUSY is the right wire answer: the requester
        # already treats it as "try another peer", a typed degradation
        # instead of a doomed transfer
        await reject(REJECT_BUSY)
        return
    if agent.snapshot_serve_sem.locked():
        await reject(REJECT_BUSY)
        return
    async with agent.snapshot_serve_sem:
        cfg = agent.config.sync
        async with agent.snapshot_build_lock:
            # one builder at a time; within the staleness window this is
            # a no-op for every requester after the first
            await asyncio.to_thread(
                cache.ensure_fresh,
                agent.store.schema,
                agent.store.site_id.bytes16,
                agent.bookie,
                cfg.snapshot_max_age_secs,
                cfg.snapshot_chunk_bytes,
            )
        age = cache.age()
        if age is not None:
            METRICS.gauge("corro.snapshot.age.seconds").set(age)
        sent = 0
        loop = asyncio.get_running_loop()
        gen = snap_mod.iter_snapshot_frames(cache.path)

        def next_batch():
            return next(gen, None)

        while True:
            batch = await loop.run_in_executor(None, next_batch)
            if batch is None:
                break
            for payload in batch:
                await asyncio.wait_for(stream.send(payload), SEND_TIMEOUT)
                sent += len(payload)
        await asyncio.wait_for(stream.finish(), SEND_TIMEOUT)
        METRICS.counter("corro.snapshot.serve.total").inc()
        METRICS.counter("corro.snapshot.serve.bytes").inc(sent)


# -- bootstrap (client) side -----------------------------------------------


# census keys that survive state transitions: `last_probe_mono` gates
# the digestless state probe and `installed_mono` gates the post-install
# cooldown — a failure record must not reset either clock, or a cold
# node pays a probe dial / re-bootstrap every sync round.  `traceparent`
# (r19) is the bootstrap's root trace context: the same sync round's
# delta top-up continues it so a cold-node bootstrap reads as ONE trace
_CENSUS_STICKY = ("last_probe_mono", "installed_mono", "traceparent")


def _set_census(agent: Agent, **fields) -> None:
    new = {
        k: agent.catchup_census[k]
        for k in _CENSUS_STICKY
        if k in agent.catchup_census
    }
    new.update(fields)
    agent.catchup_census = new


def _write_chunks(f, chunks: List[bytes]) -> int:
    n = 0
    for c in chunks:
        f.write(c)
        n += len(c)
    return n


def _local_covered_by(agent: Agent, header: SnapshotHeader) -> bool:
    """Every version this node ORIGINATED must be inside the snapshot's
    watermark — own unsynchronized writes are the one thing a swap
    cannot get back.  Remote-origin versions past the watermark (e.g.
    live-fire broadcasts applied while the transfer ran) are dropped by
    the swap but re-fetched by the immediate delta top-up: the state
    exchange sees the peer's head past our post-install bookie and
    re-pulls, so they cost a bounded re-transfer, never data."""
    own = agent.actor_id.bytes16
    ours = bookie_watermark(agent.bookie).get(own)
    if not ours:
        return True
    theirs = RangeSet(header.watermark.get(own, []))
    return all(theirs.contains_range(s, e) for s, e in ours)


async def _fetch_snapshot(
    agent: Agent, peer: Actor, tmp_db: str
) -> Optional[SnapshotHeader]:
    """Stream the peer's snapshot into `tmp_db` (decompressed).  None on
    any refusal/failure — callers fall back to delta sync."""
    from corrosion_tpu.runtime.trace import current_traceparent, span

    local_sha = local_schema_sha(agent)
    stream = await asyncio.wait_for(
        agent.transport.open_bi(peer.addr), RECV_TIMEOUT
    )
    f = None
    header: Optional[SnapshotHeader] = None
    done: Optional[SnapshotDone] = None
    pending: List[bytes] = []
    pending_bytes = 0
    received_chunks = 0
    received_raw = 0
    fetched_wire = 0
    # the fetch is one child span of the catchup.bootstrap root; its
    # W3C context rides the SnapshotReq (trailing eof-tolerant field)
    # so the SERVING peer's serve span joins the same trace
    fetch_span = span("catchup.snapshot.fetch", peer=peer.addr)
    fetch_span.__enter__()
    try:
        await asyncio.wait_for(
            stream.send(
                encode_bi_payload_snapshot_req(
                    SnapshotReq(
                        actor_id=agent.actor_id,
                        schema_sha=local_sha,
                        cluster_id=agent.cluster_id,
                        traceparent=current_traceparent(),
                    )
                )
            ),
            SEND_TIMEOUT,
        )
        f = await asyncio.to_thread(open, tmp_db, "wb")
        while True:
            frame_ = await asyncio.wait_for(stream.recv(), RECV_TIMEOUT)
            if frame_ is None:
                break
            fetched_wire += len(frame_)
            msg = decode_snapshot_msg(frame_)
            if isinstance(msg, SnapshotHeader):
                header = msg
                # abort BEFORE the bulk transfer when uninstallable
                if msg.schema_sha != local_sha:
                    METRICS.counter(
                        "corro.snapshot.install.refused.total",
                        reason="schema",
                    ).inc()
                    return None
                if not _local_covered_by(agent, msg):
                    METRICS.counter(
                        "corro.snapshot.install.refused.total",
                        reason="local_ahead",
                    ).inc()
                    return None
            elif isinstance(msg, bytes):
                raw = zlib.decompress(msg)
                received_chunks += 1
                received_raw += len(raw)
                pending.append(raw)
                pending_bytes += len(raw)
                if pending_bytes >= _WRITE_BATCH_BYTES:
                    batch, pending, pending_bytes = pending, [], 0
                    await asyncio.to_thread(_write_chunks, f, batch)
            elif isinstance(msg, SnapshotDone):
                done = msg
            elif isinstance(msg, int):  # rejection
                METRICS.counter(
                    "corro.snapshot.bootstrap.rejected.total",
                    reason=_REJECT_NAMES.get(msg, str(msg)),
                ).inc()
                return None
        if pending:
            await asyncio.to_thread(_write_chunks, f, pending)
        await asyncio.to_thread(f.close)
        f = None
        if header is None or done is None:
            return None
        if (
            received_chunks != done.n_chunks
            or received_raw != done.raw_bytes
        ):
            log.warning(
                "torn snapshot transfer from %s: %d/%d chunks %d/%d bytes",
                peer.addr, received_chunks, done.n_chunks,
                received_raw, done.raw_bytes,
            )
            return None
        METRICS.counter("corro.snapshot.fetch.bytes").inc(fetched_wire)
        return header
    finally:
        fetch_span.__exit__(None, None, None)
        if f is not None:
            await asyncio.to_thread(f.close)
        stream.close()


async def snapshot_bootstrap(agent: Agent, peer: Actor) -> bool:
    """Fetch + install one peer's snapshot; True when the database was
    swapped and the bookie rebuilt.  False = safe fallback to delta."""
    store = agent.store
    if store._is_memory:
        return False
    t0 = time.monotonic()
    tmp_db = store.path + ".snap-fetch"
    _set_census(agent, state="fetching", peer=peer.addr, started_mono=t0)
    try:
        try:
            header = await _fetch_snapshot(agent, peer, tmp_db)
        except (
            asyncio.TimeoutError, TransportError, ValueError, OSError,
            zlib.error,
        ):
            METRICS.counter("corro.snapshot.bootstrap.failed.total").inc()
            _set_census(agent, state="failed", peer=peer.addr)
            return False
        if header is None:
            METRICS.counter("corro.snapshot.bootstrap.failed.total").inc()
            _set_census(agent, state="failed", peer=peer.addr)
            return False

        # quiesce the write path for the swap: the PRIORITY lane permit
        # blocks local writers, remote applies and buffered drains alike
        async with agent.write_gate.priority():
            # the header-time _local_covered_by check ran BEFORE the
            # multi-second bulk transfer; own-origin writes committed
            # since (or between fetch completion and permit grant) would
            # be silently dropped by the swap, regressing our version
            # head and re-issuing broadcast version numbers with new
            # contents.  The write path is quiesced under this permit,
            # so rechecking here is authoritative.
            if not _local_covered_by(agent, header):
                METRICS.counter(
                    "corro.snapshot.install.refused.total",
                    reason="local_ahead",
                ).inc()
                METRICS.counter("corro.snapshot.bootstrap.failed.total").inc()
                _set_census(
                    agent, state="failed", peer=peer.addr,
                    reason="local_ahead",
                )
                return False

            def install() -> None:
                with store.swapped_database():
                    snap_mod.install_raw_db(
                        tmp_db, store.path,
                        self_site_id=store.site_id.bytes16,
                        builder_site_id=header.site_id,
                    )

            await asyncio.to_thread(install)

            def rebuild():
                return {
                    aid: store.load_booked_versions(aid)
                    for aid in store.booked_actor_ids()
                }

            # exact replacement, never an insert-merge over the old map:
            # a surviving entry for an actor absent from the snapshot
            # (e.g. broadcast changes applied during the transfer window)
            # would claim versions the swap just dropped, and the delta
            # top-up would never re-fetch them
            loaded = await asyncio.to_thread(rebuild)
            loaded.setdefault(agent.actor_id, BookedVersions(agent.actor_id))
            agent.bookie.replace_all(loaded)
            # the ingest seen-cache predates the swap: anything it
            # remembers may have been dropped with the old database
            agent.ingest_epoch += 1

        # buffered versions the SERVER had completed on disk but not yet
        # drained ride the snapshot — schedule their applies like boot
        for actor_id, booked in agent.bookie.items().items():
            with booked.read() as bv:
                complete = [
                    v for v, p in bv.partials.items() if p.is_complete()
                ]
            for version in complete:
                agent.tx_apply.try_send((actor_id, version))

        elapsed = time.monotonic() - t0
        METRICS.counter("corro.snapshot.install.total").inc()
        METRICS.histogram("corro.snapshot.install.seconds").observe(elapsed)
        _set_census(
            agent,
            state="installed",
            peer=peer.addr,
            seconds=round(elapsed, 3),
            raw_bytes=header.raw_bytes,
            watermark_versions=header.watermark_total(),
            installed_mono=time.monotonic(),
        )
        log.info(
            "snapshot bootstrap from %s: %d watermark versions, %d bytes, "
            "%.2fs — topping up with delta sync",
            peer.addr, header.watermark_total(), header.raw_bytes, elapsed,
        )
        return True
    finally:
        if os.path.exists(tmp_db):
            await asyncio.to_thread(os.unlink, tmp_db)


# -- the gap heuristic -----------------------------------------------------


def _digest_best_peer(
    agent: Agent, peers: List[Actor], held: int
) -> Tuple[Optional[Actor], int, bool]:
    """(freshest peer, its gap over us, any-digest-known).  The third
    element distinguishes "no gap" from "no information" — only the
    latter warrants a state probe."""
    from corrosion_tpu.agent.syncer import _circuit_allows

    obs = agent.observatory
    if obs is None:
        return None, 0, False
    heads = obs.advertised_heads()
    known = any(p.id.bytes16 in heads for p in peers)
    now = time.monotonic()
    best: Tuple[Optional[Actor], int] = (None, 0)
    for peer in peers:
        if not _circuit_allows(agent, peer.id, now):
            continue  # a flapping peer is the wrong bulk-transfer source
        adv = heads.get(peer.id.bytes16)
        if adv is not None and adv - held > best[1]:
            best = (peer, adv - held)
    return best[0], best[1], known


async def maybe_snapshot_bootstrap(agent: Agent, peers: List[Actor]) -> bool:
    """Called at the top of each sync round: decide whether the gap
    warrants the snapshot fast path, and run it.  Never raises — any
    failure is a counted fallback to the round's normal delta sync."""
    cfg = agent.config.sync
    if not cfg.snapshot or not peers or agent.store._is_memory:
        return False
    if agent.bulk_refuse_until > time.monotonic():
        # r22 remediation refuse-bulk: a store-faulting node must not
        # START a bulk transfer either — installing a snapshot through
        # a sick disk fails mid-swap at best; the delta plane keeps the
        # node converging at retail size until the revert clears this
        return False
    # post-install cooldown: one bootstrap per cold start — under live
    # fire the freshly-installed node still trails by however many
    # (small) versions landed during the transfer, and re-installing a
    # barely-newer snapshot would throw that progress away each round;
    # closing the residual gap is the delta plane's job
    installed_mono = agent.catchup_census.get("installed_mono")
    if (
        installed_mono is not None
        and time.monotonic() - installed_mono < cfg.snapshot_cooldown_secs
    ):
        return False
    held = held_total(agent.bookie)
    peer, gap, any_known = _digest_best_peer(agent, peers, held)
    if peer is None and not any_known:
        # no digest from any candidate yet (cold boot window, or
        # observatory off on the peers): one cheap state-probe
        # handshake — rate-limited so a digestless steady-state
        # cluster doesn't pay a probe dial every sync round
        now = time.monotonic()
        last = agent.catchup_census.get("last_probe_mono")
        if last is not None and now - last < _PROBE_MIN_INTERVAL_S:
            return False
        agent.catchup_census["last_probe_mono"] = now
        from corrosion_tpu.agent.syncer import fetch_peer_state

        peer = peers[0]
        theirs = await fetch_peer_state(agent, peer)
        if theirs is None:
            return False
        gap = state_held_total(theirs) - held
    if peer is None or gap < cfg.snapshot_min_gap_versions:
        return False
    from corrosion_tpu.runtime.trace import span

    # r19: the bootstrap's ROOT span — fetch + serve (via the SnapshotReq
    # traceparent) + install hang off it, and the same round's delta
    # top-up continues it from the census so one trace reads end to end
    bootstrap_span = span(
        "catchup.bootstrap", peer=peer.addr, actor=str(agent.actor_id),
        gap=gap,
    )
    bootstrap_span.__enter__()
    agent.catchup_census["traceparent"] = bootstrap_span.ctx.traceparent()
    try:
        return await asyncio.wait_for(
            snapshot_bootstrap(agent, peer), cfg.snapshot_timeout_secs
        )
    except asyncio.TimeoutError:
        METRICS.counter("corro.snapshot.bootstrap.failed.total").inc()
        _set_census(agent, state="failed", peer=peer.addr)
        return False
    except Exception:
        METRICS.counter("corro.snapshot.bootstrap.failed.total").inc()
        _set_census(agent, state="failed", peer=peer.addr)
        log.exception("snapshot bootstrap from %s failed", peer.addr)
        return False
    finally:
        bootstrap_span.__exit__(None, None, None)

"""Agent runtime: membership, broadcast, ingestion, sync, orchestration.

Counterpart of the `klukai-agent` crate. The compute-heavy cluster
simulation lives in `corrosion_tpu.ops.swim` (batched TPU kernel); this
package is the host runtime for *real* agents — event-driven asyncio over
the Transport seam, structured like the reference's tokio task tree but
with channels/tripwire from `corrosion_tpu.runtime`.
"""

"""Periodic agent metrics collection loop.

Counterpart of the reference's metrics loop
(`klukai-agent/src/agent/metrics.rs:18-108`, spawned every 10 s from
`run_root.rs`): per-table row and clock-row counts, per-actor gap and
buffered-version gauges, bookie breadth, membership/cluster gauges, and
sync/write-path saturation gauges. These are what make a perf
investigation diagnosable without code changes (VERDICT r2 #10).
"""

from __future__ import annotations

import asyncio
import logging

from corrosion_tpu.runtime.metrics import METRICS

logger = logging.getLogger(__name__)

COLLECT_INTERVAL_S = 10.0


def collect_once(agent) -> None:
    """One synchronous collection pass (runs on a worker thread)."""
    store = agent.store
    with store.pooled_read() as conn:
        # per-table data + clock-table sizes (metrics.rs:18-60); the
        # "invalid table" signal is clock rows far exceeding data rows
        for tname in list(store.schema.tables):
            try:
                rows = conn.execute(
                    f'SELECT COUNT(*) FROM "{tname}"'
                ).fetchone()[0]
                clock = conn.execute(
                    f'SELECT COUNT(*) FROM "{tname}__crdt_clock"'
                ).fetchone()[0]
            except Exception:
                continue  # table mid-rebuild
            METRICS.gauge("corro.db.table.rows", table=tname).set(rows)
            METRICS.gauge("corro.db.table.clock_rows", table=tname).set(clock)
        # buffered changes + seq bookkeeping backlog (metrics.rs:62-85)
        buffered = conn.execute(
            "SELECT COUNT(*), COUNT(DISTINCT site_id || '-' || db_version)"
            " FROM __corro_buffered_changes"
        ).fetchone()
        METRICS.gauge("corro.db.buffered_changes.rows").set(buffered[0])
        METRICS.gauge("corro.db.buffered_changes.versions").set(buffered[1])
        gaps = conn.execute(
            "SELECT COUNT(*), COALESCE(SUM(end - start + 1), 0)"
            " FROM __corro_bookkeeping_gaps"
        ).fetchone()
        METRICS.gauge("corro.db.gaps.count").set(gaps[0])
        METRICS.gauge("corro.db.gaps.versions").set(gaps[1])
        members = conn.execute(
            "SELECT COUNT(*) FROM __corro_members"
        ).fetchone()[0]
        METRICS.gauge("corro.db.members.persisted").set(members)

    # host-side state gauges (no db access)
    METRICS.gauge("corro.bookie.actors").set(len(agent.bookie.items()))
    METRICS.gauge("corro.members.count").set(len(agent.members.states))
    METRICS.gauge("corro.gossip.cluster_size").set(
        agent.membership.cluster_size
    )
    # effective SWIM config (log-scaled with cluster size — the reference
    # publishes these so operators see the *live* values, agent.rs:29-63)
    cfg = agent.membership.config
    csize = max(1, agent.membership.cluster_size)
    METRICS.gauge("corro.gossip.config.max_transmissions").set(
        cfg.max_transmissions(csize)
    )
    METRICS.gauge("corro.gossip.config.num_indirect_probes").set(
        cfg.num_indirect_probes
    )
    # membership FSM state census (corro.gossip.member.states) — every
    # enum value is written each pass so a count that drops to zero
    # actually reads zero instead of freezing at its last value
    from corrosion_tpu.agent.membership import MemberState

    by_state = {s.name: 0 for s in MemberState}
    # worker thread (metrics_loop's to_thread) vs event-loop mutation:
    # copy under the GIL before iterating
    for m in list(agent.membership.members.values()):
        by_state[m.state.name] = by_state.get(m.state.name, 0) + 1
    for name, count in by_state.items():
        METRICS.gauge("corro.gossip.member.states", state=name).set(count)
    METRICS.gauge("corro.sync.server.permits_available").set(
        getattr(agent.sync_serve_sem, "_value", 0)
    )
    METRICS.gauge("corro.locks.registered").set(
        len(agent.lock_registry.snapshot())
    )


async def metrics_loop(agent) -> None:
    """Spawned from agent run; collects every 10 s until tripwire."""
    while not agent.tripwire.tripped:
        try:
            await asyncio.to_thread(collect_once, agent)
        except Exception:
            logger.exception("metrics collection failed")
        try:
            await asyncio.wait_for(agent.tripwire.wait(), COLLECT_INTERVAL_S)
        except asyncio.TimeoutError:
            pass
